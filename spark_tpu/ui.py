"""Live status server: the reference serves a web UI + REST API while
queries run (reference: core/.../ui/SparkUI.scala:40, the
api/v1 endpoints under status/api/v1/ApiRootResource.scala). Here a
stdlib ThreadingHTTPServer reads the live in-memory metrics ring —
no web framework, no state of its own, always consistent with what the
engine just did.

Endpoints:
  /                     HTML (history.render_html over the live ring)
  /api/v1/queries       per-query rollups (JSON)
  /api/v1/events?n=200  recent raw events (JSON)
  /api/v1/status        app name, event count, active query
  /api/v1/storage       HBM store occupancy, counters, entry listing
  /api/v1/exchange      shuffle stats: rows/bytes/padding per exchange,
                        adaptive (AQE) decisions, exchange.* gauges
  /api/v1/compile       AOT compilation service: executable-store
                        hit/miss/put/evict counters, background
                        compile + hot-swap state, pre-warm report,
                        warmup profile, compile.* gauges
  /api/v1/lint          static plan analysis: recent AnalysisReports,
                        run/error/warning/gated counters, analysis.*
                        gauges
  /api/v1/serve         federation tier: per-replica dispatch/shed/
                        re-dispatch rollup, result-cache hit/miss/
                        single-flight counters, serve.* gauges
  /api/v1/agg           adaptive aggregation: per-strategy pick
                        counts (partial->final / bypass / hash),
                        sketch-vs-decision rollup, agg.* gauges
  /api/v1/mview         materialized views: refresh rollup
                        (incremental/full/fallback), per-view state,
                        stream merge/dedup counters, mview.* gauges
  /api/v1/trace         query-latency rollup from trace roots: p50/p95,
                        a log2 latency histogram, the slowest traces
  /trace/<trace_id>     one trace as Chrome trace-event JSON (same
                        payload the connect server serves — load in
                        ui.perfetto.dev)

Enable per session with ``spark.ui.enabled=true`` (port:
``spark.ui.port``, 0 = ephemeral) or programmatically::

    from spark_tpu.ui import StatusServer
    srv = StatusServer(spark)        # srv.port, srv.url
    ...
    srv.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from spark_tpu import conf as CF
from spark_tpu import history, metrics

UI_ENABLED = CF.register(
    "spark.ui.enabled", False,
    "Serve the live status UI/REST API for this session (reference: "
    "spark.ui.enabled).", bool)

UI_PORT = CF.register(
    "spark.ui.port", 4040,
    "Port for the live status UI; 0 binds an ephemeral port "
    "(reference: spark.ui.port).", int)


def _scheduler_status(session) -> Optional[dict]:
    """Queue depth + per-pool running counts when a query scheduler is
    serving this session (the connect server registers one)."""
    sched = getattr(session, "query_scheduler", None)
    if sched is None:
        return None
    try:
        return sched.status()
    except Exception:
        return None


def _storage_status(session) -> Optional[dict]:
    """HBM-resident store occupancy: storage vs execution bytes under
    the unified budget, hit/miss/evict counters, jit-cache gauges."""
    store = getattr(session, "memory_store", None)
    if store is None:
        return None
    try:
        return {
            "store": store.stats(),
            "memory": session.memory_manager.snapshot(),
            "gauges": metrics.gauges(),
        }
    except Exception:
        return None


def _percentile(sorted_vals, p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _trace_summary(events, top: int = 8) -> dict:
    """Latency rollup over trace ROOT spans (one per trace): p50/p95,
    a log2-bucketed histogram, and the slowest traces with their ids —
    the landing table for 'which query should I open in Perfetto'."""
    spans = [e for e in events if e.get("kind") == "span"]
    ids = {e.get("span_id") for e in spans}
    by_trace: dict = {}
    for e in spans:
        parent = e.get("parent_id")
        if parent is not None and parent in ids:
            continue  # not a local root
        t = e.get("trace_id")
        # a remote parent can leave several local roots in one trace:
        # keep the longest (the outermost local view of the query)
        if t not in by_trace or float(e.get("ms", 0.0)) > \
                float(by_trace[t].get("ms", 0.0)):
            by_trace[t] = e
    lat = sorted(float(e.get("ms", 0.0)) for e in by_trace.values())
    hist = []
    if lat:
        edge = 1.0
        while edge < lat[-1]:
            edge *= 2
        edges, e2 = [], 1.0
        while e2 <= edge:
            edges.append(e2)
            e2 *= 2
        for le in edges:
            hist.append({"le_ms": le,
                         "count": sum(1 for v in lat if v <= le)})
    slowest = sorted(by_trace.values(),
                     key=lambda e: -float(e.get("ms", 0.0)))[:top]
    return {
        "traces": len(by_trace),
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p95_ms": round(_percentile(lat, 0.95), 3),
        "max_ms": round(lat[-1], 3) if lat else 0.0,
        "histogram": hist,
        "slowest": [{"trace_id": e.get("trace_id"),
                     "root": e.get("name"),
                     "ms": round(float(e.get("ms", 0.0)), 3),
                     "t0": e.get("t0")} for e in slowest],
    }


class _Handler(BaseHTTPRequestHandler):
    server_version = "spark-tpu-ui/1"

    def log_message(self, *args) -> None:  # quiet by default
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(200, json.dumps(obj, default=str).encode(),
                   "application/json")

    def do_GET(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        q = parse_qs(url.query)
        events = metrics.recent(int(q.get("n", ["5000"])[0]))
        if url.path in ("/", "/index.html"):
            queries = history.summarize_events(events)
            html = history.render_html(queries)
            sched = _scheduler_status(
                getattr(self.server, "spark_session", None))
            if sched is not None:
                block = (
                    "<h2>Scheduler</h2><pre>"
                    f"mode={sched['mode']} queued={sched['queued']} "
                    f"rejected={sched['rejected']}\n"
                    + "\n".join(
                        f"pool {p['name']}: running={p['running']} "
                        f"queued={p['queued']} weight={p['weight']} "
                        f"device_ms={p['device_ms']}"
                        for p in sched["pools"]) + "</pre>")
                html = html.replace("</body>", block + "</body>") \
                    if "</body>" in html else html + block
            ts = _trace_summary(events)
            if ts["traces"]:
                rows = "".join(
                    f"<tr><td>{t['ms']:.1f}</td>"
                    f"<td>{t['root']}</td>"
                    f"<td><a href='/trace/{t['trace_id']}'>"
                    f"{t['trace_id']}</a></td></tr>"
                    for t in ts["slowest"])
                block = (
                    "<h2>Query latency (trace roots)</h2><pre>"
                    f"traces={ts['traces']} p50={ts['p50_ms']:.1f}ms "
                    f"p95={ts['p95_ms']:.1f}ms "
                    f"max={ts['max_ms']:.1f}ms</pre>"
                    "<table border=1 cellpadding=3><tr><th>ms</th>"
                    "<th>root</th><th>trace (Perfetto JSON)</th></tr>"
                    + rows + "</table>")
                html = html.replace("</body>", block + "</body>") \
                    if "</body>" in html else html + block
            sto = _storage_status(
                getattr(self.server, "spark_session", None))
            if sto is not None:
                st, mem = sto["store"], sto["memory"]
                block = (
                    "<h2>Memory (unified storage/execution)</h2><pre>"
                    f"budget={mem['budget_bytes']} "
                    f"storage={mem['storage_bytes']} "
                    f"execution={mem['in_use_bytes']} "
                    f"free={mem['free_bytes']}\n"
                    f"store: entries={st['entries']} hits={st['hits']} "
                    f"misses={st['misses']} evictions={st['evictions']} "
                    f"rejected_puts={st['rejected_puts']}</pre>")
                html = html.replace("</body>", block + "</body>") \
                    if "</body>" in html else html + block
            self._send(200, html.encode(), "text/html; charset=utf-8")
        elif url.path == "/api/v1/queries":
            self._json(history.summarize_events(events))
        elif url.path == "/api/v1/events":
            self._json(events)
        elif url.path == "/api/v1/status":
            session = getattr(self.server, "spark_session", None)
            active = None
            for ev in reversed(events):
                if ev.get("kind") == "query_start":
                    active = ev.get("description")
                    break
            hb = getattr(session, "heartbeat_monitor", None)
            self._json({
                "app": getattr(session, "app_name", "spark-tpu"),
                "events": len(events),
                "active_query": active,
                "heartbeat": hb.status() if hb is not None else None,
                "scheduler": _scheduler_status(session),
                "storage": _storage_status(session),
            })
        elif url.path == "/api/v1/exchange":
            from spark_tpu import tracing

            self._json({
                "profile": tracing.exchange_profile(events),
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("exchange.")},
            })
        elif url.path == "/api/v1/compile":
            from spark_tpu import tracing

            session = getattr(self.server, "spark_session", None)
            svc = None
            try:
                svc = session.compile_service if session else None
            except Exception:
                pass
            self._json({
                "service": svc.status() if svc is not None else None,
                "exec_store": metrics.exec_store_stats(),
                "warmup": tracing.warmup_profile(events),
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("compile.")},
            })
        elif url.path == "/api/v1/lint":
            from spark_tpu import tracing
            from spark_tpu.analysis import recent_reports

            self._json({
                "profile": tracing.analysis_profile(events),
                "recent": [r.to_dict() for r in recent_reports()],
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("analysis.")},
            })
        elif url.path == "/api/v1/serve":
            from spark_tpu import tracing

            self._json({
                "profile": tracing.serve_profile(events),
                "counters": metrics.serve_stats(),
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("serve.")},
            })
        elif url.path == "/api/v1/agg":
            from spark_tpu import tracing

            self._json({
                "profile": tracing.aggregation_profile(events),
                "counters": metrics.agg_stats(),
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("agg.")},
            })
        elif url.path == "/api/v1/mview":
            from spark_tpu import tracing

            session = getattr(self.server, "spark_session", None)
            mgr = getattr(session, "mview_manager", None)
            self._json({
                "profile": tracing.mview_profile(events),
                "counters": metrics.mview_stats(),
                "views": mgr.views() if mgr is not None else [],
                "gauges": {k: v for k, v in metrics.gauges().items()
                           if k.startswith("mview.")},
            })
        elif url.path == "/api/v1/trace":
            from spark_tpu import tracing

            summary = _trace_summary(events)
            for t in summary["slowest"]:
                t["breakdown"] = tracing.trace_breakdown(t["trace_id"])
            self._json(summary)
        elif url.path.startswith("/trace/"):
            tid = url.path[len("/trace/"):]
            evs = metrics.query_events(tid)
            if not evs:
                self._send(404, b'{"error": "unknown trace id"}',
                           "application/json")
            else:
                self._json(history.chrome_trace(evs))
        elif url.path == "/api/v1/storage":
            session = getattr(self.server, "spark_session", None)
            sto = _storage_status(session)
            if sto is not None:
                store = session.memory_store
                sto["entries"] = store.entries_snapshot()
            self._json(sto)
        else:
            self._send(404, b"not found", "text/plain")


class StatusServer:
    """One live UI per session; serves until stop() (daemon thread)."""

    def __init__(self, session=None, port: Optional[int] = None):
        if port is None:
            try:
                port = session.conf.get(UI_PORT) if session else 0
            except Exception:
                port = 0
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.spark_session = session  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="spark-tpu-ui",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def maybe_start(session) -> Optional[StatusServer]:
    """Start the UI when spark.ui.enabled is set (SparkSession calls
    this at construction)."""
    try:
        if session.conf.get(UI_ENABLED):
            return StatusServer(session)
    except Exception:
        pass
    return None

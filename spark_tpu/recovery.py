"""Fault tolerance: heartbeats, stage retry, durable checkpoints.

Reference peers:
- stage re-execution from lineage on task loss
  (core/.../scheduler/DAGScheduler.scala:1762 handleTaskCompletion →
  resubmit; TaskSetManager maxTaskFailures) — here the *logical plan is
  the lineage*: re-running a query recomputes every stage from source
  data, so recovery = retry the plan, optionally from a durable
  checkpoint that truncates the lineage;
- executor heartbeats (core/.../HeartbeatReceiver.scala:67) — here a
  driver-side monitor thread that proves the device/backend is still
  answering (a dead TPU host fails the next collective anyway — SPMD
  makes failure detection synchronous — the heartbeat exists to catch
  hangs *between* queries and surface them in the event log);
- reliable checkpoint (core/.../rdd/ReliableCheckpointRDD.scala) —
  ``checkpoint_dataframe`` writes Parquet and replans over the files.

Deliberately NOT rebuilt: per-task speculation and partition-level
re-fetch. A pjit stage is a gang — all shards advance or none do —
so the recovery unit is the stage program, not a task.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from spark_tpu import conf as CF
from spark_tpu import metrics

STAGE_MAX_ATTEMPTS = CF.register(
    "spark.stage.maxConsecutiveAttempts", 4,
    "Attempts for a stage/query whose failure looks transient "
    "(reference: config/package.scala STAGE_MAX_CONSECUTIVE_ATTEMPTS).",
    int)

CHECKPOINT_DIR = CF.register(
    "spark.checkpoint.dir", "",
    "Durable checkpoint directory for DataFrame.checkpoint() "
    "(reference: SparkContext.setCheckpointDir).", str)

HEARTBEAT_INTERVAL = CF.register(
    "spark.executor.heartbeatInterval", 10.0,
    "Seconds between device liveness probes (reference: "
    "HeartbeatReceiver.scala HEARTBEAT_INTERVAL).", float)

# Error-message fragments that indicate the *environment* failed (a
# host dropped out of the collective, the tunnel died, a deadline
# passed) rather than the query being wrong. Only these are retried —
# retrying a genuine bug would just quadruple its latency.
_TRANSIENT_MARKERS = (
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "connection reset",
    "Connection reset",
    "socket closed",
    "device or resource busy",
    "halted",          # TPU halt: chip needs re-init
    "slice has failed",
)


def is_transient(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def run_stage_with_recovery(fn: Callable, *, conf=None, label: str = "stage"):
    """Run ``fn`` (a stage/query execution thunk), retrying transient
    environment failures up to spark.stage.maxConsecutiveAttempts times.
    Each retry recomputes from lineage — ``fn`` must replan from the
    logical plan, not replay captured device buffers."""
    attempts = int(conf.get(STAGE_MAX_ATTEMPTS)) if conf is not None \
        else STAGE_MAX_ATTEMPTS.default
    last: Optional[BaseException] = None
    for attempt in range(max(1, attempts)):
        try:
            return fn()
        except Exception as e:
            if not is_transient(e):
                raise
            last = e
            metrics.record("stage_retry", label=label, attempt=attempt,
                           error=repr(e))
            time.sleep(min(2.0 ** attempt * 0.1, 2.0))
    raise RuntimeError(
        f"{label} failed {attempts} consecutive attempts "
        f"(last: {last!r})") from last


class HeartbeatMonitor:
    """Driver-side liveness probe: a daemon thread runs a trivial device
    computation every interval and records the result in the event log.
    ``healthy()`` is False once a probe fails or the loop stops beating
    (hang detection)."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval = float(interval_s if interval_s is not None
                              else HEARTBEAT_INTERVAL.default)
        self._stop = threading.Event()
        self._last_ok: Optional[float] = None
        self._last_error: Optional[str] = None
        self._last_err_ts: float = 0.0
        self._thread: Optional[threading.Thread] = None

    def _probe(self) -> None:
        import jax
        import jax.numpy as jnp

        x = jax.device_put(jnp.ones((8,), jnp.float32))
        got = float(jnp.sum(x).block_until_ready())
        if got != 8.0:
            raise RuntimeError(f"heartbeat probe computed {got} != 8.0")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._probe()
                self._last_ok = time.time()
                metrics.record("heartbeat", ok=True)
            except Exception as e:
                self._last_error = repr(e)
                self._last_err_ts = time.time()
                metrics.record("heartbeat", ok=False, error=repr(e))

    def start(self) -> "HeartbeatMonitor":
        if self._thread is None:
            # one immediate synchronous probe so healthy() is meaningful
            # right away
            try:
                self._probe()
                self._last_ok = time.time()
            except Exception as e:
                self._last_error = repr(e)
                self._last_err_ts = time.time()
            self._thread = threading.Thread(
                target=self._loop, name="spark-tpu-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def healthy(self, max_silence_s: Optional[float] = None) -> bool:
        if self._last_ok is None:
            return False
        if self._last_err_ts > self._last_ok:  # failed since last success
            return False
        silence = max_silence_s if max_silence_s is not None \
            else 3 * self.interval
        return (time.time() - self._last_ok) <= silence

    def status(self) -> dict:
        return {"last_ok": self._last_ok, "last_error": self._last_error,
                "interval_s": self.interval}


_CKPT_COUNTER = [0]


def checkpoint_dataframe(df, eager: bool = True):
    """Durable checkpoint: materialize to Parquet under
    spark.checkpoint.dir and return a DataFrame scanning the files —
    lineage truncated, survives the session (reference:
    ReliableCheckpointRDD; RDD.scala:1627)."""
    session = df.sparkSession
    d = str(session.conf.get(CHECKPOINT_DIR) or "")
    if not d:
        raise RuntimeError(
            "set spark.checkpoint.dir (or SparkContext.setCheckpointDir) "
            "before calling checkpoint(); use localCheckpoint() for the "
            "in-memory variant")
    _CKPT_COUNTER[0] += 1
    path = os.path.join(d, f"ckpt-{os.getpid()}-{_CKPT_COUNTER[0]}")
    df.write.mode("overwrite").parquet(path)
    out = session.read.parquet(path)
    if eager:
        out.count()
    return out

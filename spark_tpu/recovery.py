"""Fault tolerance: heartbeats, stage retry, durable checkpoints.

Reference peers:
- stage re-execution from lineage on task loss
  (core/.../scheduler/DAGScheduler.scala:1762 handleTaskCompletion →
  resubmit; TaskSetManager maxTaskFailures) — here the *logical plan is
  the lineage*: re-running a query recomputes every stage from source
  data, so recovery = retry the plan, optionally from a durable
  checkpoint that truncates the lineage;
- executor heartbeats (core/.../HeartbeatReceiver.scala:67) — here a
  driver-side monitor thread that proves the device/backend is still
  answering (a dead TPU host fails the next collective anyway — SPMD
  makes failure detection synchronous — the heartbeat exists to catch
  hangs *between* queries and surface them in the event log);
- reliable checkpoint (core/.../rdd/ReliableCheckpointRDD.scala) —
  ``checkpoint_dataframe`` writes Parquet and replans over the files.

Deliberately NOT rebuilt: per-task speculation and partition-level
re-fetch. A pjit stage is a gang — all shards advance or none do —
so the recovery unit is the stage program, not a task.
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
from contextlib import contextmanager, nullcontext
from contextvars import ContextVar
from typing import Callable, Dict, Iterator, Optional

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import deadline, faults, metrics, trace

STAGE_MAX_ATTEMPTS = CF.register(
    "spark.stage.maxConsecutiveAttempts", 4,
    "Attempts for a stage/query whose failure looks transient "
    "(reference: config/package.scala STAGE_MAX_CONSECUTIVE_ATTEMPTS).",
    int)

CHECKPOINT_DIR = CF.register(
    "spark.checkpoint.dir", "",
    "Durable checkpoint directory for DataFrame.checkpoint() "
    "(reference: SparkContext.setCheckpointDir).", str)

HEARTBEAT_INTERVAL = CF.register(
    "spark.executor.heartbeatInterval", 10.0,
    "Seconds between device liveness probes (reference: "
    "HeartbeatReceiver.scala HEARTBEAT_INTERVAL).", float)

OOM_DEGRADE_ENABLED = CF.register(
    "spark.tpu.oomDegrade.enabled", True,
    "Whole-batch device OOM replans through the chunked out-of-HBM "
    "tier with a halved spark.tpu.maxDeviceBatchBytes (halving again "
    "on repeat OOM) instead of failing — the graceful-degradation "
    "ladder (reference analogue: TungstenAggregationIterator.scala:82 "
    "sort-fallback under memory pressure).", bool)

OOM_DEGRADE_FLOOR = CF.register(
    "spark.tpu.oomDegrade.floorBytes", 1 << 20,
    "Smallest device-batch budget the OOM degradation ladder will try "
    "before giving up and surfacing the original OOM.", int)

RETRY_BUDGET_ENABLED = CF.register(
    "spark.tpu.recovery.retryBudget.enabled", True,
    "Share ONE per-query retry budget across every retry layer (stage "
    "recovery, scheduler admission, chunk pipeline, spill seams, mview "
    "refresh, dispatch re-forward) instead of letting the per-layer "
    "bounds stack multiplicatively under a fault storm.", bool)

RETRY_BUDGET_ATTEMPTS = CF.register(
    "spark.tpu.recovery.retryBudget.attempts", 8,
    "Total re-attempts one query may spend across ALL retry layers "
    "combined. Per-layer bounds still apply individually; this pool "
    "caps their sum.", int)

RETRY_BUDGET_FLOOR = CF.register(
    "spark.tpu.recovery.retryBudget.layerFloor", 1,
    "Re-attempts each layer is guaranteed even after the shared pool "
    "empties, so one retry-hungry layer cannot starve every other "
    "layer of its single recovery chance.", int)

RETRY_BACKOFF_BASE = CF.register(
    "spark.tpu.recovery.retryBudget.backoffBaseS", 0.05,
    "Base of the full-jitter exponential backoff between budgeted "
    "re-attempts (delay ~ uniform[0, min(cap, base * 2^attempt)]).",
    float)

RETRY_BACKOFF_CAP = CF.register(
    "spark.tpu.recovery.retryBudget.backoffCapS", 2.0,
    "Ceiling of the full-jitter backoff between budgeted re-attempts; "
    "every sleep is additionally capped by the caller's remaining "
    "deadline.", float)

# Error-message fragments that indicate the *environment* failed (a
# host dropped out of the collective, the tunnel died, a deadline
# passed) rather than the query being wrong. Only these are retried —
# retrying a genuine bug would just quadruple its latency.
_TRANSIENT_MARKERS = (
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "connection reset",
    "Connection reset",
    "socket closed",
    "device or resource busy",
    "halted",          # TPU halt: chip needs re-init
    "slice has failed",
)

# exception TYPES that are transient by construction, whatever their
# message says (a "" ConnectionResetError escaped the substring check)
_TRANSIENT_TYPES = (
    ConnectionResetError,
    ConnectionAbortedError,
    BrokenPipeError,
    TimeoutError,
)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def _chain(exc: BaseException) -> Iterator[BaseException]:
    """The exception plus its ``__cause__``/``__context__`` chain (a
    wrapped DEADLINE_EXCEEDED must still classify as transient)."""
    seen = set()
    node: Optional[BaseException] = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        yield node
        if node.__cause__ is not None:
            node = node.__cause__
        elif not node.__suppress_context__:
            node = node.__context__
        else:
            node = None


def is_oom(exc: BaseException) -> bool:
    """Device/host memory exhaustion anywhere in the cause chain. OOM
    is deliberately NOT transient — retrying the identical plan would
    exhaust the identical HBM; it routes to the degradation ladder
    (run_plan_with_oom_degradation) instead."""
    for e in _chain(exc):
        if isinstance(e, (faults.InjectedOOMError, MemoryError)):
            return True
        # jaxlib's XlaRuntimeError prefixes the grpc status code; match
        # by type name so jaxlib need not be importable here
        msg = str(e)
        if any(m in msg for m in _OOM_MARKERS):
            return True
    return False


def is_transient(exc: BaseException) -> bool:
    """True when the failure looks like the *environment* failed and
    re-running the same plan can succeed. Inspects exception types and
    the full ``__cause__`` chain, not just ``str(exc)`` — and OOM
    anywhere in the chain wins: it is never transient."""
    if is_oom(exc):
        return False
    for e in _chain(exc):
        # typed carve-outs BEFORE the marker scan: a caller-deadline
        # expiry says "DEADLINE_EXCEEDED" (a transient marker, because
        # a *server-side* grpc deadline is worth one retry) but the
        # CALLER being gone is terminal; likewise a drained retry
        # budget must not be re-retried by an outer layer — its cause
        # chain carries the original UNAVAILABLE-style error and would
        # otherwise classify transient, resurrecting the exact
        # multiplicative stacking the budget exists to remove.
        if isinstance(e, (deadline.DeadlineExceeded,
                          RetryBudgetExhausted)):
            return False
    for e in _chain(exc):
        if isinstance(e, (faults.InjectedTransientError,
                          faults.InjectedDeadlineError)):
            return True
        if isinstance(e, faults.InjectedFault):
            return False  # injected oom/corrupt: typed non-transient
        if isinstance(e, _TRANSIENT_TYPES):
            return True
        msg = str(e)
        if type(e).__name__ == "XlaRuntimeError":
            # status-code prefix, e.g. "ABORTED: collective timed out"
            status = msg.split(":", 1)[0].strip()
            if status in ("DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED",
                          "CANCELLED", "INTERNAL"):
                return True
        if any(m in msg for m in _TRANSIENT_MARKERS):
            return True
    return False


class RetryBudgetExhausted(RuntimeError):
    """A retry seam asked for a re-attempt after the query's unified
    retry budget drained past the layer floor. Typed and terminal:
    never transient (is_transient carves it out by type), so outer
    layers surface it instead of re-retrying."""

    def __init__(self, layer: str, budget: Optional["RetryBudget"]):
        snap = budget.snapshot() if budget is not None else \
            {"draws": "?", "attempts": "?", "layers": {}}
        super().__init__(
            f"RETRY_BUDGET_EXHAUSTED at {layer}: "
            f"{snap['draws']} re-attempts spent of "
            f"{snap['attempts']} budgeted "
            f"(per-layer: {snap['layers']})")
        self.layer = layer


class RetryBudget:
    """One per-query pool of re-attempts shared by EVERY retry layer.

    Before this existed, resilience was a stack of independent bounded
    retries — ``serve.dispatchRetries`` x ``scheduler.admit`` re-admits
    x ``chunkRetryAttempts`` x ``spillRetryAttempts`` x
    ``mview.refreshRetries`` — whose worst case is the PRODUCT of the
    bounds under a fault storm. Here every layer draws from one pool:
    the per-query total is the SUM bound ``attempts`` (plus each
    layer's small floor guarantee), whatever the nesting.

    ``draw(layer)`` consumes one re-attempt and returns whether it was
    granted; after the pool drains, a layer that has drawn fewer than
    ``layer_floor`` times is still granted (the floor keeps one
    retry-hungry layer from starving every other layer of its single
    recovery chance). Denials surface as
    :class:`RetryBudgetExhausted` at the seam.

    ``backoff_s(attempt)`` is the shared FULL-JITTER exponential
    backoff — delay ~ uniform[0, min(cap, base * 2^attempt)] — capped
    by the caller's remaining deadline, so no budgeted sleep outlives
    the caller.
    """

    def __init__(self, attempts: int, *, layer_floor: int = 1,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.attempts = max(0, int(attempts))
        self.layer_floor = max(0, int(layer_floor))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._rng = rng if rng is not None else random.Random()
        self._remaining = self.attempts
        self._layers: Dict[str, int] = {}
        self._exhausted_noted = False
        self._lock = locks.named_lock("recovery.retry_budget")

    def draw(self, layer: str) -> bool:
        """Consume one re-attempt for ``layer``; True when granted."""
        with self._lock:
            taken = self._layers.get(layer, 0)
            if self._remaining > 0:
                self._remaining -= 1
                self._layers[layer] = taken + 1
                granted, floored = True, False
            elif taken < self.layer_floor:
                self._layers[layer] = taken + 1
                granted, floored = True, True
            else:
                granted, floored = False, False
            remaining = self._remaining
            note_exhausted = (remaining == 0
                              and not self._exhausted_noted)
            if note_exhausted:
                self._exhausted_noted = True
        if granted:
            metrics.note_retry_budget("draws")
            if floored:
                metrics.note_retry_budget("floor_draws")
        else:
            metrics.note_retry_budget("denials")
        if note_exhausted:
            metrics.note_retry_budget("exhaustions")
        metrics.record("retry_draw", layer=layer, granted=granted,
                       floored=floored, remaining=remaining)
        return granted

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter delay for re-attempt ``attempt``, capped by the
        ambient deadline's remaining time."""
        ceiling = min(self.backoff_cap_s,
                      self.backoff_base_s * (2.0 ** max(0, attempt)))
        return deadline.cap_sleep(self._rng.uniform(0.0, ceiling))

    def sleep(self, attempt: int) -> None:
        time.sleep(self.backoff_s(attempt))

    def snapshot(self) -> Dict:
        with self._lock:
            return {"attempts": self.attempts,
                    "remaining": self._remaining,
                    "draws": sum(self._layers.values()),
                    "layers": dict(self._layers),
                    "layer_floor": self.layer_floor}


_BUDGET: ContextVar[Optional[RetryBudget]] = ContextVar(
    "spark_tpu_retry_budget", default=None)


def current_budget() -> Optional[RetryBudget]:
    """The query's ambient RetryBudget (None outside a budgeted query
    or with spark.tpu.recovery.retryBudget.enabled=false)."""
    return _BUDGET.get()


@contextmanager
def bind_budget(budget: Optional[RetryBudget]):
    """Enter a budget for the dynamic extent (None is a no-op).
    Thread-hopping code captures current_budget() and re-binds on the
    worker — same discipline as trace/deadline contexts."""
    if budget is None:
        yield _BUDGET.get()
        return
    token = _BUDGET.set(budget)
    try:
        yield budget
    finally:
        _BUDGET.reset(token)


def budget_from_conf(conf) -> Optional[RetryBudget]:
    """A fresh per-query budget per the conf (None when disabled)."""
    try:
        if not bool(conf.get(RETRY_BUDGET_ENABLED)):
            return None
        return RetryBudget(
            int(conf.get(RETRY_BUDGET_ATTEMPTS)),
            layer_floor=int(conf.get(RETRY_BUDGET_FLOOR)),
            backoff_base_s=float(conf.get(RETRY_BACKOFF_BASE)),
            backoff_cap_s=float(conf.get(RETRY_BACKOFF_CAP)))
    except Exception:
        return None


@contextmanager
def bind_default_budget(conf):
    """Root-entry helper (DataFrame._execute): bind a fresh budget only
    when none is already active — nested executions (mview refresh,
    cache materialization, recovery re-runs) must share the OUTER
    query's pool; that sharing IS the anti-stacking guarantee."""
    if _BUDGET.get() is not None or conf is None:
        yield _BUDGET.get()
        return
    with bind_budget(budget_from_conf(conf)) as b:
        yield b


def retry_allowed(layer: str) -> bool:
    """THE seam API: every bounded-retry loop in the tree asks this
    before each re-attempt (tools/lint_invariants.py rule 7 enforces
    it). Draws from the ambient budget when one is bound; without one
    (budget disabled, or a bare layer used outside any query) the
    legacy per-layer bound stands alone and the re-attempt is counted
    on the ``legacy_attempts`` A/B counter."""
    b = _BUDGET.get()
    if b is None:
        metrics.note_retry_budget("legacy_attempts")
        return True
    return b.draw(layer)


def backoff_sleep(attempt: int, *, base_s: float = 0.05,
                  cap_s: float = 2.0) -> None:
    """Full-jitter, deadline-capped backoff for seams re-attempting
    WITHOUT an ambient budget (the budget's own backoff_s is preferred
    when bound — it shares the jitter RNG and the configured caps)."""
    b = _BUDGET.get()
    if b is not None:
        b.sleep(attempt)
        return
    ceiling = min(float(cap_s), float(base_s) * (2.0 ** max(0, attempt)))
    time.sleep(deadline.cap_sleep(random.uniform(0.0, ceiling)))


def _note_measured_resident(lp) -> None:
    """Seed admission's measured-bytes table keyed by the OPTIMIZED
    plan after a successful resident run (DataFrame._execute keys by
    the RAW plan; the grant pre-step and the hybrid join see the
    optimized plan, so both keys must be populated)."""
    try:
        from spark_tpu.scheduler import admission

        peak = max((int(e.get("bytes", 0))
                    for e in metrics.last_query()
                    if e.get("kind") == "stage_bytes"), default=0)
        admission.note_measured_bytes(lp, peak)
    except Exception:
        pass  # observability must never fail the query


def _grant_planned_chunk(lp, conf):
    """Planned degradation BEFORE execution — the zero-replan path.
    When a MEASURED prior run of this plan shape says its working set
    exceeds what the unified memory manager could currently offer
    (storage pins, shrunken budget), re-plan through the chunked tier
    NOW at the available span instead of letting the device OOM and
    walking the replan ladder. Measured bytes only: static estimates
    are too noisy to pre-chunk on. Returns ``(found, shadow_conf)`` or
    ``(None, None)``."""
    from spark_tpu.physical.chunked import JOIN_HYBRID_ENABLED
    from spark_tpu.scheduler import admission

    try:
        if not bool(conf.get(JOIN_HYBRID_ENABLED)):
            return None, None
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        manager = getattr(sess, "memory_manager", None) \
            if sess is not None else None
        if manager is None:
            return None, None
        measured = admission.measured_plan_bytes(lp)
        if not measured:
            return None, None
        # free-for-execution span; the query's own eventual grant is
        # deliberately not modeled — storage is what it cannot evict
        # past, so that is the planning bound
        with manager.lock:
            avail = manager.budget - manager.storage_bytes()
        if avail <= 0 or int(measured) <= avail:
            return None, None
    except Exception:
        return None, None
    found, shadow = plan_chunk_first(lp, conf, avail)
    if found is None:
        return None, None
    metrics.record("planned_chunked", budget=avail,
                   measured=int(measured))
    return found, shadow


def run_plan_with_oom_degradation(lp, conf, run_fn):
    """Execute an optimized logical plan with the HBM-pressure
    degradation ladder: plans whose scans exceed the device budget run
    chunked as before; a plan whose MEASURED working set exceeds what
    the unified memory manager can currently grant is pre-planned into
    the chunked tier (``planned_chunked`` — zero replans); a
    whole-batch (or chunked) execution that dies with OOM is
    re-planned through ``find_chunkable``/``execute_chunked`` at a
    halved ``spark.tpu.maxDeviceBatchBytes``, halving again on repeat
    down to ``spark.tpu.oomDegrade.floorBytes`` — so memory pressure
    degrades to the out-of-HBM tier instead of failing the query.
    Every ladder replan bumps ``metrics.recovery_stats()['replans']``
    and chains the triggering exception as ``__cause__`` so the final
    error carries the whole replan history. ``run_fn(plan) -> Batch``
    is the raw engine."""
    from spark_tpu.conf import RuntimeConf
    from spark_tpu.physical.chunked import (MAX_DEVICE_BATCH_BYTES,
                                            execute_chunked,
                                            find_chunkable)

    try:
        found = find_chunkable(lp, conf)
        chunk_conf = conf
        if found is None:
            found, shadow = _grant_planned_chunk(lp, conf)
            if found is not None:
                chunk_conf = shadow
        if found is not None:
            return execute_chunked(found, chunk_conf, run_fn)
        # the whole-batch device execution seam
        faults.inject("execute.device", conf)
        out = run_fn(lp)
        _note_measured_resident(lp)
        return out
    except Exception as e:
        if not (conf.get(OOM_DEGRADE_ENABLED) and is_oom(e)):
            raise
        last = e

    # rung 0: adaptive execution. When the OOM hit with
    # spark.tpu.adaptive.enabled off, retry ONCE with it forced on —
    # exchange-heavy plans OOM on the D x cap receive buffers, and
    # measured post-exchange compaction shrinks exactly those while
    # producing byte-identical results. Cheaper than chunking (no
    # re-decode), so it goes first; a contextvar (not the shadow conf)
    # carries the override because run_fn closes over the SESSION conf.
    from spark_tpu.parallel import executor as _mex

    sess = None
    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
    except Exception:
        pass
    adaptive_off = not (_mex.FORCE_ADAPTIVE.get()
                        or bool(conf.get(_mex.CF.ADAPTIVE_ENABLED)))
    if adaptive_off and sess is not None \
            and getattr(sess, "_mesh", None) is not None:
        metrics.note_recovery("replans")
        metrics.record("degraded_to_adaptive", error=repr(last))
        token = _mex.FORCE_ADAPTIVE.set(True)
        try:
            out = run_fn(lp)
            metrics.record("fault_recovered", point="execute.device",
                           how="degraded_to_adaptive")
            return out
        except Exception as e2:
            if not is_oom(e2):
                raise
            if e2.__cause__ is None and e2 is not last:
                e2.__cause__ = last  # replan history rides the chain
            last = e2  # adaptive compaction was not enough: chunk
        finally:
            _mex.FORCE_ADAPTIVE.reset(token)

    budget = int(conf.get(MAX_DEVICE_BATCH_BYTES))
    floor = max(1, int(conf.get(OOM_DEGRADE_FLOOR)))
    # shadow conf: the ladder's shrinking budget must not leak into the
    # session (the next query starts from the configured budget again)
    shadow = RuntimeConf(dict(conf._overrides))
    attempted = False
    while budget // 2 >= floor:
        budget //= 2
        shadow.set(MAX_DEVICE_BATCH_BYTES.key, budget)
        found = find_chunkable(lp, shadow)
        if found is None:
            continue  # still under the halved budget: halve again
        attempted = True
        metrics.note_recovery("replans")
        metrics.record("degraded_to_chunked", budget=budget,
                       error=repr(last))
        try:
            out = execute_chunked(found, shadow, run_fn)
        except Exception as e2:
            if not is_oom(e2):
                raise
            if e2.__cause__ is None and e2 is not last:
                e2.__cause__ = last  # replan history rides the chain
            last = e2  # chunked tier still OOMs: halve again
            continue
        metrics.record("fault_recovered", point="execute.device",
                       how="degraded_to_chunked", budget=budget)
        return out
    metrics.note_recovery("ladder_exhausted")
    if not attempted:
        # no budget made the plan chunkable (e.g. an in-memory relation
        # with no file-backed scan): the ladder has nothing to offer —
        # surface the original typed OOM, not a misleading floor error
        raise last
    raise RuntimeError(
        f"device OOM persisted after degrading the batch budget down "
        f"to the {floor}-byte floor (last: {last!r})") from last


def plan_chunk_first(lp, conf, budget_bytes: int):
    """Plan a forced chunked-tier execution for the background-compile
    path (spark_tpu/compile/service): shrink the device-batch budget on
    a shadow conf so ``find_chunkable`` fires even for plans that fit
    HBM, returning ``(found, shadow_conf)`` ready for
    ``execute_chunked``, or ``(None, None)`` when the plan has no
    chunkable shape. The shadow never leaks into the session conf —
    same idiom as the OOM ladder above."""
    from spark_tpu.conf import RuntimeConf
    from spark_tpu.physical.chunked import (MAX_DEVICE_BATCH_BYTES,
                                            find_chunkable)

    shadow = RuntimeConf(dict(conf._overrides))
    shadow.set(MAX_DEVICE_BATCH_BYTES.key, max(1, int(budget_bytes)))
    found = find_chunkable(lp, shadow)
    if found is None:
        return None, None
    return found, shadow


def run_stage_with_recovery(fn: Callable, *, conf=None, label: str = "stage"):
    """Run ``fn`` (a stage/query execution thunk), retrying transient
    environment failures up to spark.stage.maxConsecutiveAttempts times.
    Each retry recomputes from lineage — ``fn`` must replan from the
    logical plan, not replay captured device buffers. Re-attempts draw
    from the query's unified RetryBudget (retry_allowed) and every
    backoff sleep is capped by the caller's remaining deadline."""
    attempts = int(conf.get(STAGE_MAX_ATTEMPTS)) if conf is not None \
        else STAGE_MAX_ATTEMPTS.default
    last: Optional[BaseException] = None
    for attempt in range(max(1, attempts)):
        try:
            # re-attempts get their own span so a trace waterfall shows
            # time lost to recovery, not just the winning attempt
            rspan = trace.span("fault.retry", point=label,
                               attempt=attempt) if attempt \
                else nullcontext()
            with rspan:
                out = fn()
            if attempt:
                metrics.record("fault_recovered", point=label,
                               how="stage_retry", attempts=attempt)
            return out
        except Exception as e:
            if not is_transient(e):
                raise
            last = e
            metrics.record("stage_retry", label=label, attempt=attempt,
                           error=repr(e))
            if attempt + 1 >= max(1, attempts):
                break
            deadline.check(label)  # the caller may already be gone
            if not retry_allowed(label):
                b = _BUDGET.get()
                raise RetryBudgetExhausted(label, b) from last
            backoff_sleep(attempt, base_s=0.1, cap_s=2.0)
    raise RuntimeError(
        f"{label} failed {attempts} consecutive attempts "
        f"(last: {last!r})") from last


class HeartbeatMonitor:
    """Driver-side liveness probe: a daemon thread runs a trivial device
    computation every interval and records the result in the event log.
    ``healthy()`` is False once a probe fails or the loop stops beating
    (hang detection)."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval = float(interval_s if interval_s is not None
                              else HEARTBEAT_INTERVAL.default)
        self._stop = threading.Event()
        self._last_ok: Optional[float] = None
        self._last_error: Optional[str] = None
        self._last_err_ts: float = 0.0
        self._thread: Optional[threading.Thread] = None

    def _probe(self) -> None:
        import jax
        import jax.numpy as jnp

        x = jax.device_put(jnp.ones((8,), jnp.float32))
        got = float(jnp.sum(x).block_until_ready())
        if got != 8.0:
            raise RuntimeError(f"heartbeat probe computed {got} != 8.0")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._probe()
                self._last_ok = time.time()
                metrics.record("heartbeat", ok=True)
            except Exception as e:
                self._last_error = repr(e)
                self._last_err_ts = time.time()
                metrics.record("heartbeat", ok=False, error=repr(e))

    def start(self) -> "HeartbeatMonitor":
        if self._thread is None:
            # one immediate synchronous probe so healthy() is meaningful
            # right away
            try:
                self._probe()
                self._last_ok = time.time()
            except Exception as e:
                self._last_error = repr(e)
                self._last_err_ts = time.time()
            self._thread = threading.Thread(
                target=self._loop, name="spark-tpu-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def healthy(self, max_silence_s: Optional[float] = None) -> bool:
        if self._last_ok is None:
            return False
        if self._last_err_ts > self._last_ok:  # failed since last success
            return False
        silence = max_silence_s if max_silence_s is not None \
            else 3 * self.interval
        return (time.time() - self._last_ok) <= silence

    def status(self) -> dict:
        return {"last_ok": self._last_ok, "last_error": self._last_error,
                "interval_s": self.interval}


_CKPT_COUNTER = [0]
_CKPT_LOCK = locks.named_lock("recovery.checkpoint")


def checkpoint_dataframe(df, eager: bool = True):
    """Durable checkpoint: materialize to Parquet under
    spark.checkpoint.dir and return a DataFrame scanning the files —
    lineage truncated, survives the session (reference:
    ReliableCheckpointRDD; RDD.scala:1627)."""
    session = df.sparkSession
    d = str(session.conf.get(CHECKPOINT_DIR) or "")
    if not d:
        raise RuntimeError(
            "set spark.checkpoint.dir (or SparkContext.setCheckpointDir) "
            "before calling checkpoint(); use localCheckpoint() for the "
            "in-memory variant")
    with _CKPT_LOCK:
        _CKPT_COUNTER[0] += 1
        seq = _CKPT_COUNTER[0]
    # the uuid component keeps paths unique across sessions in one pid
    # (the bare counter restarts with the module and collided)
    path = os.path.join(
        d, f"ckpt-{os.getpid()}-{seq}-{uuid.uuid4().hex[:8]}")
    df.write.mode("overwrite").parquet(path)
    out = session.read.parquet(path)
    if eager:
        out.count()
    return out

"""Tracing / profiling (SURVEY §5 'Tracing / profiling' row).

Reference mechanisms: per-task TaskMetrics flowing back as accumulators
(core/.../executor/TaskMetrics.scala:46, util/AccumulatorV2.scala:44),
per-operator SQLMetrics rendered in the SQL UI
(metric/SQLMetrics.scala:40, ui/SQLAppStatusListener.scala:40), planner
phase timing (QueryPlanningTracker.scala), and event-log replay.

TPU build: the device-side truth lives in XLA, so deep profiling maps
to the jax profiler (TensorBoard-format traces capturing per-HLO device
time, DMA, and ICI traffic); engine-side accounting reuses the stage
event stream from metrics.py. This module glues the two:

- ``trace(dir)``: context manager capturing a jax profiler trace of
  everything executed inside (view with TensorBoard or xprof).
- ``annotate(name)``: names a region so engine stages are findable
  inside the device trace (TraceAnnotation).
- ``format_trace()`` / ``trace_breakdown()``: the engine-side span
  tree from spark_tpu/trace/ as a text waterfall and as a
  host/queue/device/transfer time split. The two tracing layers
  compose: spans say WHICH query/stage/chunk owned the wall time,
  the jax profiler says what the device did inside it (Perfetto loads
  both — ``history.chrome_trace`` exports the span side).
- ``query_profile()``: the last query's per-operator wall-time rollup
  from the event stream — the text form of the SQL-tab DAG view.
- ``pipeline_profile()``: the out-of-HBM chunk pipeline's per-tier
  stage/overlap rollup (decode/filter/transfer vs device compute).
- ``planning_tracker``: phase timing for parse/optimize/plan (the
  QueryPlanningTracker analogue).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, List, Optional

from spark_tpu import metrics


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a jax profiler trace (TensorBoard format) of the block."""
    import jax

    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Mark a named region inside a device trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def _trace_events(events_or_id=None) -> List[dict]:
    """Resolve a trace-event source: a trace_id string (exact ring
    lookup), an event list, or None (the last query's events)."""
    if isinstance(events_or_id, str):
        return metrics.query_events(events_or_id)
    if events_or_id is not None:
        return list(events_or_id)
    return metrics.last_query()


def format_trace(events_or_id=None, width: int = 40) -> str:
    """Render one query's span tree as a text waterfall: one line per
    span, indented by depth, children in start order, with start offset
    and duration — the terminal form of the Perfetto view
    (``history.chrome_trace`` is the graphical one). Accepts a
    trace_id, an event list, or nothing (last query)."""
    evs = _trace_events(events_or_id)
    spans = [e for e in evs if e.get("kind") == "span"]
    if not spans:
        return "(no span events recorded — tracing off or unsampled)"
    spans.sort(key=lambda e: float(e.get("t0", 0.0)))
    ids = {e.get("span_id") for e in spans}
    children: Dict[Optional[str], List[dict]] = defaultdict(list)
    roots: List[dict] = []
    for e in spans:
        parent = e.get("parent_id")
        # a parent outside the ring (remote peer's span) makes this a
        # local root
        if parent is None or parent not in ids:
            roots.append(e)
        else:
            children[parent].append(e)
    base = float(roots[0].get("t0", 0.0)) if roots else 0.0
    lines = [f"trace {spans[0].get('trace_id', '?')}"]
    attr_skip = ("kind", "name", "ms", "t0", "ts", "tid", "n",
                 "trace_id", "span_id", "parent_id")

    def walk(e: dict, depth: int) -> None:
        off = (float(e.get("t0", 0.0)) - base) * 1e3
        label = ("  " * depth + str(e.get("name", "span")))[:width]
        attrs = " ".join(
            f"{k}={v}" for k, v in e.items() if k not in attr_skip)
        lines.append(f"{label:<{width}} +{off:>8.1f}ms "
                     f"{float(e.get('ms', 0.0)):>9.2f}ms"
                     + (f"  {attrs}" if attrs else ""))
        for c in children.get(e.get("span_id"), []):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


def trace_breakdown(events_or_id=None) -> Dict[str, float]:
    """Split one trace's wall time into where it went: ``wall_ms`` is
    the root span; ``queue_ms`` the scheduler admission wait
    (scheduler.queue spans), ``device_ms`` the block_until_ready-bounded
    device execution (stage.device), ``transfer_ms`` the chunk-pipeline
    host->device staging (pipeline.transfer); ``host_ms`` is the
    remainder (decode, planning, glue, HTTP) — so the four components
    sum to wall by construction. Accepts a trace_id, an event list, or
    nothing (last query)."""
    evs = _trace_events(events_or_id)
    spans = [e for e in evs if e.get("kind") == "span"]
    out = {"wall_ms": 0.0, "queue_ms": 0.0, "device_ms": 0.0,
           "transfer_ms": 0.0, "host_ms": 0.0}
    if not spans:
        return out
    ids = {e.get("span_id") for e in spans}
    roots = [e for e in spans if e.get("parent_id") is None
             or e.get("parent_id") not in ids]
    out["wall_ms"] = round(max(
        (float(e.get("ms", 0.0)) for e in roots), default=0.0), 3)
    sums = {"scheduler.queue": 0.0, "stage.device": 0.0,
            "pipeline.transfer": 0.0}
    for e in spans:
        name = e.get("name")
        if name in sums:
            sums[name] += float(e.get("ms", 0.0))
    out["queue_ms"] = round(sums["scheduler.queue"], 3)
    out["device_ms"] = round(sums["stage.device"], 3)
    out["transfer_ms"] = round(sums["pipeline.transfer"], 3)
    out["host_ms"] = round(max(
        0.0, out["wall_ms"] - out["queue_ms"] - out["device_ms"]
        - out["transfer_ms"]), 3)
    return out


def query_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up the last query's stage events into per-operator totals:
    {op: {count, total_ms, max_ms}} (the SQL-tab table, text form)."""
    evs = events if events is not None else metrics.last_query()
    out: Dict[str, dict] = defaultdict(
        lambda: {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
    for e in evs:
        if e.get("kind") != "stage":
            continue
        op = e.get("op", "?")
        ms = float(e.get("ms", 0.0))
        rec = out[op]
        rec["count"] += 1
        rec["total_ms"] = round(rec["total_ms"] + ms, 3)
        rec["max_ms"] = round(max(rec["max_ms"], ms), 3)
    return dict(out)


def format_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else query_profile()
    if not p:
        return "(no stage events recorded)"
    rows = sorted(p.items(), key=lambda kv: -kv[1]["total_ms"])
    width = max(len(op) for op, _ in rows)
    lines = [f"{'operator':<{width}}  count  total_ms  max_ms"]
    for op, rec in rows:
        lines.append(f"{op:<{width}}  {rec['count']:>5}  "
                     f"{rec['total_ms']:>8.2f}  {rec['max_ms']:>6.2f}")
    return "\n".join(lines)


_PIPELINE_EVENTS = ("chunked_agg", "chunked_topk", "grace_hash_agg",
                    "hybrid_hash_agg")


def pipeline_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up the last query's out-of-HBM pipeline events into a
    per-tier overlap summary: {tier: {chunks, decode_ms, filter_ms,
    transfer_ms, compute_ms, wall_ms, overlap_ms, overlap_ratio,
    stall_producer_ms, stall_consumer_ms, pipeline_depth}}. The
    producer-stage sums (decode+filter+transfer) against wall_ms show
    how much of the host work the pipeline hid behind device compute."""
    evs = events if events is not None else metrics.last_query()
    out: Dict[str, dict] = {}
    for e in evs:
        kind = e.get("kind")
        if kind not in _PIPELINE_EVENTS:
            continue
        rec = out.setdefault(kind, defaultdict(float))
        rec["events"] = int(rec["events"]) + 1
        for k in ("chunks", "decode_ms", "filter_ms", "transfer_ms",
                  "compute_ms", "sidecar_ms", "wall_ms", "overlap_ms",
                  "stall_producer_ms", "stall_consumer_ms"):
            if k in e:
                rec[k] = round(rec[k] + float(e[k]), 3)
        if "pipeline_depth" in e:
            rec["pipeline_depth"] = int(e["pipeline_depth"])
    for rec in out.values():
        wall = rec.get("wall_ms", 0.0)
        rec["overlap_ratio"] = (
            round(rec.get("overlap_ms", 0.0) / wall, 4) if wall else 0.0)
    return {k: dict(v) for k, v in out.items()}


def format_pipeline_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else pipeline_profile()
    if not p:
        return "(no out-of-HBM pipeline events recorded)"
    lines = []
    for tier, rec in sorted(p.items()):
        lines.append(
            f"{tier}: chunks={int(rec.get('chunks', 0))} "
            f"depth={rec.get('pipeline_depth', '?')} "
            f"wall={rec.get('wall_ms', 0.0):.1f}ms "
            f"overlap={rec.get('overlap_ms', 0.0):.1f}ms "
            f"({100 * rec.get('overlap_ratio', 0.0):.0f}%)")
        lines.append(
            f"  decode={rec.get('decode_ms', 0.0):.1f} "
            f"filter={rec.get('filter_ms', 0.0):.1f} "
            f"transfer={rec.get('transfer_ms', 0.0):.1f} "
            f"compute={rec.get('compute_ms', 0.0):.1f} "
            f"stall_prod={rec.get('stall_producer_ms', 0.0):.1f} "
            f"stall_cons={rec.get('stall_consumer_ms', 0.0):.1f}")
    return "\n".join(lines)


def exchange_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up the last query's exchange events (metrics.record_exchange
    + "aqe" decision events) into {"exchanges", "rows_sent",
    "buffer_bytes", "padding_ratio", "by_op": {op: {count, rows,
    buffer_bytes, capacity_before, capacity_after, padding_ratio}},
    "decisions": [...]} — the MapOutputStatistics view of what each
    shuffle actually moved. ``capacity_*`` are PER-DEVICE: with
    adaptive execution on, ``capacity_after`` is the bucket-rounded
    pmax of measured live counts (vs the D x local-capacity worst case
    in ``capacity_before``); in fused mode the two are equal (the stage
    output shape). ``padding_ratio`` = 1 - live rows / total
    post-exchange slots. "aqe" decisions record broadcast-join
    switches and skew splits."""
    evs = events if events is not None else metrics.last_query()
    by_op: Dict[str, dict] = {}
    decisions: List[dict] = []
    total_rows = total_bytes = total_slots = n_exchanges = 0
    for e in evs:
        kind = e.get("kind")
        if kind == "aqe":
            decisions.append({k: v for k, v in e.items()
                              if k not in ("n", "ts", "kind")})
            continue
        if kind != "exchange":
            continue
        n = int(e.get("exchanges", 1))
        rows = int(e.get("rows", 0))
        nbytes = int(e.get("buffer_bytes", 0))
        slots = int(e.get("capacity_after", 0)) * int(e.get("devices", 1))
        n_exchanges += n
        total_rows += rows
        total_bytes += nbytes
        total_slots += slots
        rec = by_op.setdefault(e.get("op", "?"), {
            "count": 0, "rows": 0, "buffer_bytes": 0, "slots": 0,
            "capacity_before": 0, "capacity_after": 0, "mode": None})
        rec["count"] += n
        rec["rows"] += rows
        rec["buffer_bytes"] += nbytes
        rec["slots"] += slots
        rec["capacity_before"] = max(rec["capacity_before"],
                                     int(e.get("capacity_before", 0)))
        rec["capacity_after"] = max(rec["capacity_after"],
                                    int(e.get("capacity_after", 0)))
        rec["mode"] = e.get("mode")
    for rec in by_op.values():
        s = rec.pop("slots")
        rec["padding_ratio"] = round(1.0 - rec["rows"] / s, 4) if s \
            else 0.0
    return {
        "exchanges": n_exchanges,
        "rows_sent": total_rows,
        "buffer_bytes": total_bytes,
        "padding_ratio": (round(1.0 - total_rows / total_slots, 4)
                          if total_slots else 0.0),
        "by_op": by_op,
        "decisions": decisions,
    }


def format_exchange_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else exchange_profile()
    if not p.get("exchanges") and not p.get("decisions"):
        return "(no exchange events recorded)"
    lines = [
        f"exchanges={p['exchanges']} rows_sent={p['rows_sent']} "
        f"ici_buffer_bytes={p['buffer_bytes']} "
        f"padding_ratio={p['padding_ratio']:.2%}"]
    for op, rec in sorted(p.get("by_op", {}).items()):
        lines.append(
            f"  {op} ({rec.get('mode', '?')}): count={rec['count']} "
            f"rows={rec['rows']} cap {rec['capacity_before']}->"
            f"{rec['capacity_after']}/dev "
            f"padding={rec['padding_ratio']:.2%}")
    for d in p.get("decisions", []):
        desc = " ".join(f"{k}={v}" for k, v in d.items()
                        if k != "decision")
        lines.append(f"  aqe: {d.get('decision', '?')} {desc}".rstrip())
    return "\n".join(lines)


_FAULT_EVENTS = ("fault_injected", "fault_recovered",
                 "degraded_to_chunked", "degraded_to_adaptive",
                 "stage_retry", "chunk_retry")


def fault_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up robustness events into {kind: {count, points}} —
    injected faults, the recoveries that absorbed them, and degradation-
    ladder activations (the fault-tolerance counterpart of the SQL-tab
    rollup; reference surfaces these as stage/task failure counts)."""
    evs = events if events is not None else metrics.recent(4096)
    out: Dict[str, dict] = {}
    for e in evs:
        kind = e.get("kind")
        if kind not in _FAULT_EVENTS:
            continue
        rec = out.setdefault(kind, {"count": 0, "points": {}})
        rec["count"] += 1
        point = e.get("point") or e.get("label")
        if point is not None:
            rec["points"][point] = rec["points"].get(point, 0) + 1
    return out


def format_fault_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else fault_profile()
    if not p:
        return "(no fault events recorded)"
    lines = []
    for kind in _FAULT_EVENTS:
        if kind not in p:
            continue
        rec = p[kind]
        pts = " ".join(f"{pt}={n}" for pt, n in sorted(
            rec["points"].items()))
        lines.append(f"{kind}: {rec['count']}" + (f"  ({pts})" if pts
                                                  else ""))
    return "\n".join(lines)


def scheduler_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up multi-tenant scheduler events into per-pool serving
    stats: {pool: {submitted, admitted, finished, failed, cancelled,
    rejected, admit_degraded, queue_wait_ms, queue_wait_max_ms,
    device_ms}} — the query-level analogue of the reference's
    fair-scheduler pool table in the UI."""
    evs = events if events is not None else metrics.recent(4096)
    out: Dict[str, dict] = {}
    for e in evs:
        if e.get("kind") != "scheduler":
            continue
        pool = e.get("pool", "?")
        rec = out.setdefault(pool, {
            "submitted": 0, "admitted": 0, "finished": 0, "failed": 0,
            "cancelled": 0, "rejected": 0, "admit_degraded": 0,
            "queue_wait_ms": 0.0, "queue_wait_max_ms": 0.0,
            "device_ms": 0.0})
        phase = e.get("phase")
        if phase in rec:
            rec[phase] += 1
        if phase in ("finished", "failed", "cancelled"):
            qw = float(e.get("queue_wait_ms", 0.0))
            rec["queue_wait_ms"] = round(rec["queue_wait_ms"] + qw, 3)
            rec["queue_wait_max_ms"] = round(
                max(rec["queue_wait_max_ms"], qw), 3)
            rec["device_ms"] = round(
                rec["device_ms"] + float(e.get("device_ms", 0.0)), 3)
    return out


def format_scheduler_profile(
        profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else scheduler_profile()
    if not p:
        return "(no scheduler events recorded)"
    lines = ["pool        done fail canc rej   queue_wait_ms  device_ms"]
    for pool, rec in sorted(p.items()):
        lines.append(
            f"{pool:<10} {rec['finished']:>5} {rec['failed']:>4} "
            f"{rec['cancelled']:>4} {rec['rejected']:>3} "
            f"{rec['queue_wait_ms']:>14.1f} {rec['device_ms']:>10.1f}")
    return "\n".join(lines)


def storage_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up HBM-resident storage events (spark_tpu/storage/) into
    per-phase totals {hit|miss|put|evict|rejected|uncache: {count,
    bytes}}, plus the live store/occupancy numbers of the active
    session ({'store': MemoryStore.stats(), 'memory':
    UnifiedMemoryManager.snapshot()} — storage vs execution occupancy
    under the shared hbmBudgetBytes)."""
    evs = events if events is not None else metrics.recent(4096)
    out: Dict[str, dict] = {}
    for e in evs:
        if e.get("kind") != "storage":
            continue
        phase = e.get("phase", "?")
        rec = out.setdefault(phase, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += int(e.get("bytes", 0))
    from spark_tpu.api.session import SparkSession

    sess = SparkSession.getActiveSession()
    store = getattr(sess, "memory_store", None) if sess else None
    if store is not None:
        out["store"] = store.stats()
        out["memory"] = sess.memory_manager.snapshot()
    return out


def format_storage_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else storage_profile()
    phases = {k: v for k, v in p.items() if k not in ("store", "memory")}
    if not phases and "store" not in p:
        return "(no storage events recorded)"
    lines = []
    for phase in ("hit", "miss", "put", "evict", "rejected", "uncache"):
        if phase in phases:
            rec = phases[phase]
            lines.append(f"{phase:<9} count={rec['count']:<6} "
                         f"bytes={rec['bytes']}")
    mem = p.get("memory")
    if mem:
        lines.append(
            f"occupancy: storage={mem['storage_bytes']} "
            f"execution={mem['in_use_bytes']} "
            f"free={mem['free_bytes']} / budget={mem['budget_bytes']}")
        gr = mem.get("grants")
        if gr:
            lines.append(
                f"grants: count={gr['grants']} bytes={gr['grant_bytes']} "
                f"waits={gr['grant_waits']} denials={gr['grant_denials']} "
                f"zero={gr['zero_grants']} grows={gr['grows']} "
                f"grow_denials={gr['grow_denials']}")
    st = p.get("store")
    if st:
        lines.append(
            f"store: entries={st['entries']} bytes={st['bytes_used']} "
            f"hits={st['hits']} misses={st['misses']} "
            f"evictions={st['evictions']} rejected={st['rejected_puts']}")
    return "\n".join(lines) if lines else "(no storage events recorded)"


def warmup_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Where did warmup time go? Splits first-run cost into its three
    host-side sinks — XLA trace/compile (stage_compile events, now
    carrying ms), parquet decode, and host->device transfer (scan
    events) — plus the persistent compilation-cache hit/miss counters,
    which say whether 'compile' meant a fresh XLA compile or an AOT
    load from disk."""
    evs = events if events is not None else metrics.recent(4096)
    out = {
        "compile": {"count": 0, "total_ms": 0.0},
        "decode": {"count": 0, "total_ms": 0.0},
        "transfer": {"count": 0, "total_ms": 0.0},
    }
    for e in evs:
        kind = e.get("kind")
        if kind == "stage_compile":
            out["compile"]["count"] += 1
            out["compile"]["total_ms"] = round(
                out["compile"]["total_ms"] + float(e.get("ms", 0.0)), 3)
        elif kind == "scan":
            out["decode"]["count"] += 1
            out["decode"]["total_ms"] = round(
                out["decode"]["total_ms"]
                + float(e.get("decode_ms", 0.0)), 3)
            out["transfer"]["count"] += 1
            out["transfer"]["total_ms"] = round(
                out["transfer"]["total_ms"]
                + float(e.get("transfer_ms", 0.0)), 3)
    out["compile_cache"] = metrics.compile_cache_stats()
    # cross-session executable store + background compile/hot-swap
    # counters (spark_tpu/compile/): hit/miss/background/swap say
    # whether warmup was skipped, hidden, or paid
    out["executable_store"] = metrics.exec_store_stats()
    return out


def format_warmup_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else warmup_profile()
    cc = p.get("compile_cache", {})
    lines = [
        f"trace/compile: {p['compile']['count']} stages, "
        f"{p['compile']['total_ms']:.1f}ms",
        f"parquet decode: {p['decode']['count']} scans, "
        f"{p['decode']['total_ms']:.1f}ms",
        f"host->device transfer: {p['transfer']['total_ms']:.1f}ms",
        f"persistent compile cache: {cc.get('hits', 0)} hits / "
        f"{cc.get('misses', 0)} misses",
    ]
    es = p.get("executable_store")
    if es:
        lines.append(
            f"executable store: {es.get('hits', 0)} hits / "
            f"{es.get('misses', 0)} misses, {es.get('puts', 0)} puts, "
            f"{es.get('background', 0)} background serves, "
            f"{es.get('swaps', 0)} swaps, "
            f"{es.get('fallbacks', 0)} fallbacks, "
            f"{es.get('prewarmed', 0)} prewarmed")
    return "\n".join(lines)


def analysis_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up static-analysis runs (spark_tpu/analysis/): per-plan
    diagnostic counts and analyzer latency from ``analysis`` events,
    plus the lifetime run/error/warning/gated counters."""
    evs = events if events is not None else metrics.recent(4096)
    out: Dict[str, dict] = {"runs": [], "totals": metrics.analysis_stats()}
    for e in evs:
        if e.get("kind") != "analysis":
            continue
        out["runs"].append({
            "plan": e.get("plan"),
            "errors": int(e.get("errors", 0)),
            "warnings": int(e.get("warnings", 0)),
            "diagnostics": int(e.get("diagnostics", 0)),
            "fingerprint_stable": bool(e.get("fingerprint_stable",
                                             True)),
            "elapsed_ms": float(e.get("elapsed_ms", 0.0)),
        })
    return out


def format_analysis_profile(
        profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else analysis_profile()
    t = p.get("totals", {})
    lines = [
        f"analyzer: {t.get('runs', 0)} runs, {t.get('errors', 0)} "
        f"errors, {t.get('warnings', 0)} warnings, "
        f"{t.get('gated', 0)} plans gated"]
    for r in p.get("runs", [])[-8:]:
        flag = "" if r["fingerprint_stable"] else "  [recompile-hazard]"
        lines.append(
            f"  {r['plan']}: {r['errors']}E/{r['warnings']}W "
            f"({r['elapsed_ms']:.1f}ms){flag}")
    return "\n".join(lines)


def serve_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up federation-tier events (spark_tpu/serve/): per-replica
    dispatch outcomes from ``serve`` events ({replica: {dispatched,
    shed, redispatched, failed}}), result-cache activity from
    ``serve_cache`` events ({hit, miss, wait, execute} counts plus
    cached-execution ms saved), and the lifetime counters
    (metrics.serve_stats)."""
    evs = events if events is not None else metrics.recent(4096)
    replicas: Dict[str, dict] = {}
    cache = {"hit": 0, "miss": 0, "wait": 0, "execute": 0,
             "execute_ms": 0.0}
    for e in evs:
        kind = e.get("kind")
        if kind == "serve":
            rid = str(e.get("replica", "?"))
            rec = replicas.setdefault(rid, {
                "dispatched": 0, "shed": 0, "redispatched": 0,
                "failed": 0, "breaker_transitions": 0})
            phase = e.get("phase")
            key = {"dispatch": "dispatched", "shed": "shed",
                   "redispatch": "redispatched",
                   "replica_down": "failed",
                   "breaker_transition": "breaker_transitions",
                   }.get(phase)
            if key is not None:
                rec[key] += 1
            if phase == "breaker_transition":
                # events arrive oldest-first, so the last one seen is
                # the replica's latest known breaker state
                rec["breaker_state"] = str(e.get("to_state", "?"))
        elif kind == "serve_cache":
            phase = e.get("phase")
            if phase in cache:
                cache[phase] += 1
            if phase == "execute":
                cache["execute_ms"] = round(
                    cache["execute_ms"] + float(e.get("ms", 0.0)), 3)
    return {"replicas": replicas, "cache": cache,
            "totals": metrics.serve_stats(),
            "resilience": {"brownout": metrics.brownout_stats(),
                           "retry_budget": metrics.retry_budget_stats()}}


def format_serve_profile(profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else serve_profile()
    t = p.get("totals", {})
    if not p.get("replicas") and not any(p.get("cache", {}).values()) \
            and not any(t.values()):
        return "(no serve events recorded)"
    c = p.get("cache", {})
    lines = [
        f"result cache: {c.get('hit', 0)} hits, {c.get('miss', 0)} "
        f"misses, {c.get('wait', 0)} single-flight waits "
        f"({c.get('execute', 0)} device executions, "
        f"{c.get('execute_ms', 0.0):.1f}ms)",
        f"router: {t.get('dispatches', 0)} dispatches, "
        f"{t.get('sheds', 0)} sheds, {t.get('redispatches', 0)} "
        f"re-dispatches, {t.get('rejected', 0)} rejected "
        f"(all saturated), {t.get('replica_failures', 0)} replica "
        "failures"]
    res = p.get("resilience", {})
    if res:
        bo = res.get("brownout", {})
        rb = res.get("retry_budget", {})
        lines.append(
            f"resilience: brownout level {bo.get('level', 0)} "
            f"({bo.get('entered', 0)} entered/{bo.get('exited', 0)} "
            f"exited), retry budget {rb.get('draws', 0)} draws "
            f"({rb.get('floor_draws', 0)} floored, "
            f"{rb.get('denials', 0)} denied, "
            f"{rb.get('exhaustions', 0)} exhausted)")
    if p.get("replicas"):
        lines.append("replica       disp shed redisp fail breaker")
        for rid, rec in sorted(p["replicas"].items()):
            lines.append(
                f"{rid:<12} {rec['dispatched']:>5} {rec['shed']:>4} "
                f"{rec['redispatched']:>6} {rec['failed']:>4} "
                f"{rec.get('breaker_state', 'closed')}")
    return "\n".join(lines)


def aggregation_profile(events: Optional[List[dict]] = None
                        ) -> Dict[str, dict]:
    """Roll up adaptive-aggregation events (parallel/executor.py):
    per-strategy pick counts from ``agg`` events, how each decision was
    made (auto from the sketch / forced by conf / pinned by legality /
    fallback after a sketch fault), the recent decisions with their
    sketched NDV, live rows and NDV ratio, and the lifetime counters
    (metrics.agg_stats)."""
    evs = events if events is not None else metrics.recent(4096)
    strategies: Dict[str, int] = {}
    modes: Dict[str, int] = {}
    recent: List[dict] = []
    for e in evs:
        if e.get("kind") != "agg":
            continue
        strat = str(e.get("strategy", "?"))
        strategies[strat] = strategies.get(strat, 0) + 1
        mode = str(e.get("mode", "?"))
        modes[mode] = modes.get(mode, 0) + 1
        recent.append({
            "strategy": strat, "mode": mode,
            "ndv": int(e.get("ndv", 0)), "rows": int(e.get("rows", 0)),
            "ratio": round(float(e.get("ratio", 0.0)), 4),
            "domain": int(e.get("domain", 0)),
            "hot_keys": int(e.get("hot_keys", 0) or 0),
            "devices": int(e.get("devices", 0))})
    return {"strategies": strategies, "modes": modes,
            "recent": recent[-16:], "totals": metrics.agg_stats()}


def format_aggregation_profile(
        profile: Optional[Dict[str, dict]] = None) -> str:
    p = profile if profile is not None else aggregation_profile()
    t = p.get("totals", {})
    if not p.get("strategies") and not any(t.values()):
        return "(no adaptive aggregation events recorded)"
    s = p.get("strategies", {})
    m = p.get("modes", {})
    lines = [
        f"strategy picks: {s.get('partial', 0)} partial->final, "
        f"{s.get('bypass', 0)} partial-bypass, "
        f"{s.get('hash', 0)} hash-partial, "
        f"{s.get('sort', 0)} sort-merge, "
        f"{s.get('presplit', 0)} hot-key-presplit",
        f"decisions: {m.get('auto', 0)} auto (sketch), "
        f"{m.get('forced', 0)} conf-forced, "
        f"{m.get('pinned', 0)} legality-pinned, "
        f"{m.get('fallback', 0)} sketch-fault fallbacks "
        f"({t.get('sketch_failures', 0)} lifetime)"]
    if p.get("recent"):
        lines.append(
            "strategy  mode      ndv~      rows  ratio domain hot")
        for r in p["recent"][-8:]:
            lines.append(
                f"{r['strategy']:<9} {r['mode']:<8} {r['ndv']:>6} "
                f"{r['rows']:>9} {r['ratio']:>6.2f} {r['domain']:>6} "
                f"{r.get('hot_keys', 0):>3}")
    return "\n".join(lines)


def mview_profile(events: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Roll up materialized-view events (spark_tpu/mview/): refresh
    outcomes by how (incremental / full / fallback), retry + dedup
    activity, per-view stream-merge counts, and the lifetime counters
    (metrics.mview_stats)."""
    evs = events if events is not None else metrics.recent(4096)
    refresh = {"incremental": 0, "full": 0, "fallback": 0,
               "materialize": 0, "files_merged": 0}
    faults = {"retries": 0, "fallbacks": 0}
    streams: Dict[str, dict] = {}
    for e in evs:
        if e.get("kind") != "mview":
            continue
        phase = e.get("phase")
        if phase == "refresh":
            how = str(e.get("how", "full"))
            if how in refresh:
                refresh[how] += 1
            if how == "incremental":
                refresh["files_merged"] += int(e.get("files", 0))
        elif phase == "materialize":
            refresh["materialize"] += 1
        elif phase == "retry":
            faults["retries"] += 1
        elif phase == "fallback":
            faults["fallbacks"] += 1
        elif phase in ("stream_merge", "dedup"):
            name = str(e.get("view", "?"))
            rec = streams.setdefault(name, {"merges": 0, "dedups": 0,
                                            "rows": 0})
            if phase == "stream_merge":
                rec["merges"] += 1
                rec["rows"] += int(e.get("rows", 0))
            else:
                rec["dedups"] += 1
    return {"refresh": refresh, "faults": faults, "streams": streams,
            "totals": metrics.mview_stats()}


def format_mview_profile(profile: Optional[Dict[str, dict]] = None
                         ) -> str:
    p = profile if profile is not None else mview_profile()
    t = p.get("totals", {})
    r = p.get("refresh", {})
    if not any(r.values()) and not any(t.values()):
        return "(no materialized-view events recorded)"
    lines = [
        f"views: {t.get('registrations', 0)} registered, "
        f"{t.get('hits', 0)} fresh hits",
        f"refresh: {r.get('incremental', 0)} incremental "
        f"({r.get('files_merged', 0)} files merged), "
        f"{r.get('full', 0)} full recomputes, "
        f"{r.get('fallback', 0)} retry-exhaustion fallbacks, "
        f"{t.get('refresh_retries', 0)} transient retries",
        f"streaming: {t.get('stream_merges', 0)} micro-batch merges, "
        f"{t.get('stream_dedups', 0)} replay dedups; "
        f"{t.get('serve_repopulations', 0)} serve-cache repopulations"]
    if p.get("streams"):
        lines.append("stream view     merges dedups   rows")
        for name, rec in sorted(p["streams"].items()):
            lines.append(f"{name:<14} {rec['merges']:>6} "
                         f"{rec['dedups']:>6} {rec['rows']:>6}")
    return "\n".join(lines)


class PlanningTracker:
    """Phase timing for the planning pipeline (reference:
    catalyst/QueryPlanningTracker.scala). Use as
    ``with tracker.phase("optimize"): ...``; phases() returns ms."""

    def __init__(self):
        self._phases: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._phases[name] = self._phases.get(name, 0.0) + \
                (time.perf_counter() - t0) * 1e3

    def phases(self) -> Dict[str, float]:
        return {k: round(v, 3) for k, v in self._phases.items()}

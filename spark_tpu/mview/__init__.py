"""Incrementally-maintained materialized views.

``df.cache()`` on an aggregate over a fingerprinted file source — with
``spark.tpu.mview.enabled`` — becomes a materialized VIEW: the cached
HBM batch is refreshed when the source files change (today's plain
cache serves stale bytes forever), and when only new files were
APPENDED and the aggregate is exactly re-mergeable (integer Sum,
non-float Min/Max — analysis/legality.remerge_verdict, the same rule
the AQE skew fan trusts), the refresh executes the aggregate over the
delta files only and re-merges the partials into the cached batch.
Everything else falls back to a transparent full recompute; both paths
are byte-identical under the on/off conf sweep.

Streaming converges here too: each micro-batch commit publishes a
delta event (streaming/execution.py) that stream-registered views
merge, deduplicated by the WAL's batch id so replay after a crash
never double-merges.

See mview/view.py for the maintainability verdict (surfaced as
PLAN-MVIEW-* diagnostics via ``df.explain(mode="lint")``) and
mview/manager.py for the refresh/merge engine.
"""

from spark_tpu.mview.manager import ViewManager
from spark_tpu.mview.view import MaterializedView, inspect_plan

__all__ = ["ViewManager", "MaterializedView", "inspect_plan"]

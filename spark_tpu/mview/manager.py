"""The materialized-view refresh/merge engine.

``ViewManager`` owns every registered view and sits behind the plan
cache: ``CacheManager._materialize`` delegates here for keys that are
registered views, so a view read is exactly a cache read PLUS a
freshness check against the shared scan fingerprint
(io/fingerprint.py). A stale file view refreshes in place — the
``MemoryStore.update`` path keeps the entry's key/LRU identity and
re-accounts only the byte delta — and when the delta is pure appends
and the aggregate is exactly re-mergeable
(analysis/legality.remerge_verdict), the refresh executes the
aggregate over the APPENDED FILES ONLY and re-merges the partials
into the cached batch. Everything else pays a transparent full
recompute; both paths produce byte-identical results (the dictionary
normalization in columnar/arrow.from_arrow makes the aggregate output
a pure function of the input row multiset).

Stream views subscribe to micro-batch delta events published by
streaming/execution.py BEFORE the WAL commit, deduplicated here by
batch id: a crash between merge and commit replays the same batch id,
which the ``batch_id <= last_batch_id`` watermark drops — replay
never double-merges.

Incremental refreshes pass through the ``mview.refresh`` fault point
with bounded transient retries (spark.tpu.mview.refreshRetries); on
exhaustion a file view falls back to a full recompute (files can be
re-scanned) while a stream view re-raises so the WAL redelivers the
delta (streams cannot).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

import pyarrow as pa

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import faults, metrics, recovery
from spark_tpu.io.fingerprint import classify_delta, source_fingerprint
from spark_tpu.mview.view import MaterializedView, inspect_plan
from spark_tpu.plan import logical as L


def _stream_key(name: str):
    return ("mview-stream", name)


class ViewManager:
    """Registry + refresh engine for one session's materialized views.

    Thread-safe: the registry mutates under ``_lock``; each view
    refreshes under its own ``view.lock`` (file views additionally
    single-flight under the CacheManager's per-entry lock, which the
    delegate call passes in)."""

    def __init__(self, session):
        self._session = session
        self._views: Dict[Any, MaterializedView] = {}
        self._by_stream: Dict[str, List[MaterializedView]] = {}
        self._lock = locks.named_lock("mview.manager")

    # -- conf ---------------------------------------------------------------

    @property
    def _conf(self):
        return self._session.conf

    def enabled(self) -> bool:
        try:
            return bool(self._conf.get(CF.MVIEW_ENABLED))
        except Exception:
            return False

    def _incremental_on(self) -> bool:
        try:
            return bool(self._conf.get(CF.MVIEW_INCREMENTAL))
        except Exception:
            return True

    # -- registration -------------------------------------------------------

    def maybe_register(self, plan: L.LogicalPlan
                       ) -> Optional[MaterializedView]:
        """Promote a ``df.cache()`` registration to a file view when
        the subsystem is enabled and the plan qualifies (root Aggregate
        over one fingerprinted file scan). Never raises — a plan that
        cannot be a view simply stays a plain cache entry."""
        if not self.enabled():
            return None
        try:
            insp = inspect_plan(plan)
        except Exception as exc:  # defensive: cache() must never break
            metrics.record("mview", phase="inspect_error",
                           error=type(exc).__name__)
            return None
        if not insp.registrable or insp.kind != "file":
            return None
        key = plan.structural_key()
        with self._lock:
            view = self._views.get(key)
            if view is None:
                view = MaterializedView(key=key, plan=plan,
                                        inspection=insp)
                self._views[key] = view
                metrics.note_mview("registrations")
                metrics.record("mview", phase="register",
                               view_kind="file",
                               incremental=insp.incremental)
                metrics.set_gauge("mview.views", len(self._views))
        return view

    def register_stream_view(self, name: str, plan: L.LogicalPlan,
                             stream: str) -> MaterializedView:
        """Register an explicitly named view over a streaming
        aggregate: ``plan`` must be a root Aggregate over exactly the
        one StreamingSource of the query named ``stream``, and must be
        incrementally maintainable — streams cannot be re-scanned, so
        there is no full-recompute fallback to fall back TO."""
        insp = inspect_plan(plan)
        if not insp.registrable or insp.kind != "stream":
            why = "; ".join(m for _, m, _ in insp.diagnostics) \
                or "plan is not a stream-view candidate"
            raise ValueError(
                f"cannot register stream view {name!r}: {why}")
        key = _stream_key(name)
        view = MaterializedView(key=key, plan=plan, inspection=insp,
                                name=name, stream=stream)
        with self._lock:
            if key in self._views:
                raise ValueError(
                    f"stream view {name!r} is already registered")
            self._views[key] = view
            self._by_stream.setdefault(stream, []).append(view)
            metrics.note_mview("registrations")
            metrics.record("mview", phase="register",
                           view_kind="stream", view=name,
                           stream=stream)
            metrics.set_gauge("mview.views", len(self._views))
        return view

    def unregister(self, key) -> None:
        with self._lock:
            view = self._views.pop(key, None)
            if view is not None and view.stream:
                subs = self._by_stream.get(view.stream, [])
                if view in subs:
                    subs.remove(view)
            metrics.set_gauge("mview.views", len(self._views))

    def drop_stream_view(self, name: str) -> None:
        self.unregister(_stream_key(name))

    def clear_file_views(self) -> None:
        """Drop every file view (CacheManager.clear delegate); stream
        views were registered explicitly and survive a cache clear."""
        with self._lock:
            for key in [k for k, v in self._views.items()
                        if v.kind == "file"]:
                del self._views[key]
            metrics.set_gauge("mview.views", len(self._views))

    def view_for(self, key) -> Optional[MaterializedView]:
        with self._lock:
            return self._views.get(key)

    def stream_view(self, name: str) -> Optional[MaterializedView]:
        return self.view_for(_stream_key(name))

    def views(self) -> List[dict]:
        with self._lock:
            return [v.to_dict() for v in self._views.values()]

    # -- file-view refresh (CacheManager._materialize delegate) --------------

    def materialize(self, view: MaterializedView, entry_lock, run,
                    store, skey):
        """Serve the view's batch, refreshing first when the source
        fingerprint moved. Same contract as the plain cache path:
        pin=True holds the served batch for the enclosing query's
        pin_scope; a store rejection still serves THIS query its
        batch."""
        with entry_lock:  # single-flight, same lock the plain path uses
            batch = store.get(skey, pin=True)
            fp = source_fingerprint(view.source())
            if batch is not None and fp is not None \
                    and fp == view.fingerprint:
                metrics.note_mview("hits")
                return batch
            if batch is None or view.fingerprint is None:
                # cold or evicted-then-missed: plain materialization
                batch = run(view.plan)
                store.put(skey, batch, pin=True)
                with view.lock:
                    view.fingerprint = fp
                metrics.record("mview", phase="materialize",
                               files=len(fp or ()))
                return batch
            kind, added = ("changed", ()) if fp is None \
                else classify_delta(view.fingerprint, fp)
            if kind == "unchanged":
                return batch  # tuple-vs-map equality raced; still fresh
            fresh = self._refresh(view, batch, kind, added, run)
            store.update(skey, fresh, pin=True)
            with view.lock:
                view.fingerprint = fp
                view.refreshes += 1
            self._repopulate_serve(view, fresh)
            self._notify_invalidation(view)
            return fresh

    def _refresh(self, view: MaterializedView, cached_batch, kind: str,
                 added, run):
        """One stale-view refresh: incremental merge when legal and the
        delta is pure appends, else full recompute. The incremental
        path passes the ``mview.refresh`` fault point; transient
        faults retry, exhaustion falls back to the recompute."""
        incremental = (kind == "appended" and bool(added)
                       and view.inspection.incremental
                       and self._incremental_on())
        if not incremental:
            view.full_recomputes += 1
            metrics.note_mview("full_recomputes")
            metrics.record("mview", phase="refresh", how="full",
                           reason=kind)
            return run(view.plan)

        def merge():
            faults.inject("mview.refresh", self._conf)
            return self._merge_file_delta(view, cached_batch, added,
                                          run)

        batch, merged = self._with_retries(
            merge, fallback=lambda: run(view.plan))
        if merged:
            view.incremental_merges += 1
            metrics.note_mview("incremental_merges")
            metrics.record("mview", phase="refresh", how="incremental",
                           files=len(added))
        else:
            view.full_recomputes += 1
            metrics.note_mview("full_recomputes")
            metrics.record("mview", phase="refresh", how="fallback")
        return batch

    def _merge_file_delta(self, view: MaterializedView, cached_batch,
                          added, run):
        """Aggregate the appended files only, then re-merge the delta
        partials with the view's own cached output through the
        MergeSpec aggregate. Byte-identical to a full recompute:
        from_arrow re-sorts/dedups dictionaries, so the merged output
        is the same pure function of the total row multiset."""
        from spark_tpu.columnar.arrow import from_arrow, to_arrow

        delta_batch = run(self._delta_plan(view, added))
        old_tbl = to_arrow(cached_batch)
        delta_tbl = to_arrow(delta_batch)
        if delta_tbl.num_rows == 0:
            return cached_batch  # appended files held no rows
        union = pa.concat_tables(
            [old_tbl, delta_tbl.select(old_tbl.column_names)])
        merge_plan = view.inspection.merge_spec.merge_plan(
            L.Relation(from_arrow(union)))
        return run(merge_plan)

    def _delta_plan(self, view: MaterializedView, added
                    ) -> L.LogicalPlan:
        """The view's plan with its scan retargeted at the appended
        files only — a fresh FileSource so none of the original
        source's caches alias the delta."""
        from spark_tpu.io.datasource import FileSource

        scan = view.inspection.scan
        src = scan.source
        delta_src = FileSource(src.fmt, list(added),
                               schema=src._schema,
                               options=dict(src.options))
        new_scan = dataclasses.replace(scan, source=delta_src)

        def fn(node):
            return new_scan if node is scan else node

        return view.plan.transform_up(fn)

    # -- stream-view maintenance ---------------------------------------------

    def on_micro_batch(self, stream: str, batch_id: int,
                       delta_tbl: pa.Table) -> None:
        """Delta event from streaming/execution.py, published BEFORE
        the WAL commit: merge the micro-batch's rows into every view
        subscribed to ``stream``. Idempotent per batch id — WAL replay
        after a commit crash redelivers the same id and is dropped."""
        with self._lock:
            views = list(self._by_stream.get(stream, ()))
        for view in views:
            self._merge_stream_delta(view, batch_id, delta_tbl)

    def _merge_stream_delta(self, view: MaterializedView,
                            batch_id: int, delta_tbl: pa.Table) -> None:
        from spark_tpu.columnar.arrow import from_arrow, to_arrow
        from spark_tpu.streaming.execution import _splice

        with view.lock:
            if batch_id <= view.last_batch_id:
                metrics.note_mview("stream_dedups")
                metrics.record("mview", phase="dedup", view=view.name,
                               batch=batch_id)
                return

            def merge():
                faults.inject("mview.refresh", self._conf)
                delta_plan = _splice(
                    view.plan, L.Relation(from_arrow(delta_tbl)))
                delta_batch = self._run(delta_plan)
                if view.state is None:
                    return delta_batch
                d_tbl = to_arrow(delta_batch)
                if d_tbl.num_rows == 0:
                    return view.state
                old_tbl = to_arrow(view.state)
                union = pa.concat_tables(
                    [old_tbl, d_tbl.select(old_tbl.column_names)])
                return self._run(
                    view.inspection.merge_spec.merge_plan(
                        L.Relation(from_arrow(union))))

            # fallback=None: exhaustion re-raises, failing the batch
            # BEFORE its WAL commit — replay redelivers the delta and
            # the untouched last_batch_id accepts it
            batch, _ = self._with_retries(merge, fallback=None)
            view.state = batch
            view.last_batch_id = batch_id
            view.refreshes += 1
            view.incremental_merges += 1
            store = getattr(self._session, "memory_store", None)
            if store is not None:
                # mirror into the store for unified byte accounting;
                # the view keeps its own reference, so an eviction
                # costs bytes-visibility, never state
                store.update(("mview", view.key), batch)
            metrics.note_mview("stream_merges")
            metrics.record("mview", phase="stream_merge",
                           view=view.name, batch=batch_id,
                           rows=delta_tbl.num_rows)
            self._repopulate_serve(view, batch)
            self._notify_invalidation(view)

    def read(self, name: str):
        """The current state of stream view ``name`` as a DataFrame
        (point-in-time snapshot: a Relation over the state batch)."""
        view = self.stream_view(name)
        if view is None:
            raise KeyError(f"no stream view named {name!r}")
        with view.lock:
            state = view.state
        if state is None:
            raise ValueError(
                f"stream view {name!r} has no state yet (no "
                "micro-batch has committed)")
        from spark_tpu.api.dataframe import DataFrame

        return DataFrame(self._session, L.Relation(state))

    # -- shared plumbing ------------------------------------------------------

    def _with_retries(self, fn, fallback):
        """Run ``fn`` with bounded transient retries
        (spark.tpu.mview.refreshRetries); returns (result, True) from
        ``fn`` or (fallback(), False) after exhaustion/non-transient
        failure. ``fallback=None`` re-raises instead."""
        try:
            retries = max(0, int(self._conf.get(CF.MVIEW_REFRESH_RETRIES)))
        except Exception:
            retries = 2
        from spark_tpu import deadline

        last: Optional[BaseException] = None
        for attempt in range(retries + 1):
            try:
                return fn(), True
            except Exception as exc:
                last = exc
                if (recovery.is_transient(exc) and attempt < retries
                        and not deadline.expired()
                        and recovery.retry_allowed("mview.refresh")):
                    metrics.note_mview("refresh_retries")
                    metrics.record("mview", phase="retry",
                                   error=type(exc).__name__,
                                   attempt=attempt + 1)
                    continue
                break
        if fallback is None:
            raise last
        metrics.note_mview("refresh_fallbacks")
        metrics.record("mview", phase="fallback",
                       error=type(last).__name__)
        metrics.record("fault_recovered", point="mview.refresh",
                       how="full_recompute")
        return fallback(), False

    def _run(self, plan: L.LogicalPlan):
        """Engine for stream-view delta/merge plans — same dispatch the
        streaming runtime uses (mesh when the session has one)."""
        ex = getattr(self._session, "mesh_executor", None)
        if ex is not None:
            return ex.execute_logical(plan)
        from spark_tpu.physical.planner import execute_logical

        return execute_logical(plan)

    def _repopulate_serve(self, view: MaterializedView, batch) -> None:
        """Push the refreshed result into the serve-tier ResultCache
        under the NEW fingerprint key, so the first post-refresh
        request hits instead of cold-missing. The bytes are exactly
        what the connect server would serialize (table_to_ipc of the
        same Arrow table), so hits stay byte-identical."""
        cache = getattr(self._session, "serve_result_cache", None)
        if cache is None or not cache.enabled():
            return
        try:
            if not bool(self._conf.get(CF.MVIEW_SERVE_REPOPULATE)):
                return
        except Exception:
            return
        try:
            from spark_tpu.columnar.arrow import to_arrow
            from spark_tpu.serve import result_cache as rc

            key = rc.plan_result_key(view.plan)
            cache.put(key, rc.table_to_ipc(to_arrow(batch)))
            metrics.note_mview("serve_repopulations")
            metrics.record("mview", phase="serve_repopulate",
                           key=rc.key_digest(key))
        except Exception as exc:  # serve repopulation is best-effort
            metrics.record("mview", phase="serve_repopulate_error",
                           error=type(exc).__name__)

    def _notify_invalidation(self, view: MaterializedView) -> None:
        """Append a versioned ``mview_refresh`` record to the session's
        fleet invalidation log the moment a refresh COMMITS: every
        subscribed replica ResultCache drops entries touching the
        view's source paths, closing the stale-serve window a TTL'd
        fingerprint probe would otherwise leave open. Only fires when
        a log already exists (fleet mode attached one) — single-replica
        serving keeps its zero-overhead path."""
        log = getattr(self._session, "serve_invalidation_log", None)
        if log is None:
            return
        try:
            scan = view.inspection.scan
            paths = getattr(getattr(scan, "source", None), "paths",
                            None) if scan is not None else None
            if paths:
                log.append("mview_refresh", paths)
        except Exception as exc:  # coherence push is best-effort;
            # the per-request fingerprint TTL still bounds staleness
            metrics.record("mview", phase="invalidate_error",
                           error=type(exc).__name__)

    def stats(self) -> dict:
        with self._lock:
            views = [v.to_dict() for v in self._views.values()]
        return {
            "views": len(views),
            "file_views": sum(1 for v in views if v["kind"] == "file"),
            "stream_views": sum(
                1 for v in views if v["kind"] == "stream"),
            "refreshes": sum(v["refreshes"] for v in views),
            "incremental_merges": sum(
                v["incremental_merges"] for v in views),
            "full_recomputes": sum(
                v["full_recomputes"] for v in views),
        }

"""Materialized-view descriptors and the maintainability verdict.

``inspect_plan`` answers, statically, the two questions the manager
and the plan analyzer both need:

1. is this plan REGISTRABLE as a view at all (root Aggregate over
   exactly one fingerprinted file scan, or one streaming source), and
2. is a registered view INCREMENTALLY maintainable (grouping keys
   carried through to the output, every aggregate exactly
   re-mergeable per analysis/legality.remerge_verdict)?

A registrable-but-not-incremental view still refreshes — by full
recompute — so freshness never depends on merge legality; legality
only decides the device cost of a refresh. The same inspection feeds
the ``PLAN-MVIEW-*`` diagnostic family in ``df.explain(mode="lint")``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from spark_tpu.analysis import legality
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


@dataclass(frozen=True)
class MergeSpec:
    """How to re-merge a view's own output with delta partials: group
    by the key OUTPUT columns, re-apply each Sum/Min/Max to its own
    output column (exact by the re-merge legality rule)."""

    key_names: Tuple[str, ...]
    merge_outs: Tuple[E.Expression, ...]

    def merge_plan(self, child: L.LogicalPlan) -> L.LogicalPlan:
        keys = tuple(E.Col(n) for n in self.key_names)
        return L.Aggregate(keys, self.merge_outs, child)


@dataclass(frozen=True)
class Inspection:
    """Static view-candidacy report for one logical plan."""

    registrable: bool
    incremental: bool
    kind: str                     # "file" | "stream" | ""
    scan: Optional[L.LogicalPlan]  # the single UnresolvedScan, if file
    merge_spec: Optional[MergeSpec]
    #: (code, message, hint) triples for the PLAN-MVIEW-* family
    diagnostics: Tuple[Tuple[str, str, str], ...]


def _not_registrable(code: str, message: str, hint: str) -> Inspection:
    return Inspection(False, False, "", None, None,
                      ((code, message, hint),))


def _merge_spec(agg: L.Aggregate):
    """Build the key/merge output lists, or a (code, message) pair when
    the structure cannot be re-merged: every grouping must surface as a
    plain output column (its value is what the merge re-groups by)."""
    out_by_key = {}
    for e in agg.aggregates:
        inner = E.strip_alias(e)
        if isinstance(inner, E.Col):
            out_by_key[E.expr_key(inner)] = e.name
    key_names = []
    for g in agg.groupings:
        name = out_by_key.get(E.expr_key(E.strip_alias(g)))
        if name is None:
            return None, (
                "PLAN-MVIEW-KEYS",
                f"grouping key {g} is not carried through to the "
                "output as a plain column; the merge cannot re-group "
                "delta partials without its value")
        key_names.append(name)
    merge_outs: List[E.Expression] = []
    for e in agg.aggregates:
        inner = E.strip_alias(e)
        if isinstance(inner, E.Col):
            merge_outs.append(E.Alias(E.Col(e.name), e.name))
            continue
        calls = E.collect_aggregates(inner)
        # remerge_verdict (checked by the caller) guarantees exactly
        # one Sum/Min/Max call equal to the whole expression
        call = calls[0]
        merge_outs.append(E.Alias(type(call)(E.Col(e.name)), e.name))
    return MergeSpec(tuple(key_names), tuple(merge_outs)), None


def inspect_plan(plan: L.LogicalPlan) -> Inspection:
    """Classify ``plan`` as a materialized-view candidate. Only root
    Aggregates are candidates (operators above the aggregate would have
    to re-run over the refreshed state — out of scope, exactly the
    streaming restriction)."""
    from spark_tpu.io.fingerprint import source_fingerprint

    if not isinstance(plan, L.Aggregate):
        return _not_registrable(
            "PLAN-MVIEW-SHAPE",
            "materialized views require the aggregate at the plan "
            "root",
            "cache() the groupBy().agg() result itself; operators "
            "above it re-run per query anyway")

    from spark_tpu.streaming.execution import StreamingSource

    streams = L.collect_nodes(plan, StreamingSource)
    scans = L.collect_nodes(plan, L.UnresolvedScan)
    if streams:
        if len(streams) != 1 or scans:
            return _not_registrable(
                "PLAN-MVIEW-SOURCE",
                "stream views require exactly one streaming source "
                "and no file scans",
                "split multi-source plans before registering")
        kind, scan = "stream", None
    else:
        if len(scans) != 1:
            return _not_registrable(
                "PLAN-MVIEW-SOURCE",
                f"materialized views require exactly one file scan "
                f"(found {len(scans)})",
                "joins of several sources refresh ambiguously; cache "
                "each side instead")
        scan = scans[0]
        if source_fingerprint(scan.source) is None:
            return _not_registrable(
                "PLAN-MVIEW-SOURCE",
                "the scan source has no file fingerprint (in-memory "
                "relation?) — no delta to detect",
                "only file-backed sources can be refreshed")
        kind = "file"

    diags: List[Tuple[str, str, str]] = []
    v = legality.remerge_verdict(plan)
    spec = None
    if not v.ok:
        diags.append((
            "PLAN-MVIEW-RECOMPUTE",
            f"view refreshes by FULL recompute: {v.reason} "
            f"({v.offending})",
            "integer Sum / non-float Min/Max aggregates merge "
            "incrementally; others stay correct but pay a full "
            "device recompute per refresh"))
    else:
        spec, err = _merge_spec(plan)
        if spec is None:
            code, message = err
            diags.append((
                code, message,
                "add the grouping column itself to the aggregate "
                "output list"))
        else:
            diags.append((
                "PLAN-MVIEW-OK",
                "view is incrementally maintainable: appended files "
                "merge into the cached batch without a full recompute",
                ""))
    incremental = spec is not None
    if kind == "stream" and not incremental:
        # streams cannot be re-scanned, so a stream view without a
        # merge path cannot exist at all
        return Inspection(False, False, kind, scan, None, tuple(diags))
    return Inspection(True, incremental, kind, scan, spec, tuple(diags))


@dataclass(eq=False)
class MaterializedView:
    """One registered view: the plan, its inspection, and the mutable
    refresh state (guarded by ``lock`` — the manager single-flights
    refreshes per view)."""

    key: Any                       # structural plan key
    plan: L.LogicalPlan
    inspection: Inspection
    name: str = ""                 # stream views: reader handle
    stream: str = ""               # stream views: source query name
    fingerprint: Optional[tuple] = None   # file views: last refreshed
    last_batch_id: int = -1        # stream views: WAL dedup watermark
    state: Any = None              # stream views: merged device batch
    lock: threading.Lock = field(default_factory=threading.Lock)
    refreshes: int = 0
    incremental_merges: int = 0
    full_recomputes: int = 0

    @property
    def kind(self) -> str:
        return self.inspection.kind

    def source(self):
        return self.inspection.scan.source if self.inspection.scan \
            is not None else None

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "name": self.name,
            "stream": self.stream,
            "incremental": self.inspection.incremental,
            "plan": self.plan.node_string(),
            "files": len(self.fingerprint or ()),
            "last_batch_id": self.last_batch_id,
            "refreshes": self.refreshes,
            "incremental_merges": self.incremental_merges,
            "full_recomputes": self.full_recomputes,
        }

"""Session extension points.

Analogue of SparkSessionExtensions (reference:
sql/core/.../SparkSessionExtensions.scala — injectOptimizerRule:268,
injectFunction:344, injectParser:318, injectPlannerStrategy:298) and the
driver/executor plugin hook (core/.../api/plugin/SparkPlugin.java:37,
activated by the ``spark.plugins`` conf,
internal/config/package.scala:1718).

Kept deliberately small: extensions register *callables* —
  - optimizer rules: LogicalPlan -> LogicalPlan, run after the built-in
    fixpoint batch every optimize();
  - functions: name -> Expression builder, resolvable from SQL and the
    DataFrame API;
  - parser interceptors: (sql_text, catalog, default_parse) -> plan,
    first non-None wins (dialect front-ends);
  - plugins: objects with init(session)/shutdown() driven by the
    ``spark.plugins`` conf (module:attr paths).
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, List, Optional

from spark_tpu import conf as CF

PLUGINS = CF.register(
    "spark.plugins", "",
    "Comma-separated module:attr paths of plugin objects with "
    "init(session) / shutdown() (reference: SparkPlugin.java:37).", str)


class Extensions:
    """Per-session registry (reference: SparkSessionExtensions)."""

    def __init__(self):
        self._optimizer_rules: List[Callable] = []
        self._functions: Dict[str, Callable] = {}
        self._parser_hooks: List[Callable] = []
        self._plugins: List[Any] = []

    # -- injection points ----------------------------------------------------

    def inject_optimizer_rule(self, rule: Callable) -> None:
        """rule: LogicalPlan -> LogicalPlan, applied after the built-in
        batch (reference: injectOptimizerRule)."""
        self._optimizer_rules.append(rule)

    injectOptimizerRule = inject_optimizer_rule

    def inject_function(self, name: str, builder: Callable) -> None:
        """builder(*arg_exprs) -> Expression (reference: injectFunction).
        Resolvable from SQL calls and ``F.call_function``."""
        self._functions[name.lower()] = builder

    injectFunction = inject_function

    def inject_parser(self, hook: Callable) -> None:
        """hook(sql, catalog, default_parse) -> Optional[LogicalPlan];
        first non-None wins (reference: injectParser)."""
        self._parser_hooks.append(hook)

    injectParser = inject_parser

    # -- lookups used by the engine ------------------------------------------

    def optimizer_rules(self) -> List[Callable]:
        return list(self._optimizer_rules)

    def function(self, name: str) -> Optional[Callable]:
        return self._functions.get(name.lower())

    def parse(self, sql: str, catalog, default_parse):
        for hook in self._parser_hooks:
            plan = hook(sql, catalog, default_parse)
            if plan is not None:
                return plan
        return default_parse(sql, catalog)

    # -- plugin lifecycle ----------------------------------------------------

    def load_plugins(self, session) -> None:
        """Instantiate spark.plugins entries (module:attr) and call
        init(session) (reference: PluginContainer.scala:30)."""
        spec = str(session.conf.get(PLUGINS) or "")
        for path in filter(None, (p.strip() for p in spec.split(","))):
            mod_name, _, attr = path.partition(":")
            obj = getattr(importlib.import_module(mod_name), attr or "plugin")
            if isinstance(obj, type):
                obj = obj()
            if hasattr(obj, "init"):
                obj.init(session)
            self._plugins.append(obj)

    def shutdown_plugins(self) -> None:
        for p in self._plugins:
            if hasattr(p, "shutdown"):
                p.shutdown()
        self._plugins.clear()

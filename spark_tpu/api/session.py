"""SparkSession: the user entry point (reference:
sql/core/src/main/scala/org/apache/spark/sql/SparkSession.scala and
SparkContext.scala:85 — collapsed: there is no driver/executor split to
bootstrap, the 'cluster' is the jax device mesh).
"""

from __future__ import annotations

import datetime
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

import jax

from spark_tpu import locks
from spark_tpu import types as T
from spark_tpu.api.dataframe import DataFrame
from spark_tpu.conf import RuntimeConf
from spark_tpu.plan import logical as L
from spark_tpu.types import Field, Schema


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache. XLA compiles on this class of
    host are multi-second even for trivial programs; the disk cache turns
    warm-process startup into sub-second loads (the analogue of the
    reference reusing Janino-compiled classes across queries,
    CodeGenerator.scala:1442 'cache')."""
    import os

    if os.environ.get("SPARK_TPU_JAX_CACHE") in ("0", "off"):
        # XLA:CPU AOT (de)serialization is not reliable on this host
        # class (observed: SIGSEGV in deserialize_executable and SIGABRT
        # in serialize_executable deep into long multi-hundred-compile
        # processes, always via the persistent cache paths; plus E-level
        # 'machine feature +prefer-no-scatter not supported' loader
        # warnings on every hit). The test suite opts out; normal
        # sessions and the TPU bench keep the disk cache.
        return

    try:
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    # AOT executables embed the compile machine's ISA features; loading
    # them on a host without those features can SIGILL. Key the cache
    # dir on a CPU-feature fingerprint as well as the backend.
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            flags = next((ln for ln in f if ln.startswith("flags")), "")
        cpu_tag = hashlib.sha1(flags.encode()).hexdigest()[:8]
    except OSError:
        import platform as _plat

        cpu_tag = _plat.machine()
    cache_dir = os.environ.get(
        "SPARK_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     f"spark_tpu_jax_{platform}_{cpu_tag}"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass  # older jax without these flags: in-memory caching only
    _harden_cache_writes()


def _harden_cache_writes() -> None:
    """Make persistent-cache entry writes atomic. jax's LRUCache.put
    writes entries with a bare ``Path.write_bytes`` (lru_cache.py:152) —
    a process killed mid-write leaves a TRUNCATED serialized executable,
    and every later process SIGSEGVs inside
    ``backend.deserialize_executable`` when it reads the entry (observed:
    full-suite segfaults after a timeout-killed run poisoned the cache).
    Wrap put() so entry files land via write-temp + os.replace."""
    try:
        from jax._src import lru_cache as _lru
    except Exception:
        return
    if getattr(_lru.LRUCache.put, "_spark_tpu_atomic", False):
        return
    orig = _lru.LRUCache.put
    suffix = getattr(_lru, "_CACHE_SUFFIX", None)
    if suffix is None:
        return  # unknown layout: leave jax untouched

    def put(self, key, val, _orig=orig, _suffix=suffix):
        # Pre-create the entry file ATOMICALLY (temp + rename), then let
        # the original put run: it sees the entry exists and returns,
        # still doing its own locking/eviction bookkeeping. No global
        # state is patched, so concurrent writers are unaffected.
        import os
        import threading

        try:
            if key:
                cache_path = self.path / f"{key}{_suffix}"
                tmp = cache_path.with_name(
                    f"{cache_path.name}.tmp{os.getpid()}-"
                    f"{threading.get_ident()}")
                tmp.write_bytes(val)
                os.replace(tmp, cache_path)
        except OSError:
            pass  # fall through: original non-atomic path still works
        return _orig(self, key, val)

    put._spark_tpu_atomic = True
    _lru.LRUCache.put = put


def _instrument_compile_cache() -> None:
    """Count persistent compilation-cache hits/misses, and keep the
    managed executable store within its byte bound. jax's lookup funnel
    is ``compilation_cache.get_executable_and_time`` — returns a
    deserialized executable on a disk hit, None on a miss (followed by
    a fresh XLA compile, which jax then writes back to the cache dir).
    Wrapping it feeds metrics.note_compile_cache so warmup time is
    attributable, and — when the compile service routes jax's cache
    inside the spark.tpu.compile.store.dir root — schedules LRU budget
    enforcement after each miss, so jax's own cache writes count
    against the same size bound as our AOT entries."""
    try:
        from jax._src import compilation_cache as _cc
    except Exception:
        return
    fn = getattr(_cc, "get_executable_and_time", None)
    if fn is None or getattr(fn, "_spark_tpu_counted", False):
        return

    from spark_tpu import metrics as _metrics

    def get_executable_and_time(*a, _orig=fn, **kw):
        out = _orig(*a, **kw)
        try:
            executable = out[0] if isinstance(out, tuple) else out
            hit = executable is not None
            _metrics.note_compile_cache(hit)
            if not hit:
                # a miss means jax is about to write a fresh cache
                # entry: re-check the managed store's byte bound
                # (misses happen once per compile — seconds apart —
                # so the directory walk is off the hot path)
                from spark_tpu.compile.service import active_service

                svc = active_service()
                if svc is not None and svc.store is not None:
                    svc.store.enforce_budget()
        except Exception:
            pass
        return out

    get_executable_and_time._spark_tpu_counted = True
    _cc.get_executable_and_time = get_executable_and_time


class CacheManager:
    """Lazy in-memory plan cache (reference: CacheManager.scala +
    InMemoryRelation): cache() registers the logical plan; the first
    execution materializes it to a device Batch held in the
    HBM-resident MemoryStore (storage/store.py), and every later query
    whose tree contains a cached subplan scans the stored batch instead
    of recomputing. Identity is structural_key() — injective plan
    structure plus leaf batch/source identity.

    Because the batches live in the byte-accounted store, cached plans
    are EVICTABLE: execution admission or storage pressure may drop an
    unpinned entry LRU-first, and the next query that needs it simply
    re-materializes (the plan registration survives eviction — only
    the bytes are reclaimed). uncache()/clear() remove the store entry
    too, releasing its bytes immediately.

    Thread-safe: the registry mutates under a lock, and each entry
    materializes under its own per-entry lock (single-flight — two
    concurrent queries hitting the same cold cached plan must not
    both materialize it; the registry lock is NOT held during the
    materializing run, so unrelated queries proceed)."""

    def __init__(self, store=None):
        if store is None:
            # standalone manager (tests / sessions built without a
            # store): private unified budget, same code path
            from spark_tpu.storage import MemoryStore, UnifiedMemoryManager

            store = MemoryStore(UnifiedMemoryManager())
        self._store = store
        # entry = [plan, entry lock]
        self._entries: Dict[str, list] = {}
        self._lock = locks.named_lock("session.cache.registry")
        # set by SparkSession: the materialized-view manager; when a
        # cached key is a registered view, materialization delegates
        # to its freshness-checking refresh path (spark_tpu/mview/)
        self._mview = None

    @staticmethod
    def _key(plan: L.LogicalPlan):
        # injective structural identity incl. leaf batch/source identity
        return plan.structural_key()

    @staticmethod
    def _skey(key):
        # namespace cache entries apart from auto-cached scans, which
        # share the store
        return ("cache", key)

    def add(self, plan: L.LogicalPlan) -> None:
        with self._lock:
            self._entries.setdefault(
                self._key(plan), [plan, locks.named_lock("session.cache.entry")])
        if self._mview is not None:
            self._mview.maybe_register(plan)

    def drop(self, plan: L.LogicalPlan) -> bool:
        key = self._key(plan)
        with self._lock:
            entry = self._entries.pop(key, None)
        if entry is None:
            return False
        if self._mview is not None:
            self._mview.unregister(key)
        self._store.remove(self._skey(key))  # releases the bytes
        return True

    def clear(self) -> None:
        with self._lock:
            keys = list(self._entries)
            self._entries.clear()
        if self._mview is not None:
            self._mview.clear_file_views()
        for key in keys:
            self._store.remove(self._skey(key))

    def apply(self, plan: L.LogicalPlan, run) -> L.LogicalPlan:
        """Substitute cached subtrees, LARGEST first (top-down — the
        reference CacheManager matches outermost plans first so a cached
        derived plan hits even when its own subtree is also cached)."""
        with self._lock:
            if not self._entries:
                return plan

        def go(node: L.LogicalPlan) -> L.LogicalPlan:
            with self._lock:
                entry = self._entries.get(self._key(node))
            if entry is not None:
                return L.Relation(self._materialize(node, entry, run))
            children = tuple(go(c) for c in node.children())
            return node.with_children(children) if children else node

        return go(plan)

    def _materialize(self, node: L.LogicalPlan, entry: list, run):
        """Store-hit or single-flight recompute; pin=True holds the
        batch for the duration of the enclosing query's pin_scope."""
        key = self._key(node)
        skey = self._skey(key)
        if self._mview is not None:
            view = self._mview.view_for(key)
            if view is not None:
                # registered materialized view: the manager checks the
                # source fingerprint and refreshes in place before
                # serving (a plain store hit would serve stale bytes)
                return self._mview.materialize(
                    view, entry[1], run, self._store, skey)
        batch = self._store.get(skey, pin=True)
        if batch is not None:
            return batch
        with entry[1]:  # single-flight materialization
            batch = self._store.get(skey, pin=True)
            if batch is not None:
                return batch
            batch = run(entry[0])
            # a rejected put (cannot fit under the unified budget even
            # after evicting the store's LRU tail) still serves THIS
            # query its batch; the entry stays recomputable
            self._store.put(skey, batch, pin=True)
            return batch


class Catalog:
    """Temp-view + table registry (reference:
    sql/catalyst/.../catalog/SessionCatalog.scala:61, pared to the
    in-memory session catalog; file-backed tables register here too)."""

    def __init__(self, session: "SparkSession"):
        self._session = session
        self._views: Dict[str, L.LogicalPlan] = {}

    def _register_view(self, name: str, plan: L.LogicalPlan) -> None:
        self._views[name.lower()] = plan

    def lookup(self, name: str) -> L.LogicalPlan:
        key = name.lower()
        if key not in self._views:
            plan = self._load_persistent(key)
            if plan is None:
                raise KeyError(f"table or view not found: {name}")
            return plan
        return self._views[key]

    def _warehouse(self) -> str:
        from spark_tpu import conf as CF

        return self._session.conf.get(CF.WAREHOUSE_DIR)

    def _load_persistent(self, key: str):
        """Persistent (saveAsTable) tier: tables live as
        <warehouse>/<name>/{_table.json,data/} and survive sessions
        (reference: SessionCatalog external-catalog lookup)."""
        import json
        import os

        meta_path = os.path.join(self._warehouse(), key, "_table.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        from spark_tpu.io.datasource import FileSource

        options = dict(meta.get("options") or {})
        if meta.get("partition_by"):
            # partition columns live in hive directory names
            options["partitioning"] = "hive"
        src = FileSource(meta.get("format", "parquet"),
                         [os.path.join(self._warehouse(), key, "data")],
                         options=options)
        plan = L.UnresolvedScan(src)
        self._views[key] = plan  # memoize for the session
        return plan

    def refresh_persistent(self, key: str) -> None:
        """Drop any memoized plan so the next lookup re-reads the
        (re)written table."""
        self._views.pop(key, None)

    def listTables(self) -> List[str]:
        import os

        names = set(self._views)
        wh = self._warehouse()
        if os.path.isdir(wh):
            for d in os.listdir(wh):
                if os.path.exists(os.path.join(wh, d, "_table.json")):
                    names.add(d)
        return sorted(names)

    def dropTempView(self, name: str) -> bool:
        return self._views.pop(name.lower(), None) is not None

    def tableExists(self, name: str) -> bool:
        return name.lower() in self._views


class SparkSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}
        self._app_name = "spark-tpu"
        self._ext_fns: list = []

    def appName(self, name: str) -> "SparkSessionBuilder":
        self._app_name = name
        return self

    def withExtensions(self, fn) -> "SparkSessionBuilder":
        """fn(extensions) registers injection points at session build
        (reference: SparkSession.Builder.withExtensions)."""
        self._ext_fns.append(fn)
        return self

    def master(self, url: str) -> "SparkSessionBuilder":
        """master URL analogue (reference: SparkContext master parsing):
        ``local`` / ``local[*]`` = single-device; ``mesh[N]`` = SPMD
        execution over an N-device jax mesh (the cluster IS the mesh)."""
        if url.startswith("mesh"):
            n = None
            if "[" in url:
                inner = url[url.index("[") + 1:url.index("]")]
                n = None if inner == "*" else int(inner)
            from spark_tpu import conf as CF

            self._conf[CF.MESH_DEVICES.key] = n if n is not None else -1
        return self

    def config(self, key: str, value: Any) -> "SparkSessionBuilder":
        self._conf[key] = value
        return self

    def getOrCreate(self) -> "SparkSession":
        if SparkSession._active is None:
            SparkSession._active = SparkSession(self._app_name, self._conf)
        else:
            for k, v in self._conf.items():
                SparkSession._active.conf.set(k, v)
        for fn in self._ext_fns:
            fn(SparkSession._active.extensions)
        self._ext_fns = []
        return SparkSession._active


class SparkSession:
    _active: Optional["SparkSession"] = None

    builder = SparkSessionBuilder()

    def __init__(self, app_name: str = "spark-tpu",
                 conf: Optional[Dict[str, Any]] = None):
        # SQL engines need 64-bit ints/floats; flip jax's default.
        jax.config.update("jax_enable_x64", True)
        _enable_compilation_cache()
        _instrument_compile_cache()
        self.app_name = app_name
        self.conf = RuntimeConf(conf)
        # runtime lock-order validation (spark.tpu.debug.lockOrder):
        # flip the global flag before any service builds its locks
        locks.configure(self.conf)
        self.catalog = Catalog(self)
        # unified storage/execution HBM accounting: the MemoryStore
        # (cached/auto-cached batches) and the scheduler's admission
        # controller share one budget (spark.tpu.scheduler.hbmBudgetBytes)
        from spark_tpu.storage import MemoryStore, UnifiedMemoryManager

        self.memory_manager = UnifiedMemoryManager(conf=self.conf)
        self.memory_store = MemoryStore(self.memory_manager)
        self.cache_manager = CacheManager(store=self.memory_store)
        # materialized views ride on the plan cache: cache() promotes
        # qualifying aggregates to views; the cache's materialize path
        # delegates to the view manager for freshness (spark_tpu/mview/)
        from spark_tpu.mview import ViewManager

        self.mview_manager = ViewManager(self)
        self.cache_manager._mview = self.mview_manager
        self._stopped = False
        from spark_tpu.extensions import Extensions

        self.extensions = Extensions()
        self._read = None
        self._mesh = None
        self._mesh_executor = None
        from spark_tpu import conf as CF

        n = self.conf.get(CF.MESH_DEVICES)
        if n is not None:
            from spark_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(None if n == -1 else int(n))
        # live status UI/REST server (reference: SparkUI.scala:40),
        # gated on spark.ui.enabled
        from spark_tpu import ui as _ui

        self._ui = _ui.maybe_start(self)
        # last: plugins may exercise any session API from init(session)
        self.extensions.load_plugins(self)

    @property
    def ui_web_url(self) -> Optional[str]:
        """URL of the live status UI when enabled (reference:
        SparkContext.uiWebUrl)."""
        return self._ui.url if self._ui is not None else None

    @property
    def mesh_executor(self):
        """Distributed executor when running under a mesh master URL."""
        if self._mesh is None:
            return None
        if self._mesh_executor is None:
            from spark_tpu.parallel.executor import MeshExecutor

            self._mesh_executor = MeshExecutor(self._mesh, conf=self.conf)
        return self._mesh_executor

    @property
    def compile_service(self):
        """AOT compilation service (spark_tpu/compile/) when any
        spark.tpu.compile.* feature is enabled; None otherwise —
        callers treat None as 'legacy behavior'. Re-resolved per
        access so conf changes (store dir, background flag) take
        effect immediately."""
        from spark_tpu.compile import maybe_service

        return maybe_service(self)

    # -- builder is reset-safe for tests
    @classmethod
    def _reset(cls):
        cls._active = None
        cls.builder = SparkSessionBuilder()

    @classmethod
    def getActiveSession(cls) -> Optional["SparkSession"]:
        """Reference: SparkSession.getActiveSession."""
        return cls._active

    @classmethod
    def setActiveSession(cls, session: "SparkSession") -> None:
        cls._active = session

    def _ensure_active(self) -> None:
        """Make this session the process-current one if none is (global
        lookups — injected functions/rules, conf-driven optimizer flags —
        resolve against the active session; a session that is executing
        a query is by definition current). A stop()ed session never
        resurrects itself: getOrCreate() must build a fresh one."""
        if SparkSession._active is None and not self._stopped:
            SparkSession._active = self

    @property
    def sparkContext(self):
        """RDD-tier entry point (reference: SparkContext.scala:85)."""
        if getattr(self, "_sc", None) is None:
            from spark_tpu.rdd import SparkContext

            self._sc = SparkContext(self)
        return self._sc

    @property
    def read(self):
        from spark_tpu.io.readwriter import DataFrameReader

        return DataFrameReader(self)

    @property
    def readStream(self):
        from spark_tpu.streaming.readwriter import DataStreamReader

        return DataStreamReader(self)

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1, numSlices: Optional[int] = None) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Range(int(start), int(end), int(step)))

    def table(self, name: str) -> DataFrame:
        return DataFrame(self, self.catalog.lookup(name))

    def sql(self, query: str) -> DataFrame:
        from spark_tpu.sql.parser import parse_sql

        self._ensure_active()
        # injected parser hooks first (injectParser:318 analogue)
        plan = self.extensions.parse(query, self.catalog, parse_sql)
        df = DataFrame(self, plan)
        # carried for the compile service's served-plan history: SQL
        # text is the cross-process-replayable identity of this plan
        df._sql_text = query
        return df

    def createDataFrame(
        self,
        data: Union["pa.Table", "pd.DataFrame", Iterable],
        schema: Optional[Union[Schema, Sequence[str]]] = None,
    ) -> DataFrame:
        import pandas as pd
        import pyarrow as pa

        from spark_tpu.columnar.arrow import from_arrow

        if isinstance(data, pa.Table):
            table = data
        elif isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        else:
            rows = list(data)
            if not rows:
                raise ValueError("cannot create DataFrame from empty data "
                                 "without an explicit arrow/pandas input")
            if isinstance(rows[0], dict):
                names = list(rows[0].keys())
                cols = {n: [r[n] for r in rows] for n in names}
            else:
                if schema is None:
                    raise ValueError("tuple rows require column names")
                names = (list(schema.names) if isinstance(schema, Schema)
                         else list(schema))
                cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
            table = pa.table(cols)
        df = DataFrame(self, L.Relation(from_arrow(table)))
        if isinstance(schema, Sequence) and not isinstance(schema, Schema) \
                and schema is not None and not isinstance(schema, str):
            old = df.columns
            if list(schema) != old and len(schema) == len(old):
                for o, n in zip(old, schema):
                    df = df.withColumnRenamed(o, n)
        return df

    def _stop_services(self) -> None:
        """Stop and join every background service/thread the session
        owns (compile workers, scheduler worker pool, heartbeat
        monitor, status UI). Split from ``stop()`` so tests can
        quiesce the threads without tearing down the singleton."""
        svc = self.__dict__.pop("_compile_service", None)
        if svc is not None:
            svc.stop()
        sched = getattr(self, "query_scheduler", None)
        if sched is not None:
            sched.stop()
            self.query_scheduler = None
        hb = getattr(self, "heartbeat_monitor", None)
        if hb is not None:
            hb.stop()
            self.heartbeat_monitor = None
        if self._ui is not None:
            self._ui.stop()
            self._ui = None

    def stop(self) -> None:
        self._stopped = True
        self._stop_services()
        self.extensions.shutdown_plugins()
        SparkSession._reset()

    @property
    def version(self) -> str:
        from spark_tpu import __version__

        return __version__

    def __repr__(self):
        return f"<SparkSession app={self.app_name} devices={jax.device_count()}>"

"""Window specifications — the pyspark.sql.window surface (reference:
sql/core/src/main/scala/org/apache/spark/sql/expressions/Window.scala,
python/pyspark/sql/window.py).

    from spark_tpu.api.window import Window
    w = Window.partitionBy("dept").orderBy(F.desc("salary"))
    df.withColumn("rk", F.rank().over(w))
"""

from __future__ import annotations

import sys
from typing import Optional, Tuple

from spark_tpu.expr import expressions as E


def _c(x):
    return x if isinstance(x, E.Expression) else E.Col(x)


def _order(x) -> E.SortOrder:
    e = _c(x)
    return e if isinstance(e, E.SortOrder) else E.SortOrder(e, True)


class WindowSpec:
    def __init__(self, partition_by: Tuple[E.Expression, ...] = (),
                 order_by: Tuple[E.SortOrder, ...] = (),
                 frame: Optional[tuple] = None):
        self._partition_by = partition_by
        self._order_by = order_by
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        return WindowSpec(tuple(_c(c) for c in cols), self._order_by,
                          self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self._partition_by,
                          tuple(_order(c) for c in cols), self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        s = None if start <= Window.unboundedPreceding else start
        e = None if end >= Window.unboundedFollowing else end
        return WindowSpec(self._partition_by, self._order_by,
                          ("rows", s, e))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        s = None if start <= Window.unboundedPreceding else start
        e = None if end >= Window.unboundedFollowing else end
        return WindowSpec(self._partition_by, self._order_by,
                          ("range", s, e))

    def _attach(self, func: E.Expression) -> E.WindowExpr:
        return E.WindowExpr(func, self._partition_by, self._order_by,
                            self._frame)


class Window:
    unboundedPreceding = -(sys.maxsize - 1)
    unboundedFollowing = sys.maxsize - 1
    currentRow = 0

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)

    @staticmethod
    def rowsBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rowsBetween(start, end)

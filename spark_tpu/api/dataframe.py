"""DataFrame: lazy logical-plan builder + actions (reference:
sql/core/src/main/scala/org/apache/spark/sql/Dataset.scala — collect:3432
withAction:4173; python surface python/pyspark/sql/dataframe.py).

A DataFrame is (session, logical plan). Transformations build new plans;
actions run optimize -> physical plan -> stage-fused execution
(QueryExecution.scala:55 pipeline analogue, see physical/planner.py).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

from spark_tpu.api.row import Row
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.types import Schema

ColumnOrName = Union[E.Expression, str]


def _c(c: ColumnOrName) -> E.Expression:
    return c if isinstance(c, E.Expression) else E.Col(c)


def _order(c: ColumnOrName) -> E.SortOrder:
    e = _c(c)
    if isinstance(e, E.SortOrder):
        return e
    return E.SortOrder(e, ascending=True)


class DataFrame:
    def __init__(self, session, plan: L.LogicalPlan):
        self._session = session
        self._plan = plan

    # ---- metadata ----------------------------------------------------------

    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return list(self._plan.schema.names)

    @property
    def sparkSession(self):
        return self._session

    def explain(self, extended: bool = False,
                mode: Optional[str] = None) -> None:
        from spark_tpu.plan.optimizer import optimize
        from spark_tpu.physical.planner import plan_physical

        if mode == "lint" or extended == "lint":
            # static plan analysis without executing (reference:
            # Dataset.explain(mode) ExplainMode, Dataset.scala:590 —
            # "lint" is this engine's extra mode)
            from spark_tpu import analysis

            conf = self._session.conf if self._session is not None \
                else None
            print(analysis.analyze(self._plan, conf).format())
            return
        print("== Logical Plan ==")
        print(self._plan.tree_string())
        opt = optimize(self._plan)
        if extended:
            print("== Optimized Logical Plan ==")
            print(opt.tree_string())
        print("== Physical Plan ==")
        print(plan_physical(opt).tree_string())

    def _with(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(self._session, plan)

    # ---- transformations ---------------------------------------------------

    def select(self, *cols: ColumnOrName) -> "DataFrame":
        if not cols:
            cols = tuple(self.columns)
        exprs: List[E.Expression] = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                exprs.extend(E.Col(n) for n in self.columns)
            else:
                exprs.append(_c(c))
        return self._with(L.project_with_windows(tuple(exprs), self._plan))

    def selectExpr(self, *exprs: str) -> "DataFrame":
        from spark_tpu.sql.parser import parse_projection

        parsed = [parse_projection(s, self._plan.schema) for s in exprs]
        return self._with(L.project_with_windows(tuple(parsed), self._plan))

    def filter(self, condition: Union[E.Expression, str]) -> "DataFrame":
        if isinstance(condition, str):
            from spark_tpu.sql.parser import parse_expression

            condition = parse_expression(condition)
        return self._with(L.Filter(condition, self._plan))

    where = filter

    def withColumn(self, name: str, col: E.Expression) -> "DataFrame":
        exprs = []
        replaced = False
        for n in self.columns:
            if n == name:
                exprs.append(E.Alias(col, name))
                replaced = True
            else:
                exprs.append(E.Col(n))
        if not replaced:
            exprs.append(E.Alias(col, name))
        return self._with(L.project_with_windows(tuple(exprs), self._plan))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = tuple(
            E.Alias(E.Col(n), new) if n == old else E.Col(n)
            for n in self.columns)
        return self._with(L.Project(exprs, self._plan))

    def drop(self, *names: str) -> "DataFrame":
        drop = set(names)
        exprs = tuple(E.Col(n) for n in self.columns if n not in drop)
        return self._with(L.Project(exprs, self._plan))

    def alias(self, name: str) -> "DataFrame":
        return self._with(L.SubqueryAlias(name, self._plan))

    def distinct(self) -> "DataFrame":
        return self._with(L.Distinct(self._plan))

    def dropDuplicates(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        if subset is None:
            return self.distinct()
        keys = tuple(E.Col(n) for n in subset)
        outs = tuple(
            E.Col(n) if n in set(subset) else E.Alias(E.First(E.Col(n)), n)
            for n in self.columns)
        return self._with(L.Aggregate(keys, outs, self._plan))

    drop_duplicates = dropDuplicates

    def limit(self, n: int) -> "DataFrame":
        return self._with(L.Limit(n, self._plan))

    def offset(self, n: int) -> "DataFrame":
        return self._with(L.Limit(1 << 62, self._plan, offset=n))

    def sort(self, *cols: ColumnOrName, ascending=None) -> "DataFrame":
        orders = [_order(c) for c in cols]
        if ascending is not None:
            flags = ([ascending] * len(orders)
                     if isinstance(ascending, bool) else list(ascending))
            orders = [
                E.SortOrder(o.child, asc, o.nulls_first)
                for o, asc in zip(orders, flags)
            ]
        return self._with(L.Sort(tuple(orders), self._plan))

    orderBy = sort

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.Union(self._plan, other._plan))

    unionAll = union

    def unionByName(self, other: "DataFrame") -> "DataFrame":
        reordered = other.select(*[E.Col(n) for n in self.columns])
        return self._with(L.Union(self._plan, reordered._plan))

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        return self._with(L.Sample(fraction, seed, self._plan))

    def repartition(self, num_partitions: int, *cols: ColumnOrName) -> "DataFrame":
        return self._with(L.Repartition(
            num_partitions, tuple(_c(c) for c in cols), self._plan))

    def coalesce(self, num_partitions: int) -> "DataFrame":
        return self._with(L.Repartition(num_partitions, (), self._plan))

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        how = {"outer": "full", "full_outer": "full", "fullouter": "full",
               "leftouter": "left", "left_outer": "left",
               "rightouter": "right", "right_outer": "right",
               "semi": "left_semi", "leftsemi": "left_semi",
               "anti": "left_anti", "leftanti": "left_anti"}.get(how, how)
        if how not in L.JOIN_TYPES:
            raise ValueError(f"unsupported join type {how!r}")
        if on is None:
            return self._with(L.Join(self._plan, other._plan, "cross", (), ()))
        if isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            lkeys = tuple(E.Col(n) for n in on)
            rkeys = tuple(E.Col(n) for n in on)
            joined = L.Join(self._plan, other._plan, how, lkeys, rkeys)
            if how in ("left_semi", "left_anti"):
                return self._with(joined)
            # name-based join keeps ONE copy of the join columns (Spark
            # semantics, Dataset.join(usingColumns)); the right-side copy
            # appears as 'name#2' after the Join.schema dedup
            on_set = set(on)
            right_start = len(self._plan.schema.names)
            joined_names = list(joined.schema.names)
            # the right copy of join column `k` is `k` or `k#2` post-dedup
            right_copy = {}
            for i, n in enumerate(joined_names):
                base = n[:-2] if n.endswith("#2") else n
                if i >= right_start and base in on_set:
                    right_copy[base] = n
            keep = []
            for i, n in enumerate(joined_names):
                if i >= right_start and n in right_copy.values():
                    continue
                if i < right_start and n in on_set and how in ("right", "full"):
                    # usingColumns full outer merges the key columns
                    keep.append(E.Alias(
                        E.Coalesce((E.Col(n), E.Col(right_copy[n]))), n))
                else:
                    keep.append(E.Col(n))
            return self._with(L.Project(tuple(keep), joined))
        # Column expression: extract equi conjuncts
        cond = on
        lnames = set(self._plan.schema.names)
        rnames = set(other._plan.schema.names)
        lkeys_l: List[E.Expression] = []
        rkeys_l: List[E.Expression] = []
        residual: List[E.Expression] = []
        from spark_tpu.plan.optimizer import split_conjuncts, combine_conjuncts

        for c in split_conjuncts(cond):
            if isinstance(c, E.Cmp) and c.op == "==":
                lr, rr = c.left.references(), c.right.references()
                if lr <= lnames and rr <= rnames:
                    lkeys_l.append(c.left)
                    rkeys_l.append(c.right)
                    continue
                if lr <= rnames and rr <= lnames:
                    lkeys_l.append(c.right)
                    rkeys_l.append(c.left)
                    continue
            residual.append(c)
        res = combine_conjuncts(residual) if residual else None
        if not lkeys_l and how == "inner":
            return self._with(L.Join(self._plan, other._plan, "cross", (), (),
                                     condition=res))
        return self._with(L.Join(self._plan, other._plan, how,
                                 tuple(lkeys_l), tuple(rkeys_l), res))

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.Join(self._plan, other._plan, "cross", (), ()))

    def groupBy(self, *cols: ColumnOrName) -> "GroupedData":
        return GroupedData(self, tuple(_c(c) for c in cols))

    groupby = groupBy

    def rollup(self, *cols: ColumnOrName) -> "GroupedData":
        """Hierarchical subtotals (reference: Dataset.rollup ->
        ResolveGroupingAnalytics/ExpandExec)."""
        return GroupedData(self, tuple(_c(c) for c in cols), "rollup")

    def cube(self, *cols: ColumnOrName) -> "GroupedData":
        """All subtotal combinations (reference: Dataset.cube)."""
        return GroupedData(self, tuple(_c(c) for c in cols), "cube")

    def agg(self, *exprs: E.Expression) -> "DataFrame":
        return self.groupBy().agg(*exprs)

    def __getitem__(self, item):
        if isinstance(item, str):
            return E.Col(item)
        if isinstance(item, E.Expression):
            return self.filter(item)
        raise TypeError(item)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._plan.schema:
            return E.Col(name)
        raise AttributeError(name)

    # ---- actions -----------------------------------------------------------

    def _execute(self):
        from spark_tpu import deadline, recovery, trace

        # root span when standalone; child when a connect server /
        # scheduler ticket already carries a trace for this query.
        # same shape for resilience context: an ambient deadline /
        # retry budget (scheduler ticket, connect request) is kept;
        # standalone, a default deadline is minted from
        # spark.tpu.deadline.defaultTimeoutS and a fresh per-query
        # retry budget is bound so every retry seam below draws from
        # ONE pool instead of multiplying per-layer caps
        conf = self._session.conf if self._session is not None else None
        with deadline.bind_default(conf), \
                recovery.bind_default_budget(conf), \
                trace.span("query.execute",
                           plan=type(self._plan).__name__):
            return self._execute_traced()

    def _execute_traced(self):
        from spark_tpu import metrics, trace

        if self._session is not None:
            self._session._ensure_active()
            # submit-time static analysis gate: no-op at the default
            # level=off; raises PlanAnalysisError at level=error when
            # the plan carries error-level diagnostics
            from spark_tpu.analysis import maybe_gate

            with trace.span("query.analysis"):
                maybe_gate(self._plan, self._session.conf)
        metrics.query_start(self._plan.node_string())
        ex = getattr(self._session, "mesh_executor", None) \
            if self._session is not None else None

        def run(plan, optimize=True):
            if ex is not None:
                return ex.execute_logical(plan, optimize)
            from spark_tpu.physical.planner import execute_logical

            return execute_logical(plan, optimize)

        def run_full(plan):
            """Engine run with the out-of-HBM chunking decision applied
            — also used to materialize cached plans so a cached big
            aggregate chunks instead of OOMing. Whole-batch OOM degrades
            through the chunked tier at a halved device budget
            (recovery.run_plan_with_oom_degradation) instead of
            failing."""
            if self._session is None:
                return run(plan)
            from spark_tpu.plan.optimizer import optimize as opt
            from spark_tpu.recovery import run_plan_with_oom_degradation

            lp = opt(plan)
            svc = self._session.compile_service
            if svc is not None:
                # compile-service routing: with background compile on,
                # serve through the chunked tier while the fused
                # executable compiles off-thread (byte-identical
                # either way)
                return svc.execute_plan(
                    lp, self._session.conf,
                    lambda p: run(p, optimize=False))
            return run_plan_with_oom_degradation(
                lp, self._session.conf,
                lambda p: run(p, optimize=False))

        plan = self._plan
        if self._session is not None:
            from spark_tpu.recovery import run_stage_with_recovery
            from spark_tpu.storage import pin_scope

            svc = self._session.compile_service
            if svc is not None:
                # journal the served plan (+ SQL text when this frame
                # came from session.sql) for the pre-warm replay
                svc.note_served(self._plan,
                                sql=getattr(self, "_sql_text", None))

            # pin_scope: every MemoryStore entry this query reads
            # (cached plans, auto-cached scans) is held against
            # eviction until the query finishes
            with trace.span("storage.pin"), pin_scope():
                with trace.span("mview.probe"):
                    plan = self._session.cache_manager.apply(
                        plan, run_full)
                # lineage recompute on transient environment failure
                # (reference: DAGScheduler.scala:1762 stage resubmission)
                out = run_stage_with_recovery(
                    lambda: run_full(plan), conf=self._session.conf,
                    label=type(self._plan).__name__)
                self._note_measured_bytes()
                return out
        return run_full(plan)

    def _note_measured_bytes(self) -> None:
        """Feed scheduler admission with the measured peak stage
        footprint of this query (the max stage_bytes event the mesh
        executor recorded since query_start), keyed by the RAW logical
        plan — the same plan shape scheduler.submit_query estimates
        before execution, so the next admission of this query uses
        measured, not static, bytes."""
        try:
            from spark_tpu import metrics
            from spark_tpu.scheduler import admission

            peak = max((int(e.get("bytes", 0))
                        for e in metrics.last_query()
                        if e.get("kind") == "stage_bytes"), default=0)
            admission.note_measured_bytes(self._plan, peak)
        except Exception:
            pass  # observability must never fail the query

    def collect(self) -> List[Row]:
        batch = self._execute()
        return [Row.from_dict(d) for d in batch.to_pylist()]

    @property
    def isStreaming(self) -> bool:
        from spark_tpu.streaming.execution import StreamingSource

        return bool(L.collect_nodes(self._plan, StreamingSource))

    @property
    def writeStream(self):
        from spark_tpu.streaming.readwriter import DataStreamWriter

        return DataStreamWriter(self)

    def withWatermark(self, col_name: str, delay) -> "DataFrame":
        from spark_tpu.streaming.readwriter import with_watermark

        return with_watermark(self, col_name, delay)

    def toPandas(self):
        return self._execute().to_pandas()

    @property
    def na(self):
        """Null handling (reference: DataFrameNaFunctions.scala)."""
        from spark_tpu.api.na_stat import DataFrameNaFunctions

        return DataFrameNaFunctions(self)

    @property
    def stat(self):
        """Statistics (reference: DataFrameStatFunctions.scala)."""
        from spark_tpu.api.na_stat import DataFrameStatFunctions

        return DataFrameStatFunctions(self)

    def dropna(self, how: str = "any", thresh=None, subset=None):
        return self.na.drop(how, thresh, subset)

    def fillna(self, value, subset=None):
        return self.na.fill(value, subset)

    def replace(self, to_replace, value=None, subset=None):
        return self.na.replace(to_replace, value, subset)

    def describe(self, *cols: str):
        from spark_tpu.api.na_stat import describe

        return describe(self, list(cols) or None)

    summary = describe

    def corr(self, col1: str, col2: str, method: str = "pearson") -> float:
        return self.stat.corr(col1, col2, method)

    def cov(self, col1: str, col2: str) -> float:
        return self.stat.cov(col1, col2)

    def approxQuantile(self, col, probabilities, relativeError=0.0):
        return self.stat.approxQuantile(col, probabilities, relativeError)

    def crosstab(self, col1: str, col2: str):
        return self.stat.crosstab(col1, col2)

    def freqItems(self, cols, support: float = 0.01):
        return self.stat.freqItems(cols, support)

    def sampleBy(self, col: str, fractions, seed: int = 42):
        return self.stat.sampleBy(col, fractions, seed)

    @property
    def rdd(self):
        """Bridge to the RDD tier: collected Rows, partitioned over the
        default parallelism (reference: Dataset.rdd — the escape hatch
        out of the columnar engine)."""
        return self._session.sparkContext.parallelize(self.collect())

    def toArrow(self):
        from spark_tpu.columnar.arrow import to_arrow

        return to_arrow(self._execute())

    def count(self) -> int:
        agg = L.Aggregate((), (E.Alias(E.Count(None), "count"),), self._plan)
        batch = self._with(agg)._execute()
        return int(batch.to_pylist()[0]["count"])

    def first(self) -> Optional[Row]:
        rows = self.limit(1).collect()
        return rows[0] if rows else None

    def head(self, n: int = 1):
        rows = self.limit(n).collect()
        return rows[0] if n == 1 and rows else rows

    def take(self, n: int) -> List[Row]:
        return self.limit(n).collect()

    def isEmpty(self) -> bool:
        return len(self.take(1)) == 0

    def show(self, n: int = 20, truncate: bool = True) -> None:
        rows = self.limit(n).collect()
        names = self.columns
        cells = [[_fmt(r[c], truncate) for c in names] for r in rows]
        widths = [
            max(len(str(nm)), *(len(row[i]) for row in cells)) if cells
            else len(str(nm))
            for i, nm in enumerate(names)
        ]
        sep = "+" + "+".join("-" * w for w in widths) + "+"
        print(sep)
        print("|" + "|".join(str(nm).ljust(w)
                             for nm, w in zip(names, widths)) + "|")
        print(sep)
        for row in cells:
            print("|" + "|".join(v.ljust(w) for v, w in zip(row, widths)) + "|")
        print(sep)

    @property
    def write(self):
        from spark_tpu.io.readwriter import DataFrameWriter

        return DataFrameWriter(self)

    def createOrReplaceTempView(self, name: str) -> None:
        self._session.catalog._register_view(name, self._plan)

    def cache(self) -> "DataFrame":
        """Mark this plan cached (lazy — materialized on first use and
        reused by ANY query containing it; reference: CacheManager.scala
        / InMemoryRelation)."""
        if self._session is not None:
            self._session.cache_manager.add(self._plan)
        return self

    persist = cache

    def unpersist(self) -> "DataFrame":
        if self._session is not None:
            self._session.cache_manager.drop(self._plan)
        return self

    def checkpoint(self, eager: bool = True) -> "DataFrame":
        """Durable checkpoint: Parquet under spark.checkpoint.dir,
        lineage truncated (reference: Dataset.checkpoint →
        ReliableCheckpointRDD)."""
        from spark_tpu.recovery import checkpoint_dataframe

        return checkpoint_dataframe(self, eager=eager)

    def localCheckpoint(self, eager: bool = True) -> "DataFrame":
        """In-memory lineage truncation (reference:
        Dataset.localCheckpoint → LocalCheckpointRDD)."""
        df = self.cache()
        if eager:
            df.count()
        return df


def _fmt(v, truncate: bool) -> str:
    s = "NULL" if v is None else str(v)
    if truncate and len(s) > 20:
        s = s[:17] + "..."
    return s


class GroupedData:
    """Result of groupBy/rollup/cube (reference:
    sql/core/.../RelationalGroupedDataset.scala)."""

    def __init__(self, df: DataFrame, keys: Tuple[E.Expression, ...],
                 mode: str = "groupby"):
        self._df = df
        self._keys = keys
        self._mode = mode

    def agg(self, *exprs: E.Expression) -> DataFrame:
        outs = tuple(self._keys) + tuple(exprs)
        if self._mode != "groupby":
            from spark_tpu.plan.grouping import (cube_sets,
                                                 grouping_sets_aggregate,
                                                 rollup_sets)

            sets = (rollup_sets(len(self._keys))
                    if self._mode == "rollup"
                    else cube_sets(len(self._keys)))
            plan, _ = grouping_sets_aggregate(
                self._df._plan, self._keys, sets, outs)
            return self._df._with(plan)
        return self._df._with(
            L.Aggregate(self._keys, outs, self._df._plan))

    def _simple(self, fn, cols: Tuple[str, ...]) -> DataFrame:
        names = cols or tuple(
            n for n in self._df.columns
            if self._df.schema.field(n).dtype.is_numeric
            and not any(k.name == n for k in self._keys))
        aggs = tuple(E.Alias(fn(E.Col(n)), f"{fn.__name__.lower()}({n})")
                     for n in names)
        return self.agg(*aggs)

    def sum(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple(E.Sum, cols)

    def avg(self, *cols: str) -> DataFrame:
        return self._simple(E.Avg, cols)

    mean = avg

    def min(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple(E.Min, cols)

    def max(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple(E.Max, cols)

    def applyInPandasWithState(self, func, outputStructType,
                               stateStructType=None,
                               outputMode: str = "append",
                               timeoutConf: str = "NoTimeout") -> DataFrame:
        """Arbitrary stateful per-group streaming transform (reference:
        python/pyspark/sql/pandas/group_ops.py applyInPandasWithState →
        FlatMapGroupsWithStateExec). ``func(key_tuple, pandas_df,
        GroupState) -> pandas_df``; start the returned DataFrame with
        writeStream. ``stateStructType`` accepted for surface parity
        (state is pickled whole). ``timeoutConf='ProcessingTimeTimeout'``
        enables state.setTimeoutDuration(ms): groups whose deadline
        passes with no new data are invoked with an empty frame and
        state.hasTimedOut=True (reference:
        FlatMapGroupsWithStateExec.scala:373 timeout processing)."""
        from spark_tpu.streaming.groups import FlatMapGroupsWithState
        from spark_tpu.types import Schema, parse_ddl_schema

        out_schema = (outputStructType
                      if isinstance(outputStructType, Schema)
                      else parse_ddl_schema(outputStructType))
        key_names = []
        for k in self._keys:
            inner = E.strip_alias(k)
            if not isinstance(inner, E.Col):
                raise NotImplementedError(
                    "applyInPandasWithState keys must be plain columns")
            key_names.append(inner.col_name)
        if timeoutConf not in ("NoTimeout", "ProcessingTimeTimeout"):
            raise NotImplementedError(
                "timeoutConf: NoTimeout | ProcessingTimeTimeout "
                "(event-time timeouts not implemented)")
        node = FlatMapGroupsWithState(
            tuple(key_names), func, out_schema, self._df._plan,
            timeout_conf=timeoutConf)
        return DataFrame(self._df._session, node)

    def count(self) -> DataFrame:
        return self.agg(E.Alias(E.Count(None), "count"))

"""User-defined functions (reference:
sql/core/.../execution/python/ArrowPythonRunner.scala,
ArrowEvalPythonExec.scala, python/pyspark/sql/udf.py).

Two tiers, mirroring the reference's pandas-UDF split but TPU-first:

- **jax UDFs** (``@F.udf`` default): the function receives jnp arrays
  and returns one; it traces INTO the fused stage program like any
  built-in expression — zero interpreter involvement at execution time.
  This is the preferred tier: the reference pays a JVM<->Python socket
  round trip per batch (PythonRunner.scala:126), here the UDF *is* XLA.
- **arrow UDFs** (``@F.arrow_udf``): the function receives/returns
  pyarrow arrays and runs host-side per batch — for logic that cannot
  trace (arbitrary Python). The column round-trips device->host->device
  exactly once per batch, like the reference's Arrow stream to the
  Python worker, but in-process (no fork server, no sockets).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.types import DataType


@dataclass(eq=False, frozen=True)
class JaxUdf(E.Expression):
    """Traceable UDF: fn(*jnp_arrays) -> jnp_array. Nulls: the result is
    NULL where any input is NULL (Spark's null-intolerant default)."""

    fn: Callable
    return_type: DataType
    args: Tuple[E.Expression, ...]
    fn_name: str = "udf"

    def children(self):
        return self.args

    def data_type(self, schema):
        return self.return_type

    @property
    def name(self):
        return f"{self.fn_name}({', '.join(str(a) for a in self.args)})"

    def __str__(self):
        return self.name


@dataclass(eq=False, frozen=True)
class ArrowUdf(E.Expression):
    """Host-side UDF over pyarrow arrays; evaluated eagerly between
    stages (forces a stage break like a blocking operator)."""

    fn: Callable
    return_type: DataType
    args: Tuple[E.Expression, ...]
    fn_name: str = "arrow_udf"
    blocks_trace = True

    def children(self):
        return self.args

    def data_type(self, schema):
        return self.return_type

    @property
    def name(self):
        return f"{self.fn_name}({', '.join(str(a) for a in self.args)})"

    def __str__(self):
        return self.name


def udf(fn: Optional[Callable] = None, returnType: DataType = T.FLOAT64):
    """Decorator/factory for jax UDFs:

        @F.udf(returnType=T.FLOAT64)
        def my_fn(x, y):            # jnp arrays in, jnp array out
            return jnp.sqrt(x) + y

        df.select(my_fn("a", "b"))
    """

    def wrap(f: Callable):
        def build(*cols):
            args = tuple(
                c if isinstance(c, E.Expression) else E.Col(c)
                for c in cols)
            return JaxUdf(f, returnType, args, f.__name__)

        build.__name__ = f.__name__
        return build

    if fn is not None:
        return wrap(fn)
    return wrap


def arrow_udf(fn: Optional[Callable] = None,
              returnType: DataType = T.FLOAT64):
    """Decorator/factory for host-side pyarrow UDFs (the escape hatch
    for untraceable Python)."""

    def wrap(f: Callable):
        def build(*cols):
            args = tuple(
                c if isinstance(c, E.Expression) else E.Col(c)
                for c in cols)
            return ArrowUdf(f, returnType, args, f.__name__)

        build.__name__ = f.__name__
        return build

    if fn is not None:
        return wrap(fn)
    return wrap

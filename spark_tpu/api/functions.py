"""Column functions — the pyspark.sql.functions surface (reference:
sql/core/src/main/scala/org/apache/spark/sql/functions.scala,
python/pyspark/sql/functions/). Columns ARE expression trees here
(no Py4J indirection): every function builds an expr/expressions node.
"""

from __future__ import annotations

from typing import Any, Optional, Union

from spark_tpu.expr import expressions as E
from spark_tpu import types as T

Column = E.Expression
ColumnOrName = Union[Column, str]


def _c(c: ColumnOrName) -> Column:
    return c if isinstance(c, E.Expression) else E.Col(c)


def col(name: str) -> Column:
    return E.Col(name)


column = col


def lit(value: Any) -> Column:
    if isinstance(value, E.Expression):
        return value
    return E.Literal(value)


def expr(sql_text: str) -> Column:
    """Parse a SQL expression string (reference: functions.expr)."""
    from spark_tpu.sql.parser import parse_expression

    return parse_expression(sql_text)


def window(c: ColumnOrName, width: int) -> Column:
    """Tumbling event-time window of ``width`` time units; the produced
    column is the window START (reference: functions.window)."""
    return E.TumblingWindow(_c(c), int(width))


def session_window(c: ColumnOrName, gap: int) -> Column:
    """Gap-based session window grouping for streaming aggregation; the
    produced column is the merged session START (reference:
    functions.session_window, MergingSessionsExec)."""
    return E.SessionWindow(_c(c), int(gap))


# ---- window functions ------------------------------------------------------


def row_number() -> Column:
    return E.RowNumber()


def rank() -> Column:
    return E.Rank(dense=False)


def dense_rank() -> Column:
    return E.Rank(dense=True)


def ntile(n: int) -> Column:
    return E.NTile(n)


def lag(c: ColumnOrName, offset: int = 1, default: Any = None) -> Column:
    d = None if default is None else lit(default)
    return E.LagLead(_c(c), offset, d, lead=False)


def lead(c: ColumnOrName, offset: int = 1, default: Any = None) -> Column:
    d = None if default is None else lit(default)
    return E.LagLead(_c(c), offset, d, lead=True)


from spark_tpu.api.udf import arrow_udf, udf  # noqa: E402,F401

# ---- aggregates ------------------------------------------------------------


def sum(c: ColumnOrName) -> Column:  # noqa: A001
    return E.Sum(_c(c))


def avg(c: ColumnOrName) -> Column:
    return E.Avg(_c(c))


mean = avg


def count(c: ColumnOrName = "*") -> Column:
    if isinstance(c, str) and c == "*":
        return E.Count(None)
    return E.Count(_c(c))


def countDistinct(c: ColumnOrName) -> Column:
    return E.Count(_c(c), distinct=True)


count_distinct = countDistinct


def sumDistinct(c: ColumnOrName) -> Column:
    return E.Sum(_c(c), distinct=True)


sum_distinct = sumDistinct


def avgDistinct(c: ColumnOrName) -> Column:
    return E.Avg(_c(c), distinct=True)


avg_distinct = avgDistinct


def min(c: ColumnOrName) -> Column:  # noqa: A001
    return E.Min(_c(c))


def max(c: ColumnOrName) -> Column:  # noqa: A001
    return E.Max(_c(c))


def first(c: ColumnOrName, ignorenulls: bool = False) -> Column:
    return E.First(_c(c), ignorenulls)


def stddev(c: ColumnOrName) -> Column:
    return E.StddevVariance("stddev_samp", _c(c))


stddev_samp = stddev


def stddev_pop(c: ColumnOrName) -> Column:
    return E.StddevVariance("stddev_pop", _c(c))


def variance(c: ColumnOrName) -> Column:
    return E.StddevVariance("var_samp", _c(c))


var_samp = variance


def var_pop(c: ColumnOrName) -> Column:
    return E.StddevVariance("var_pop", _c(c))


# ---- scalar ----------------------------------------------------------------


def abs(c: ColumnOrName) -> Column:  # noqa: A001
    return E.Abs(_c(c))


def coalesce(*cols: ColumnOrName) -> Column:
    return E.Coalesce(tuple(_c(c) for c in cols))


def isnull(c: ColumnOrName) -> Column:
    return E.IsNull(_c(c))


def isnotnull(c: ColumnOrName) -> Column:
    return E.Not(E.IsNull(_c(c)))


def when(condition: Column, value: Any) -> E.Case:
    """CASE builder; chain .when(...).otherwise(...) (an unterminated
    chain is a valid CASE with NULL for unmatched rows)."""
    return E.Case(((condition, lit(value)),), None)


def floor(c: ColumnOrName) -> Column:
    return E.UnaryMath("floor", _c(c))


def ceil(c: ColumnOrName) -> Column:
    return E.UnaryMath("ceil", _c(c))


def sqrt(c: ColumnOrName) -> Column:
    return E.UnaryMath("sqrt", _c(c))


def exp(c: ColumnOrName) -> Column:
    return E.UnaryMath("exp", _c(c))


def log(c: ColumnOrName) -> Column:
    return E.UnaryMath("ln", _c(c))


ln = log


def log10(c: ColumnOrName) -> Column:
    return E.UnaryMath("log10", _c(c))


def signum(c: ColumnOrName) -> Column:
    return E.UnaryMath("sign", _c(c))


def round(c: ColumnOrName, scale: int = 0) -> Column:  # noqa: A001
    return E.Round(_c(c), scale)


def pow(a: ColumnOrName, b) -> Column:  # noqa: A001
    return E.Pow(_c(a), lit(b) if not isinstance(b, E.Expression) else b)


power = pow


def approx_count_distinct(c: ColumnOrName, rsd: float = 0.05) -> Column:
    """Distinct-count estimate (reference: approx_count_distinct /
    HyperLogLog++). Implemented EXACTLY via the DISTINCT-aggregate
    dedup kernel — a valid estimator with rsd=0; the sketch module
    (spark_tpu.sketch) provides mergeable CMS/Bloom structures."""
    return E.Count(_c(c), distinct=True)


# ---- string ----------------------------------------------------------------


def substring(c: ColumnOrName, pos: int, length: int) -> Column:
    return E.Substring(_c(c), pos, length)


def startswith(c: ColumnOrName, prefix: str) -> Column:
    return E.StringPredicate("startswith", _c(c), prefix)


def endswith(c: ColumnOrName, suffix: str) -> Column:
    return E.StringPredicate("endswith", _c(c), suffix)


def contains(c: ColumnOrName, needle: str) -> Column:
    return E.StringPredicate("contains", _c(c), needle)


def like(c: ColumnOrName, pattern: str) -> Column:
    return E.Like(_c(c), pattern)


def upper(c: ColumnOrName) -> Column:
    return E.StringTransform("upper", _c(c))


def lower(c: ColumnOrName) -> Column:
    return E.StringTransform("lower", _c(c))


def trim(c: ColumnOrName) -> Column:
    return E.StringTransform("trim", _c(c))


def ltrim(c: ColumnOrName) -> Column:
    return E.StringTransform("ltrim", _c(c))


def rtrim(c: ColumnOrName) -> Column:
    return E.StringTransform("rtrim", _c(c))


def length(c: ColumnOrName) -> Column:
    return E.StrLength(_c(c))


def regexp_extract(c: ColumnOrName, pattern: str, idx: int = 1) -> Column:
    return E.RegexpExtract(_c(c), pattern, idx)


def regexp_replace(c: ColumnOrName, pattern: str,
                   replacement: str) -> Column:
    return E.RegexpReplace(_c(c), pattern, replacement)


def rlike(c: ColumnOrName, pattern: str) -> Column:
    return E.RegexpLike(_c(c), pattern)


def concat(*cols: ColumnOrName) -> Column:
    return E.Concat(tuple(_c(c) for c in cols))


# ---- temporal --------------------------------------------------------------


def year(c: ColumnOrName) -> Column:
    return E.ExtractDatePart("year", _c(c))


def month(c: ColumnOrName) -> Column:
    return E.ExtractDatePart("month", _c(c))


def dayofmonth(c: ColumnOrName) -> Column:
    return E.ExtractDatePart("day", _c(c))


def add_months(c: ColumnOrName, months: int) -> Column:
    return E.AddMonths(_c(c), months)


def date_add(c: ColumnOrName, days: int) -> Column:
    return E.Arith("+", _c(c), E.Literal(days))


def date_sub(c: ColumnOrName, days: int) -> Column:
    return E.Arith("-", _c(c), E.Literal(days))


def datediff(end: ColumnOrName, start: ColumnOrName) -> Column:
    return E.Arith("-", _c(end), _c(start))


def to_date(c: ColumnOrName) -> Column:
    return E.Cast(_c(c), T.DATE)


def date_trunc(unit: str, c: ColumnOrName) -> Column:
    return E.DateTrunc(unit.lower(), _c(c))


def last_day(c: ColumnOrName) -> Column:
    return E.LastDay(_c(c))


# ---- conditional / comparison breadth --------------------------------------


def greatest(*cols: ColumnOrName) -> Column:
    """Largest non-null value (reference: conditionalExpressions.scala
    Greatest — nulls are skipped, not propagated)."""
    out = _c(cols[0])
    for c in cols[1:]:
        b = _c(c)
        out = E.Case(((E.IsNull(out), b),
                      (E.Or(E.IsNull(b), E.Cmp(">=", out, b)), out)), b)
    return out


def least(*cols: ColumnOrName) -> Column:
    out = _c(cols[0])
    for c in cols[1:]:
        b = _c(c)
        out = E.Case(((E.IsNull(out), b),
                      (E.Or(E.IsNull(b), E.Cmp("<=", out, b)), out)), b)
    return out


def ifnull(a: ColumnOrName, b: ColumnOrName) -> Column:
    return E.Coalesce((_c(a), _c(b)))


nvl = ifnull


def nvl2(a: ColumnOrName, b: ColumnOrName, c: ColumnOrName) -> Column:
    return E.Case(((E.Not(E.IsNull(_c(a))), _c(b)),), _c(c))


def nullif(a: ColumnOrName, b: ColumnOrName) -> Column:
    x = _c(a)
    return E.Case(((E.Cmp("==", x, _c(b)), E.NullOf(x)),), x)


def negative(c: ColumnOrName) -> Column:
    return E.Neg(_c(c))


def positive(c: ColumnOrName) -> Column:
    return _c(c)


# ---- math breadth -----------------------------------------------------------


def log2(c: ColumnOrName) -> Column:
    import math as _math

    return E.Arith("/", E.UnaryMath("ln", _c(c)),
                   E.Literal(_math.log(2.0)))


def degrees(c: ColumnOrName) -> Column:
    import math as _math

    return E.Arith("*", _c(c), E.Literal(180.0 / _math.pi))


def radians(c: ColumnOrName) -> Column:
    import math as _math

    return E.Arith("*", _c(c), E.Literal(_math.pi / 180.0))


def pmod(a: ColumnOrName, b) -> Column:
    bb = b if isinstance(b, E.Expression) else E.Literal(b)
    inner = E.Arith("%", _c(a), bb)
    return E.Arith("%", E.Arith("+", inner, bb), bb)


# ---- datetime breadth -------------------------------------------------------


def quarter(c: ColumnOrName) -> Column:
    m = E.ExtractDatePart("month", _c(c))
    return E.UnaryMath("floor", E.Arith(
        "/", E.Arith("+", m, E.Literal(2)), E.Literal(3)))


def dayofweek(c: ColumnOrName) -> Column:
    """1 = Sunday .. 7 = Saturday (reference: datetimeExpressions.scala
    DayOfWeek). 1970-01-01 (day 0) was a Thursday = 5."""
    days = E.Cast(_c(c), T.INT64)
    return E.Arith("+", E.Arith("%", E.Arith("+", days, E.Literal(4)),
                                E.Literal(7)), E.Literal(1))


def weekday(c: ColumnOrName) -> Column:
    """0 = Monday .. 6 = Sunday."""
    days = E.Cast(_c(c), T.INT64)
    return E.Arith("%", E.Arith("+", days, E.Literal(3)), E.Literal(7))


def dayofyear(c: ColumnOrName) -> Column:
    x = _c(c)
    return E.Arith("+", E.Arith(
        "-", E.Cast(x, T.INT64),
        E.Cast(E.DateTrunc("year", x), T.INT64)), E.Literal(1))


def months_between(end: ColumnOrName, start: ColumnOrName) -> Column:
    """Fractional months (reference: datetimeExpressions.scala
    MonthsBetween): whole-month diff when both dates are the same day of
    month or both month-ends, else + (day1-day2)/31."""
    a, b = _c(end), _c(start)
    whole = E.Arith("-", E.Arith(
        "+", E.Arith("*", E.ExtractDatePart("year", a), E.Literal(12)),
        E.ExtractDatePart("month", a)), E.Arith(
        "+", E.Arith("*", E.ExtractDatePart("year", b), E.Literal(12)),
        E.ExtractDatePart("month", b)))
    da = E.ExtractDatePart("day", a)
    db = E.ExtractDatePart("day", b)
    both_end = E.And(E.Cmp("==", a, E.LastDay(a)),
                     E.Cmp("==", b, E.LastDay(b)))
    same_day = E.Cmp("==", da, db)
    frac = E.Arith("/", E.Cast(E.Arith("-", da, db), T.FLOAT64),
                   E.Literal(31.0))
    return E.Case(((E.Or(same_day, both_end),
                    E.Cast(whole, T.FLOAT64)),),
                  E.Arith("+", E.Cast(whole, T.FLOAT64), frac))


def current_date() -> Column:
    import datetime as _dt

    return E.Literal(_dt.date.today())


def hour(c: ColumnOrName) -> Column:
    us = E.Cast(_c(c), T.INT64)
    day_us = E.Literal(86_400_000_000)
    in_day = pmod(E.Arith("%", us, day_us), day_us)
    return E.UnaryMath("floor", E.Arith(
        "/", in_day, E.Literal(3_600_000_000)))


def minute(c: ColumnOrName) -> Column:
    us = E.Cast(_c(c), T.INT64)
    day_us = E.Literal(86_400_000_000)
    in_day = pmod(E.Arith("%", us, day_us), day_us)
    return E.Arith("%", E.UnaryMath("floor", E.Arith(
        "/", in_day, E.Literal(60_000_000))), E.Literal(60))


def second(c: ColumnOrName) -> Column:
    us = E.Cast(_c(c), T.INT64)
    day_us = E.Literal(86_400_000_000)
    in_day = pmod(E.Arith("%", us, day_us), day_us)
    return E.Arith("%", E.UnaryMath("floor", E.Arith(
        "/", in_day, E.Literal(1_000_000))), E.Literal(60))


# ---- string breadth ---------------------------------------------------------


def initcap(c: ColumnOrName) -> Column:
    return E.StringTransform("initcap", _c(c))


def reverse(c: ColumnOrName) -> Column:
    return E.StringTransform("reverse", _c(c))


def repeat(c: ColumnOrName, n: int) -> Column:
    return E.StringTransform("repeat", _c(c), (int(n),))


def lpad(c: ColumnOrName, length: int, pad: str = " ") -> Column:
    return E.StringTransform("lpad", _c(c), (int(length), str(pad)))


def rpad(c: ColumnOrName, length: int, pad: str = " ") -> Column:
    return E.StringTransform("rpad", _c(c), (int(length), str(pad)))


def translate(c: ColumnOrName, matching: str, replace: str) -> Column:
    return E.StringTransform("translate", _c(c), (matching, replace))


def concat_ws(sep: str, *cols: ColumnOrName) -> Column:
    return E.ConcatWs(str(sep), tuple(_c(c) for c in cols))


# ---- ordering --------------------------------------------------------------


def asc(c: ColumnOrName) -> Column:
    return E.SortOrder(_c(c), ascending=True)


def desc(c: ColumnOrName) -> Column:
    return E.SortOrder(_c(c), ascending=False)


def asc_nulls_last(c: ColumnOrName) -> Column:
    return E.SortOrder(_c(c), ascending=True, nulls_first=False)


def desc_nulls_first(c: ColumnOrName) -> Column:
    return E.SortOrder(_c(c), ascending=False, nulls_first=True)


# ---- complex types (arrays / generators) ------------------------------------
# Reference: collectionOperations.scala, complexTypeCreator.scala,
# generators.scala / GenerateExec.scala:1.


def array(*cols: ColumnOrName) -> Column:
    return E.MakeArray(tuple(_c(c) for c in cols))


def split(c: ColumnOrName, delim: str) -> Column:
    return E.Split(_c(c), str(delim))


def size(c: ColumnOrName) -> Column:
    return E.Size(_c(c))


def element_at(c: ColumnOrName, index) -> Column:
    ix = index if isinstance(index, E.Expression) else E.Literal(int(index))
    return E.ElementAt(_c(c), ix)


def array_contains(c: ColumnOrName, value) -> Column:
    v = value if isinstance(value, E.Expression) else E.Literal(value)
    return E.ArrayContains(_c(c), v)


def create_map(*cols) -> Column:
    """map(k1, v1, k2, v2, ...) — legal at the top of a projection (the
    Project expands it into '#keys'/'#vals' components, types.MapType;
    reference: functions.map / CreateMap)."""
    return E.CreateMap(tuple(_c(c) for c in cols))


def map_from_arrays(keys: ColumnOrName, vals: ColumnOrName) -> Column:
    return E.MapFromArrays(_c(keys), _c(vals))


def _map_base(c: ColumnOrName) -> str:
    if isinstance(c, str):
        name = c
    elif isinstance(c, E.Col):
        name = c.col_name
    else:
        raise TypeError(
            "map accessors need a map column reference or an inline "
            "map() expression (maps are decomposed into component "
            "columns — types.MapType)")
    base = T.map_base_name(name)
    return base if base is not None else name


def map_keys(c: ColumnOrName) -> Column:
    if isinstance(c, E.CreateMap):  # inline map(): pure rewrite
        return E.MakeArray(c.args[::2])
    if isinstance(c, E.MapFromArrays):
        return c.keys
    return E.Col(T.map_keys_col(_map_base(c)))


def map_values(c: ColumnOrName) -> Column:
    if isinstance(c, E.CreateMap):
        return E.MakeArray(c.args[1::2])
    if isinstance(c, E.MapFromArrays):
        return c.vals
    return E.Col(T.map_vals_col(_map_base(c)))


def map_contains_key(c: ColumnOrName, key) -> Column:
    return E.ArrayContains(map_keys(c), lit(key))


def _lambda(fn) -> "E.Lambda":
    """Python callable -> Lambda node: the callable's own parameter
    names become the bound variables (pyspark's LambdaFunction shape,
    reference: higherOrderFunctions.scala)."""
    import inspect

    params = tuple(inspect.signature(fn).parameters)
    return E.Lambda(params, _c(fn(*[E.Col(p) for p in params])))


def transform(c: ColumnOrName, fn) -> Column:
    """transform(array, x -> ...) / (x, i) -> ... (reference:
    functions.transform, ArrayTransform)."""
    return E.HigherOrder("transform", _c(c), _lambda(fn))


def filter(c: ColumnOrName, fn) -> Column:  # noqa: A001
    return E.HigherOrder("filter", _c(c), _lambda(fn))


def exists(c: ColumnOrName, fn) -> Column:
    return E.HigherOrder("exists", _c(c), _lambda(fn))


def forall(c: ColumnOrName, fn) -> Column:
    return E.HigherOrder("forall", _c(c), _lambda(fn))


def aggregate(c: ColumnOrName, zero, merge, finish=None) -> Column:
    """aggregate(array, zero, (acc, x) -> ..., [acc -> ...]) (reference:
    functions.aggregate, ArrayAggregate)."""
    return E.HigherOrder(
        "aggregate", _c(c), _lambda(merge), lit(zero),
        None if finish is None else _lambda(finish))


def collect_list(c: ColumnOrName) -> Column:
    return E.Collect(_c(c))


def collect_set(c: ColumnOrName) -> Column:
    return E.Collect(_c(c), unique=True)


array_agg = collect_list


def percentile_approx(c: ColumnOrName, percentage: float,
                      accuracy: int = 10000) -> Column:
    """Value at the given percentile. The TPU build computes the EXACT
    element (accuracy accepted for API parity, unused) — see
    expr.expressions.Percentile."""
    return E.Percentile(_c(c), float(percentage))


approx_percentile = percentile_approx


def percentile(c: ColumnOrName, percentage: float) -> Column:
    return E.Percentile(_c(c), float(percentage), interpolate=True)


def median(c: ColumnOrName) -> Column:
    return E.Percentile(_c(c), 0.5, interpolate=True)


def explode(c: ColumnOrName) -> Column:
    return E.Explode(_c(c))


def posexplode(c: ColumnOrName) -> Column:
    return E.Explode(_c(c), with_position=True)


def replace(c: ColumnOrName, find: str, replacement: str) -> Column:
    """Literal substring replacement (reference: StringReplace)."""
    import re as _re

    # Only backslash is special in a re.sub replacement template (it
    # introduces \1 backreferences and \g<> groups); escape it so the
    # replacement is inserted literally. re.escape would be wrong here:
    # it targets pattern syntax and would leak extra backslashes.
    return E.RegexpReplace(_c(c), _re.escape(str(find)),
                           str(replacement).replace("\\", "\\\\"))

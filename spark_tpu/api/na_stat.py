"""DataFrameNaFunctions + DataFrameStatFunctions.

Reference: sql/core/.../DataFrameNaFunctions.scala (drop/fill/replace)
and DataFrameStatFunctions.scala (approxQuantile:75, corr, cov,
crosstab, freqItems, sampleBy). Everything lowers to engine expressions
(IsNull/Coalesce/Case aggregates) so it fuses into the same jitted
stages; only result-shaping (crosstab pivot) happens host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L


def _is_numeric(dtype) -> bool:
    return isinstance(dtype, (T.IntegralType, T.FractionalType))


class DataFrameNaFunctions:
    """df.na — null handling (reference: DataFrameNaFunctions.scala)."""

    def __init__(self, df):
        self._df = df

    def drop(self, how: str = "any",
             thresh: Optional[int] = None,
             subset: Optional[Sequence[str]] = None):
        df = self._df
        cols = list(subset) if subset is not None else df.columns
        nullable = [c for c in cols if df.schema.field(c).nullable]
        if not nullable:
            return df
        not_nulls = [E.Not(E.IsNull(E.Col(c))) for c in nullable]
        if thresh is not None:
            # keep rows with >= thresh non-null values among cols
            counts = [E.Case(((nn, E.Literal(1)),), E.Literal(0))
                      for nn in not_nulls]
            # non-subset columns always count as non-null
            base = len(cols) - len(nullable)
            total: E.Expression = E.Literal(base)
            for c in counts:
                total = E.Arith("+", total, c)
            return df.filter(E.Cmp(">=", total, E.Literal(int(thresh))))
        combine = E.And if how == "any" else E.Or
        cond = not_nulls[0]
        for nn in not_nulls[1:]:
            cond = combine(cond, nn)
        return df.filter(cond)

    def fill(self, value: Union[int, float, str, bool, dict],
             subset: Optional[Sequence[str]] = None):
        df = self._df
        if isinstance(value, dict):
            mapping: Dict[str, object] = dict(value)
        else:
            cols = list(subset) if subset is not None else df.columns
            mapping = {}
            for c in cols:
                f = df.schema.field(c)
                if isinstance(value, str) and isinstance(f.dtype, T.StringType):
                    mapping[c] = value
                elif isinstance(value, bool):
                    if isinstance(f.dtype, T.BooleanType):
                        mapping[c] = value
                elif isinstance(value, (int, float)) and _is_numeric(f.dtype):
                    mapping[c] = value
        out = df
        for c, v in mapping.items():
            if c not in df.columns or not df.schema.field(c).nullable:
                continue
            out = out.withColumn(
                c, E.Coalesce((E.Col(c), E.Literal(v))))
        return out

    def replace(self, to_replace, value=None,
                subset: Optional[Sequence[str]] = None):
        df = self._df
        if isinstance(to_replace, dict):
            pairs = list(to_replace.items())
        else:
            olds = to_replace if isinstance(to_replace, (list, tuple)) \
                else [to_replace]
            news = value if isinstance(value, (list, tuple)) \
                else [value] * len(olds)
            pairs = list(zip(olds, news))
        cols = list(subset) if subset is not None else df.columns
        out = df
        for c in cols:
            f = df.schema.field(c)
            branches = []
            for old, new in pairs:
                type_ok = (isinstance(old, str)
                           and isinstance(f.dtype, T.StringType)) or \
                    (isinstance(old, (int, float))
                     and not isinstance(old, bool)
                     and _is_numeric(f.dtype))
                if type_ok:
                    branches.append((E.Cmp("==", E.Col(c), E.Literal(old)),
                                     E.Literal(new, f.dtype)))
            if branches:
                out = out.withColumn(
                    c, E.Case(tuple(branches), E.Col(c)))
        return out


class DataFrameStatFunctions:
    """df.stat (reference: DataFrameStatFunctions.scala)."""

    def __init__(self, df):
        self._df = df

    def approxQuantile(self, col: Union[str, Sequence[str]],
                       probabilities: Sequence[float],
                       relativeError: float = 0.0) -> List:
        """Quantiles per column. Computed exactly (device sort + host
        pick), which trivially satisfies any requested error bound —
        the reference's Greenwald-Khanna sketch exists to avoid a JVM
        shuffle, which this engine doesn't pay."""
        cols = [col] if isinstance(col, str) else list(col)
        out = []
        for c in cols:
            import numpy as np

            vals = np.asarray(
                [r[c] for r in self._df.select(c).collect()
                 if r[c] is not None], dtype=np.float64)
            if vals.size == 0:
                out.append([float("nan")] * len(probabilities))
                continue
            vals.sort()
            qs = []
            for p in probabilities:
                idx = min(int(p * vals.size), vals.size - 1)
                qs.append(float(vals[idx]))
            out.append(qs)
        return out[0] if isinstance(col, str) else out

    def corr(self, col1: str, col2: str, method: str = "pearson") -> float:
        if method != "pearson":
            raise ValueError("only pearson correlation is supported "
                             "(reference: StatFunctions.pearsonCorrelation)")
        import math

        s = self._moments(col1, col2)
        n = s["n"]
        if n == 0:
            return float("nan")
        cov = s["xy"] / n - (s["x"] / n) * (s["y"] / n)
        vx = s["xx"] / n - (s["x"] / n) ** 2
        vy = s["yy"] / n - (s["y"] / n) ** 2
        denom = math.sqrt(vx * vy)
        return cov / denom if denom else float("nan")

    def cov(self, col1: str, col2: str) -> float:
        s = self._moments(col1, col2)
        n = s["n"]
        if n <= 1:
            return float("nan")
        # sample covariance (reference: StatFunctions.calculateCov)
        return (s["xy"] - s["x"] * s["y"] / n) / (n - 1)

    def _moments(self, c1: str, c2: str) -> Dict[str, float]:
        # pairwise deletion (reference: StatFunctions computes co-moments
        # over rows where BOTH columns are present): per-column null
        # skipping would mix Sum(x) over x-rows with Count over xy-rows
        # and silently corrupt corr/cov when either column has nulls
        df = self._df.filter(E.And(E.Not(E.IsNull(E.Col(c1))),
                                   E.Not(E.IsNull(E.Col(c2)))))
        x = E.Cast(E.Col(c1), T.FLOAT64)
        y = E.Cast(E.Col(c2), T.FLOAT64)
        agg = df.agg(
            E.Alias(E.Sum(x), "x"), E.Alias(E.Sum(y), "y"),
            E.Alias(E.Sum(x * y), "xy"),
            E.Alias(E.Sum(x * x), "xx"), E.Alias(E.Sum(y * y), "yy"),
            E.Alias(E.Count(x), "n"))
        r = agg.collect()[0]
        return {k: (float(r[k]) if r[k] is not None else 0.0)
                for k in ("x", "y", "xy", "xx", "yy", "n")}

    def crosstab(self, col1: str, col2: str):
        """Contingency table as a DataFrame: one row per distinct col1,
        one column per distinct col2 (reference: StatFunctions.crossTabulate)."""
        df = self._df
        rows = df.groupBy(col1, col2).count().collect()
        import pyarrow as pa

        row_keys = sorted({str(r[col1]) for r in rows})
        col_keys = sorted({str(r[col2]) for r in rows})
        counts = {(str(r[col1]), str(r[col2])): r["count"] for r in rows}
        data = {f"{col1}_{col2}": row_keys}
        for ck in col_keys:
            data[ck] = [counts.get((rk, ck), 0) for rk in row_keys]
        return df.sparkSession.createDataFrame(pa.table(data))

    def freqItems(self, cols: Sequence[str], support: float = 0.01):
        """Columns of frequent items (appearing in >= support fraction
        of rows). Exact counting via group-by (the reference uses a
        lossy counting sketch for one-pass JVM streaming). Deviation:
        the engine has no array columns yet, so each ``{col}_freqItems``
        cell is the item list serialized as a JSON string."""
        import json

        import pyarrow as pa

        df = self._df
        total = df.count()
        floor = max(1.0, total * support)  # frequency >= support * n
        data = {}
        for c in cols:
            counted = df.groupBy(c).count().collect()
            items = sorted((r[c] for r in counted
                            if r["count"] >= floor),
                           key=lambda x: (x is None, str(x)))
            data[f"{c}_freqItems"] = [json.dumps(items)]
        return df.sparkSession.createDataFrame(pa.table(data))

    def sampleBy(self, col: str, fractions: Dict, seed: int = 42):
        """Stratified sample: per-stratum Bernoulli sampling, unioned —
        each branch stays an engine-native Sample node."""
        df = self._df
        out = None
        for i, (k, frac) in enumerate(sorted(fractions.items(),
                                             key=lambda kv: str(kv[0]))):
            part = df.filter(E.Cmp("==", E.Col(col), E.Literal(k))) \
                .sample(float(frac), seed=seed + i)
            out = part if out is None else out.union(part)
        return out if out is not None else df.limit(0)


def describe(df, cols: Optional[Sequence[str]] = None):
    """count/mean/stddev/min/max per numeric column (reference:
    Dataset.describe -> StatFunctions.summary)."""
    import pyarrow as pa

    names = [c for c in (cols or df.columns)
             if _is_numeric(df.schema.field(c).dtype)]
    aggs = []
    for c in names:
        x = E.Cast(E.Col(c), T.FLOAT64)
        aggs += [E.Alias(E.Count(x), f"n_{c}"),
                 E.Alias(E.Avg(x), f"mean_{c}"),
                 E.Alias(E.StddevVariance("stddev_samp", x),
                         f"std_{c}"),
                 E.Alias(E.Min(x), f"min_{c}"),
                 E.Alias(E.Max(x), f"max_{c}")]
    if not aggs:
        return df.limit(0)
    r = df.agg(*aggs).collect()[0]

    def fmt(v):
        return None if v is None else str(v)

    data = {"summary": ["count", "mean", "stddev", "min", "max"]}
    for c in names:
        data[c] = [fmt(r[f"n_{c}"]), fmt(r[f"mean_{c}"]),
                   fmt(r[f"std_{c}"]), fmt(r[f"min_{c}"]),
                   fmt(r[f"max_{c}"])]
    return df.sparkSession.createDataFrame(pa.table(data))

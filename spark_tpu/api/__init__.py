from spark_tpu.api.session import SparkSession  # noqa: F401
from spark_tpu.api.dataframe import DataFrame  # noqa: F401
from spark_tpu.api.row import Row  # noqa: F401

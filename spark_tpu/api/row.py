"""Row: the result-row type returned by collect() (reference:
sql/catalyst/.../expressions/rows.scala GenericRow / python
pyspark/sql/types.py Row). Field access by name or position."""

from __future__ import annotations

from typing import Any, Dict, Iterator, Tuple


class Row:
    __slots__ = ("_names", "_values")

    def __init__(self, names: Tuple[str, ...], values: Tuple[Any, ...]):
        self._names = names
        self._values = values

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Row":
        return cls(tuple(d.keys()), tuple(d.values()))

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._values[key]
        return self._values[self._names.index(key)]

    def __getattr__(self, name: str):
        try:
            names = object.__getattribute__(self, "_names")
        except AttributeError:
            raise AttributeError(name)
        if name in names:
            return self._values[names.index(name)]
        raise AttributeError(name)

    def asDict(self) -> Dict[str, Any]:
        return dict(zip(self._names, self._values))

    def __iter__(self) -> Iterator[Any]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __eq__(self, other) -> bool:
        if isinstance(other, Row):
            return self._values == other._values
        if isinstance(other, tuple):
            return self._values == other
        return NotImplemented

    def __hash__(self):
        return hash(self._values)

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}={v!r}" for n, v in
                          zip(self._names, self._values))
        return f"Row({inner})"

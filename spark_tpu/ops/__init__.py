"""Hand-written TPU kernels (Pallas) for hot ops the XLA autofusion
path leaves on the table. Selection is measured, not assumed: callers
go through ``maybe_*`` entry points that fall back to the pure-XLA
kernels in physical/kernels.py whenever shapes/dtypes/platform don't
qualify."""

from spark_tpu.ops.pallas_agg import (  # noqa: F401
    maybe_pallas_seg_count,
    maybe_pallas_seg_max,
    maybe_pallas_seg_mean,
    maybe_pallas_seg_min,
    maybe_pallas_seg_sum,
    pallas_available,
    pallas_seg_minmax,
    pallas_seg_sum,
)

"""Pallas TPU kernels: one-pass segmented (grouped) reductions.

The hot aggregation path in physical/kernels.py handles small group
counts with K masked dense reductions (`_masked_reduce`) — K full passes
over the data column from HBM. That is the right call for tiny K, but
HBM traffic scales as K*N. This kernel makes ONE pass: the column is
streamed HBM -> VMEM in (block_rows, 128) tiles, per-group partial sums
accumulate in a VMEM-resident (K, 128) lane-parallel accumulator, and
the final cross-lane reduce of the tiny (K, 128) result happens in
plain XLA outside the kernel.

Reference peer: the Tungsten hash-aggregate inner loop
(sql/core/.../aggregate/TungstenAggregationIterator.scala:82 probing
BytesToBytesMap.java:497) — rebuilt as a blocked streaming kernel
because on TPU the accumulator fits VMEM and "probing" is a vector
compare, not a pointer chase.

Constraints (checked by ``pallas_available``): float32 data (TPU
Pallas has no f64; the engine's f64 columns keep the XLA path),
2 <= K <= 1024 (VMEM accumulator budget), data length padded to the
block size by the wrapper. Tests run the same kernel with
``interpret=True`` on CPU against a numpy oracle.

Measured on a v5e (N=16M rows, 80% live, 2026-07): per-pass ms

    K          64      128     256     512     1024    2048
    this       4.8     10.0    17.6    29.3    ~58     ~116
    XLA fused  3.9     10.4    12.2    16.8    33.5    63.4
    scatter    149     153     152     153     158     126

XLA's fused multi-reduction ("K-pass" that the compiler collapses to
one pass) WINS at runtime — but its compile time is the unrolled
HLO's: 28 s at K=1024, 64 s at K=2048, vs ~1 s flat for this kernel.
Selection encoded in physical/kernels.py: K <= 64 XLA fused (compile
stays sub-second), 64 < K <= 1024 this kernel on TPU (avoids both the
scatter cliff and multi-second compiles), else scatter/sort paths.

Accumulator family (same tiling, same selection table): Sum
(``pallas_seg_sum``), Count (``maybe_pallas_seg_count`` — the sum
kernel over the mask with an exact-int epilogue), Min/Max
(``pallas_seg_minmax`` — sentinel-carried instead of zero-carried, so
masked-out rows and lane padding cannot win the reduction), and Mean
(``maybe_pallas_seg_mean`` — sum/count composition, two passes sharing
the tile layout). Min/Max measure within a few percent of the sum
kernel at equal K: the inner loop swaps an add for a select-compare,
both lane-parallel.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_BLOCK_ROWS = 64          # (64, 128) tiles: 8k elements per grid step
_LANES = 128
_MAX_K = 1024             # (1024, 128) f32 accumulator = 512 KiB VMEM


def pallas_available(dtype, num_segments: int,
                     platform: Optional[str] = None) -> bool:
    """Whether the Pallas path applies: TPU backend (or forced via
    SPARK_TPU_PALLAS=force for interpret-mode testing), supported dtype,
    accumulator-friendly K."""
    mode = os.environ.get("SPARK_TPU_PALLAS", "auto")
    if mode == "0":
        return False
    if not (2 <= num_segments <= _MAX_K):
        return False
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return False
    if mode == "force":
        return True
    if platform is None:
        try:
            platform = jax.default_backend()
        except Exception:
            return False
    return platform == "tpu"


def _kernel(seg_ref, data_ref, mf_ref, acc_ref, *, num_segments: int):
    """One grid step: accumulate this (B, 128) tile's per-group,
    per-lane partial sums into the (K, 128) output accumulator."""
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    seg = seg_ref[:]                      # (B, 128) int32
    data = data_ref[:]                    # (B, 128) f32
    mf = mf_ref[:]                        # (B, 128) f32 (0/1 mask)
    masked = data * mf

    def body(k, carry):
        sel = (seg == k).astype(masked.dtype)          # (B, 128)
        part = jnp.sum(sel * masked, axis=0, keepdims=True)  # (1, 128)
        prev = acc_ref[pl.ds(k, 1), :]
        acc_ref[pl.ds(k, 1), :] = prev + part
        return carry

    jax.lax.fori_loop(0, num_segments, body, 0)


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "interpret",
                                    "exact_int"))
def pallas_seg_sum(data: jnp.ndarray, seg: jnp.ndarray,
                   mask: jnp.ndarray, num_segments: int,
                   interpret: bool = False,
                   exact_int: bool = False) -> jnp.ndarray:
    """Grouped sum of ``data`` (1-D) by segment id in ONE pass over HBM.
    Rows with mask False (or seg outside [0, K)) contribute nothing.
    Returns float32[num_segments], or int64 when ``exact_int`` (counts:
    per-lane accumulators hold exact integers up to 2^24, so the final
    cross-lane reduce happens in int64)."""
    from jax.experimental import pallas as pl

    n = data.shape[0]
    block = _BLOCK_ROWS * _LANES
    pad = (-n) % block
    f32 = jnp.float32
    d = jnp.pad(data.astype(f32), (0, pad))
    s = jnp.pad(seg.astype(jnp.int32), (0, pad),
                constant_values=num_segments)  # out of range: ignored
    m = jnp.pad(mask.astype(f32), (0, pad))
    rows = (n + pad) // _LANES
    d2 = d.reshape(rows, _LANES)
    s2 = s.reshape(rows, _LANES)
    m2 = m.reshape(rows, _LANES)
    grid = rows // _BLOCK_ROWS

    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    acc = pl.pallas_call(
        functools.partial(_kernel, num_segments=num_segments),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=pl.BlockSpec((num_segments, _LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_segments, _LANES), f32),
        interpret=interpret,
    )(s2, d2, m2)
    if exact_int:
        return acc.astype(jnp.int64).sum(axis=1)
    return acc.sum(axis=1)


def _minmax_kernel(seg_ref, data_ref, mf_ref, acc_ref, *,
                   num_segments: int, is_max: bool):
    """One grid step of the segmented min/max: masked-out rows carry the
    identity sentinel (not zero — zero would win min over positives),
    so padding and dead rows can never beat a live value."""
    from jax.experimental import pallas as pl

    ident = jnp.float32(-jnp.inf if is_max else jnp.inf)
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        acc_ref[:] = jnp.full_like(acc_ref, ident)

    seg = seg_ref[:]                      # (B, 128) int32
    data = data_ref[:]                    # (B, 128) f32
    live = mf_ref[:] > 0                  # (B, 128) bool
    pick = jnp.maximum if is_max else jnp.minimum

    def body(k, carry):
        sel = live & (seg == k)                        # (B, 128)
        cand = jnp.where(sel, data, ident)
        if is_max:
            part = jnp.max(cand, axis=0, keepdims=True)
        else:
            part = jnp.min(cand, axis=0, keepdims=True)
        prev = acc_ref[pl.ds(k, 1), :]
        acc_ref[pl.ds(k, 1), :] = pick(prev, part)
        return carry

    jax.lax.fori_loop(0, num_segments, body, 0)


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "is_max",
                                    "interpret"))
def pallas_seg_minmax(data: jnp.ndarray, seg: jnp.ndarray,
                      mask: jnp.ndarray, num_segments: int,
                      is_max: bool = False,
                      interpret: bool = False) -> jnp.ndarray:
    """Grouped min (or max) of ``data`` (1-D) by segment id, one pass
    over HBM. Groups with no live row yield the identity (+inf for min,
    -inf for max) — same convention as the XLA kernels' sentinel, so
    the caller's empty-group handling is path-independent."""
    from jax.experimental import pallas as pl

    n = data.shape[0]
    block = _BLOCK_ROWS * _LANES
    pad = (-n) % block
    f32 = jnp.float32
    d = jnp.pad(data.astype(f32), (0, pad))
    s = jnp.pad(seg.astype(jnp.int32), (0, pad),
                constant_values=num_segments)  # out of range: ignored
    m = jnp.pad(mask.astype(f32), (0, pad))
    rows = (n + pad) // _LANES
    d2 = d.reshape(rows, _LANES)
    s2 = s.reshape(rows, _LANES)
    m2 = m.reshape(rows, _LANES)
    grid = rows // _BLOCK_ROWS

    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    acc = pl.pallas_call(
        functools.partial(_minmax_kernel, num_segments=num_segments,
                          is_max=is_max),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=pl.BlockSpec((num_segments, _LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_segments, _LANES), f32),
        interpret=interpret,
    )(s2, d2, m2)
    # cross-lane reduce outside the kernel: sentinel lanes lose
    return acc.max(axis=1) if is_max else acc.min(axis=1)


# engine-side selection bound: below this the XLA fused multi-reduce
# compiles fast and runs faster (see measurement table above)
MIN_ENGINE_K = 64


def maybe_pallas_seg_sum(data, seg, mask, num_segments: int):
    """Engine entry point for float32 grouped sums: the Pallas path when
    it qualifies, else None (caller falls back to the XLA kernels)."""
    if num_segments <= MIN_ENGINE_K or \
            not pallas_available(data.dtype, num_segments):
        return None
    interpret = jax.default_backend() != "tpu"
    return pallas_seg_sum(data, seg, mask, num_segments,
                          interpret=interpret)


def maybe_pallas_seg_count(seg, mask, num_segments: int):
    """Engine entry point for grouped counts (exact int64 result).
    Per-(group, lane) f32 accumulators stay exact below 2^24 increments,
    i.e. up to 2^31 rows — beyond any single static batch."""
    if num_segments <= MIN_ENGINE_K or \
            not pallas_available(np.float32, num_segments):
        return None
    if seg.shape[0] >= (1 << 31):
        return None
    interpret = jax.default_backend() != "tpu"
    ones = mask.astype(jnp.float32)
    return pallas_seg_sum(ones, seg, mask, num_segments,
                          interpret=interpret, exact_int=True)


def maybe_pallas_seg_min(data, seg, mask, num_segments: int):
    """Engine entry point for float32 grouped min: Pallas when it
    qualifies, else None. Empty groups come back +inf, matching the
    XLA sentinel convention in physical/kernels.seg_min."""
    if num_segments <= MIN_ENGINE_K or \
            not pallas_available(data.dtype, num_segments):
        return None
    interpret = jax.default_backend() != "tpu"
    return pallas_seg_minmax(data, seg, mask, num_segments,
                             is_max=False, interpret=interpret)


def maybe_pallas_seg_max(data, seg, mask, num_segments: int):
    """Engine entry point for float32 grouped max (empty groups -inf)."""
    if num_segments <= MIN_ENGINE_K or \
            not pallas_available(data.dtype, num_segments):
        return None
    interpret = jax.default_backend() != "tpu"
    return pallas_seg_minmax(data, seg, mask, num_segments,
                             is_max=True, interpret=interpret)


def maybe_pallas_seg_mean(data, seg, mask, num_segments: int):
    """Engine entry point for float32 grouped mean: sum and count from
    the same tiled kernels (two passes), divided outside. Empty groups
    yield NaN (0/0 guarded to 0-count -> NaN via where), which callers
    mask with their own validity. None when the path doesn't qualify."""
    if num_segments <= MIN_ENGINE_K or \
            not pallas_available(data.dtype, num_segments):
        return None
    if seg.shape[0] >= (1 << 31):
        return None
    interpret = jax.default_backend() != "tpu"
    s = pallas_seg_sum(data, seg, mask, num_segments,
                       interpret=interpret)
    c = pallas_seg_sum(mask.astype(jnp.float32), seg, mask,
                       num_segments, interpret=interpret,
                       exact_int=True)
    return jnp.where(c > 0, s / jnp.maximum(c, 1).astype(jnp.float32),
                     jnp.float32(jnp.nan))

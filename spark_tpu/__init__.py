"""spark_tpu — a TPU-native distributed data-analytics engine with the
capabilities of Apache Spark, built from scratch on JAX/XLA/Pallas/pjit.

See SURVEY.md at the repo root for the structural analysis of the
reference (Apache Spark 3.5.0-SNAPSHOT) this is built to match.
"""

__version__ = "0.1.0"


def _require_x64():
    """The SQL engine needs int64/float64; enable x64 once, lazily."""
    import jax

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

"""Multi-host execution: the DCN tier (reference:
core/.../scheduler/cluster/CoarseGrainedSchedulerBackend.scala:53 driver
RPC loop + executor registration, HeartbeatReceiver.scala:67).

TPU-first replacement: there is no driver/executor RPC protocol to
build — `jax.distributed` IS the control plane (a coordination service
every host connects to), and once initialized, `jax.devices()` spans all
hosts so the SAME MeshExecutor programs run SPMD across the pod:
intra-slice exchanges ride ICI, cross-slice collectives ride DCN, and
XLA partitions every stage program automatically. "Task launch" on N
hosts is N processes dispatching the same jitted stage; the coordination
service supplies barriers, health, and failure propagation (a dead host
fails the collective -> every host sees the error -> the driver restarts
from the last completed stage, the lineage-recompute analogue).

What each host runs:

    from spark_tpu.parallel.multihost import initialize, global_mesh
    initialize(coordinator="host0:8476", num_processes=N, process_id=i)
    spark = SparkSession.builder.master("mesh[*]").getOrCreate()
    # identical driver code on every host; collect() returns on host 0

This module is deliberately thin: everything mesh-shaped in the engine
(exchange collectives, stage programs, shard layouts) is already
host-count agnostic — the ShardedBatch axis simply spans more devices.
Single-host CI exercises the same code paths through the virtual-device
mesh (tests/conftest.py)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the jax.distributed coordination service (reference peer:
    CoarseGrainedExecutorBackend registering with the driver). On
    single-host setups this is a no-op; on TPU pods with autodetection
    all arguments may be None."""
    if num_processes is not None and int(num_processes) <= 1:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    except (RuntimeError, ValueError) as e:
        if coordinator is None and num_processes is None:
            # bare call outside a managed multi-host environment
            # (autodetection needs a TPU pod / cluster): single host
            return
        raise e


def barrier_kv_exchange(key: str, value: str, peer_key: str,
                        timeout_s: int = 30) -> str:
    """Cross-process rendezvous through the coordination service's
    key-value store: publish ``key``=``value``, block until ``peer_key``
    appears, return the peer's value. This is the driver<->executor
    registration handshake shape (reference:
    CoarseGrainedSchedulerBackend RegisterExecutor/RegisteredExecutor)
    carried by the SAME control plane every production barrier uses —
    and the thing a two-process test can assert REALLY crosses process
    boundaries."""
    from jax._src import distributed as _dist

    client = _dist.global_state.client
    if client is None:
        raise RuntimeError("multihost.initialize() has not run")
    client.key_value_set(key, value)
    return client.blocking_key_value_get(peer_key, timeout_s * 1000)


def global_mesh(devices: Optional[Sequence] = None):
    """A data mesh over EVERY device in the job (all hosts). Shardings
    placed on this mesh make XLA route intra-host traffic over ICI and
    inter-host traffic over DCN without any engine changes."""
    from spark_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=list(devices) if devices is not None
                     else list(jax.devices()))


def process_info() -> dict:
    """Host-level topology facts (the executor-registration record)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": [str(d) for d in jax.local_devices()],
        "global_devices": len(jax.devices()),
    }


def is_coordinator() -> bool:
    return jax.process_index() == 0


# ---- data plane: per-host addressable-shard feeding -------------------------
#
# The reference's executors each read their OWN input splits
# (FileScanRDD preferred locations over HDFS blocks); the TPU analogue
# is each PROCESS converting its local parquet fragments to numpy and
# handing jax.make_array_from_process_local_data the local slice — the
# global sharded array materializes with ZERO cross-host data movement,
# and every MeshExecutor stage then runs on it unchanged.


def local_fragments(path, fmt: str = "parquet") -> list:
    """This process's share of a multi-file dataset (round-robin over
    the sorted file list — the preferred-location analogue: each host
    scans only its own fragments)."""
    import pyarrow.dataset as pads

    ds = pads.dataset(path, format=fmt)
    files = sorted(ds.files)
    return files[jax.process_index()::jax.process_count()]


def sharded_batch_from_local(table, mesh=None,
                             per_device_capacity: "int | None" = None):
    """Assemble a global ShardedBatch from THIS process's rows.

    Every process calls this with its own (different) table;
    ``jax.make_array_from_process_local_data`` stitches the local
    slices into one global array sharded over the mesh's data axis.
    ``per_device_capacity`` must agree across processes — pass it
    explicitly in multi-host jobs (e.g. from a barrier_kv_exchange of
    per-host maxima); the local default is only safe single-process."""
    import math

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from spark_tpu.columnar.arrow import from_arrow
    from spark_tpu.columnar.batch import BatchData, ColumnData
    from spark_tpu.parallel.mesh import DATA_AXIS
    from spark_tpu.parallel.sharded import ShardedBatch
    from spark_tpu.physical.kernels import bucket

    if mesh is None:
        mesh = global_mesh()
    pidx = jax.process_index()
    local_devs = [d for d in mesh.devices.flat
                  if d.process_index == pidx]
    if not local_devs:
        raise ValueError("mesh has no devices on this process")
    p = per_device_capacity or bucket(
        math.ceil(max(1, table.num_rows) / len(local_devs)), 128)
    local_cap = p * len(local_devs)
    if table.num_rows > local_cap:
        raise ValueError(
            f"local rows {table.num_rows} exceed local capacity "
            f"{local_cap}; raise per_device_capacity")
    lb = from_arrow(table, capacity=local_cap)
    sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))

    def put(arr):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(arr))

    cols = tuple(
        ColumnData(put(cd.data),
                   None if cd.validity is None else put(cd.validity))
        for cd in lb.data.columns)
    return ShardedBatch(lb.schema,
                        BatchData(cols, put(lb.data.row_mask)), mesh)


def read_parquet_sharded(path, mesh=None, columns=None,
                         per_device_capacity: "int | None" = None):
    """Distributed scan: each process reads its own fragment subset and
    contributes the rows as addressable shards of one global
    ShardedBatch (reference role: FileScanRDD + preferred locations)."""
    import pyarrow.dataset as pads

    frags = local_fragments(path)
    if frags:
        table = pads.dataset(frags, format="parquet").to_table(
            columns=list(columns) if columns is not None else None)
    else:
        table = pads.dataset(path, format="parquet").schema.empty_table()
    return sharded_batch_from_local(
        table, mesh, per_device_capacity=per_device_capacity)

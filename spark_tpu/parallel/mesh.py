"""Device mesh construction.

The mesh plays the role of the reference's cluster topology (executors
registered with the driver, reference:
core/.../cluster/CoarseGrainedSchedulerBackend.scala:53) — except
membership is static for a program and agreed on by construction, so
there is no registration protocol, heartbeat, or executor bookkeeping to
rebuild. One mesh axis, ``data``, carries partition parallelism (the
analogue of Spark task slots); further axes can be added for model-style
parallelism without touching the exchange layer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a 1-D ``data`` mesh over the first ``n_devices`` devices
    (defaults to all). The local[N] / mesh[N] master-URL analogue."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"mesh[{n_devices}] requested but only {len(devices)} "
                f"devices are available")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def mesh_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]

"""Mesh-distributed execution.

This package replaces the reference's entire distributed runtime — the
driver/executor split, Netty RPC, and the sort-based shuffle machinery
(reference: core/.../scheduler/DAGScheduler.scala:121,
shuffle/sort/SortShuffleManager.scala:73, rpc/netty/NettyRpcEnv.scala:45,
network-common) — with the TPU-native shape: data lives sharded over a
`jax.sharding.Mesh`, a "stage" is one pjit/shard_map-compiled SPMD
program, and "shuffle" is an in-HBM `all_to_all` over ICI instead of
sorted spill files fetched over TCP (SURVEY.md §2 "Distributed
communication backend", §7 design stance).
"""

from spark_tpu.parallel.mesh import DATA_AXIS, make_mesh  # noqa: F401
from spark_tpu.parallel.sharded import ShardedBatch  # noqa: F401

"""Distributed physical operators (traced inside shard_map).

These compose with the single-device operators (physical/operators.py) in
ONE fused SPMD program per stage: local pipeline work is the same trace
code, and cross-device redistribution appears as exchange collectives at
exactly the points where the reference plants ShuffleExchangeExec /
BroadcastExchangeExec nodes (reference: exchange/EnsureRequirements.scala:49,
ShuffleExchangeExec.scala:120, BroadcastExchangeExec.scala:78). A whole
distributed stage — scan, filter, partial agg, psum merge, final agg —
compiles to a single XLA executable with collectives scheduled on ICI.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.expr import compiler as C
from spark_tpu.expr import expressions as E
from spark_tpu.expr.compiler import Env, TV
from spark_tpu.parallel import exchange as X
from spark_tpu.parallel.sharded import ShardedBatch
from spark_tpu.physical import kernels as K
from spark_tpu.physical import operators as P
from spark_tpu.physical.operators import (Pipe, _distinct_mask_cached,
                                          rewrite_agg_outputs)
from spark_tpu.types import Field, Schema


@dataclass(eq=False)
class ShardScanExec(P.PhysicalPlan):
    """Leaf: a materialized ShardedBatch; the stage runner feeds each
    device its local slice."""

    sharded: ShardedBatch
    traceable = True

    @property
    def schema(self) -> Schema:
        return self.sharded.schema

    def node_string(self):
        return f"ShardScan{list(self.schema.names)}"

    def plan_key(self):
        dicts = tuple(f.dictionary for f in self.schema.fields)
        return ("ShardScan", self.sharded.per_device_capacity,
                tuple((f.name, repr(f.dtype)) for f in self.schema.fields),
                hash(dicts))


@dataclass(eq=False)
class DistRangeExec(P.PhysicalPlan):
    """range() generated directly sharded: device d materializes global
    positions [d*p, (d+1)*p) — nothing is ever resident on one device
    (reference RangeExec:412 splits by numSlices; here the mesh is the
    slicing)."""

    start: int
    end: int
    step: int
    num_rows: int
    per_device: int
    col_name: str = "id"
    traceable = True

    @property
    def schema(self) -> Schema:
        return Schema((Field(self.col_name, T.INT64, nullable=False),))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        p = self.per_device
        gpos = X.axis_index().astype(jnp.int64) * p + jnp.arange(
            p, dtype=jnp.int64)
        ids = self.start + gpos * self.step
        mask = gpos < self.num_rows
        return Pipe({self.col_name: TV(ids, None, T.INT64, None)}, mask,
                    [self.col_name])

    def plan_key(self):
        return ("DistRange", self.start, self.end, self.step, self.num_rows,
                self.per_device, self.col_name)


# ---- exchanges --------------------------------------------------------------

#: fixed odd 64-bit seeds for the Count-Min hash rows (pairwise-
#: independent enough through the avalanche rehash; depth <= 8). Fixed
#: so the probe participates in the jit plan cache like every other
#: trace constant.
_CM_SEEDS = (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
             0x165667B19E3779F9, 0x27D4EB2F165667C5,
             0x85EBCA77C2B2AE63, 0x2545F4914F6CDD1D,
             0xD6E8FEB86659FD93, 0xA24BAED4963EE407)


@dataclass(eq=False)
class HashPartitionExchangeExec(P.PhysicalPlan):
    """``key_union_dicts`` (optional, per key): a unified string
    dictionary; codes translate through it before hashing so that two
    relations with different dictionaries route equal strings to the
    same device.

    Adaptive fields (set by executor._run_adaptive_exchange from
    measured stats; all participate in plan_key so re-traces at the same
    bucket-rounded bounds hit the jit stage cache):
    ``slice_capacity``/``out_capacity`` bound the send slice and the
    received capacity (see exchange.exchange); ``fan_destinations``
    reroutes rows bound for skewed destinations back to their source
    device (exchange.fan_local) ahead of a partial-aggregate pre-merge;
    ``presplit_hashes`` (Count-Min heavy-hitter row hashes) salts the
    rows of hot KEYS round-robin over all devices BEFORE the exchange —
    legal only on a raw-row exchange ahead of a partial->final pair
    whose accumulators are partition-invariant (legality.
    strategy_verdict), where spreading one key over many partials is
    re-merged exactly by the final; a 64-bit hash collision merely
    salts one cold key too, which the same invariance makes harmless.
    """

    keys: Tuple[E.Expression, ...]
    child: P.PhysicalPlan
    key_union_dicts: Optional[Tuple[Optional[Tuple[str, ...]], ...]] = None
    slice_capacity: Optional[int] = None
    out_capacity: Optional[int] = None
    fan_destinations: Optional[Tuple[int, ...]] = None
    presplit_hashes: Optional[Tuple[int, ...]] = None
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def _key_tvs(self, pipe: Pipe) -> List[TV]:
        """Key columns after union-dictionary translation — the exact
        values routing hashes over (also what the stats stage sketches
        and measures, so decisions see what the exchange will see)."""
        env = pipe.env()
        tvs = [C.evaluate(k, env) for k in self.keys]
        if self.key_union_dicts is not None:
            translated = []
            for tv, union in zip(tvs, self.key_union_dicts):
                if union is not None and tv.dictionary is not None:
                    pos = {s: i for i, s in enumerate(union)}
                    table = np.array([pos[s] for s in tv.dictionary],
                                     dtype=np.int64)
                    tv = TV(jnp.asarray(table)[tv.data], tv.validity,
                            tv.dtype, union)
                translated.append(tv)
            tvs = translated
        return tvs

    def _target(self, pipe: Pipe, d: int) -> jnp.ndarray:
        key_tvs = self._key_tvs(pipe)
        target = X.hash_target(key_tvs, pipe.mask, d)
        if self.presplit_hashes:
            h = X.hash_rows(key_tvs)
            hot = jnp.zeros(h.shape, dtype=jnp.bool_)
            for ph in self.presplit_hashes:
                hot = hot | (h == jnp.uint64(np.uint64(ph)))
            hot = hot & pipe.mask
            # hot rows round-robin over ALL devices, offset by the
            # source device so the d salted streams interleave instead
            # of marching in lockstep onto the same destinations
            rank = jnp.cumsum(hot.astype(jnp.int32)) - 1
            salted = ((rank + X.axis_index()) % d).astype(jnp.int32)
            target = jnp.where(hot, salted, target)
        if self.fan_destinations:
            target = X.fan_local(target, self.fan_destinations)
        return target

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        return X.exchange(pipe, self._target(pipe, X.axis_size()),
                          self.slice_capacity, self.out_capacity)

    def node_string(self):
        return f"Exchange[hash({', '.join(map(str, self.keys))})]"

    def plan_key(self):
        return ("HashExchange", tuple(E.expr_key(k) for k in self.keys),
                self.key_union_dicts, self.slice_capacity,
                self.out_capacity, self.fan_destinations,
                self.presplit_hashes, self.child.plan_key())


@dataclass(eq=False)
class RoundRobinExchangeExec(P.PhysicalPlan):
    """Balanced redistribution (RoundRobinPartitioning analogue)."""

    child: P.PhysicalPlan
    slice_capacity: Optional[int] = None
    out_capacity: Optional[int] = None
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def _target(self, pipe: Pipe, d: int) -> jnp.ndarray:
        rank = jnp.cumsum(pipe.mask.astype(jnp.int32)) - 1
        return ((rank + X.axis_index()) % d).astype(jnp.int32)

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        return X.exchange(pipe, self._target(pipe, X.axis_size()),
                          self.slice_capacity, self.out_capacity)

    def plan_key(self):
        return ("RoundRobinExchange", self.slice_capacity,
                self.out_capacity, self.child.plan_key())


@dataclass(eq=False)
class RangeExchangeExec(P.PhysicalPlan):
    """Range-partition rows by the leading sort key so device order ==
    global sort order; a local sort downstream completes a distributed
    global sort (reference: ShuffleExchangeExec.scala:280 + SortExec)."""

    orders: Tuple[E.SortOrder, ...]
    child: P.PhysicalPlan
    slice_capacity: Optional[int] = None
    out_capacity: Optional[int] = None
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def _target(self, pipe: Pipe, d: int) -> jnp.ndarray:
        o = self.orders[0]
        key = C.evaluate(o.child, pipe.env())
        return X.range_target(key, o.ascending, o.nulls_first_resolved, d,
                              pipe.mask)

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        return X.exchange(pipe, self._target(pipe, X.axis_size()),
                          self.slice_capacity, self.out_capacity)

    def node_string(self):
        return f"Exchange[range({', '.join(map(str, self.orders))})]"

    def plan_key(self):
        return ("RangeExchange",
                tuple((E.expr_key(o.child), o.ascending,
                       o.nulls_first_resolved) for o in self.orders),
                self.slice_capacity, self.out_capacity,
                self.child.plan_key())


@dataclass(eq=False)
class ExchangeStatsExec(P.PhysicalPlan):
    """Measure an exchange WITHOUT running it: re-derive the routing
    targets (the same ``_target`` computation the exchange itself will
    trace, so the counts are exact, not estimates) and reduce them to
    two d-length vectors with on-device collectives — ``__incoming``
    (psum of per-destination live counts: rows each device will
    receive) and ``__maxslice`` (pmax: the largest single (src, dest)
    send cell). One tiny SPMD stage, one host fetch of 2*d int64s —
    the MapOutputStatistics of this engine (reference:
    MapOutputTrackerMaster.getStatistics, consumed by
    AdaptiveSparkPlanExec between stages).

    Optional extensions riding the same stage + fetch (hash exchanges
    only; both default off so existing uses measure exactly as before):

    - ``sketch_registers`` > 0 adds ``__ndvreg``: HyperLogLog-style
      register maxima over the exchange keys. Register index and rank
      come from the SAME full-width hash chain routing uses (minus the
      mod-D), ranks seg-max locally (through the measured selection
      table — 64 < R <= 1024 rides the Pallas one-pass kernel on TPU)
      and pmax across the mesh; the host turns register maxima into a
      distinct-key estimate. One extra O(registers) int vector.
    - ``key_stats`` > 0 adds ``__kmin``/``__kmax``/``__knull``: global
      per-key value min/max (pmin/pmax) and a nulls-present flag over
      the translated key columns — the measured packed-code domain for
      the hash-partial aggregation strategy.
    - ``cm_depth``/``cm_width`` > 0 add ``__hothash``/``__hotest``: a
      Count-Min heavy-hitter probe over the SAME row hashes routing
      uses. Each of ``cm_depth`` rows rehashes with a fixed odd seed
      into a ``cm_width``-wide count table (seg_count local, psum
      global), the per-row estimate is the min over depths, and each
      device publishes its local argmax candidate (full 64-bit key
      hash + global CM estimate) at position ``axis_index`` of the two
      d-length vectors. The host dedups candidates by hash and elects
      hot KEYS for pre-splitting (see ``presplit_hashes`` above) —
      per-key frequency the HLL sketch cannot see, at the cost of
      2*depth collectives of width ``cm_width``.
    """

    exchange: P.PhysicalPlan  # Hash/RoundRobin/Range exchange exec
    sketch_registers: int = 0    # power of two; 0 = no distinct sketch
    key_stats: int = 0           # number of keys to min/max; 0 = none
    cm_depth: int = 0            # Count-Min hash rows; 0 = no CM probe
    cm_width: int = 0            # power of two; 0 = no CM probe
    traceable = True

    def children(self):
        return self.exchange.children()

    @property
    def schema(self) -> Schema:
        fields = [Field("__incoming", T.INT64, nullable=False),
                  Field("__maxslice", T.INT64, nullable=False)]
        if self.sketch_registers:
            fields.append(Field("__ndvreg", T.INT64, nullable=False))
        if self.key_stats:
            fields.append(Field("__kmin", T.INT64, nullable=False))
            fields.append(Field("__kmax", T.INT64, nullable=False))
            fields.append(Field("__knull", T.INT64, nullable=False))
        if self.cm_depth and self.cm_width:
            fields.append(Field("__hothash", T.INT64, nullable=False))
            fields.append(Field("__hotest", T.INT64, nullable=False))
        return Schema(tuple(fields))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        d = X.axis_size()
        target = self.exchange._target(pipe, d)
        local = K.seg_count(jnp.clip(target, 0, d - 1).astype(jnp.int32),
                            pipe.mask, d)
        incoming = X.psum(local).astype(jnp.int64)
        maxslice = X.pmax(local).astype(jnp.int64)

        cap = max(d, self.sketch_registers or 0, self.key_stats or 0)

        def padded(v):
            return jnp.pad(v.astype(jnp.int64), (0, cap - v.shape[0]))

        cols = {"__incoming": TV(padded(incoming), None, T.INT64, None),
                "__maxslice": TV(padded(maxslice), None, T.INT64, None)}
        order = ["__incoming", "__maxslice"]

        if self.sketch_registers or self.key_stats or \
                (self.cm_depth and self.cm_width):
            key_tvs = self.exchange._key_tvs(pipe)

        if self.sketch_registers:
            r = int(self.sketch_registers)
            p = r.bit_length() - 1          # r = 2**p (validated by caller)
            h = X.hash_rows(key_tvs)
            idx = (h & jnp.uint64(r - 1)).astype(jnp.int32)
            w = h >> jnp.uint64(p)
            # rank = leading zeros of the (64-p)-bit suffix + 1, via the
            # float64 highest-set-bit trick (floor(log2)). f64 holds 53
            # mantissa bits < the 64-p suffix width, so a value within
            # half-ulp of a power of two can mis-rank by one register —
            # an error far inside the sketch's own ~1/sqrt(r) noise.
            wf = w.astype(jnp.float64)
            hb = jnp.floor(jnp.log2(jnp.maximum(wf, 1.0)))
            rho = jnp.where(w == jnp.uint64(0),
                            jnp.float64(64 - p + 1),
                            jnp.float64(64 - p) - hb)
            # f32 ranks (<= 56: exact) route the register max through
            # the measured selection table — Pallas one-pass on TPU
            reg = K.seg_max(rho.astype(jnp.float32), idx, pipe.mask, r)
            reg = jnp.maximum(X.pmax(reg), 0.0).astype(jnp.int64)
            cols["__ndvreg"] = TV(padded(reg), None, T.INT64, None)
            order.append("__ndvreg")

        if self.key_stats:
            mins, maxs, nulls = [], [], []
            for tv in key_tvs[:self.key_stats]:
                data = tv.data.astype(jnp.int64)
                valid = pipe.mask if tv.validity is None \
                    else pipe.mask & tv.validity
                big = jnp.iinfo(jnp.int64).max
                small = jnp.iinfo(jnp.int64).min
                mins.append(X.pmin(jnp.min(
                    jnp.where(valid, data, big))[None])[0])
                maxs.append(X.pmax(jnp.max(
                    jnp.where(valid, data, small))[None])[0])
                nnull = jnp.zeros((), jnp.int64) if tv.validity is None \
                    else (pipe.mask & ~tv.validity).sum(dtype=jnp.int64)
                nulls.append(X.psum(nnull[None])[0])
            cols["__kmin"] = TV(padded(jnp.stack(mins)), None, T.INT64,
                                None)
            cols["__kmax"] = TV(padded(jnp.stack(maxs)), None, T.INT64,
                                None)
            cols["__knull"] = TV(padded(jnp.stack(nulls)), None,
                                 T.INT64, None)
            order += ["__kmin", "__kmax", "__knull"]

        if self.cm_depth and self.cm_width:
            w = int(self.cm_width)               # power of two (caller)
            h = X.hash_rows(key_tvs)
            est = None
            for seed in _CM_SEEDS[:int(self.cm_depth)]:
                hj = K.hash64(h ^ jnp.uint64(seed))
                idx = (hj & jnp.uint64(w - 1)).astype(jnp.int32)
                table = X.psum(K.seg_count(idx, pipe.mask, w))
                e = table[idx]
                est = e if est is None else jnp.minimum(est, e)
            # dead rows estimate -1 so the argmax candidate is a live
            # row whenever one exists; the host drops est <= 0 anyway
            est = jnp.where(pipe.mask, est, jnp.int64(-1))
            cand = jnp.argmax(est)
            # each device publishes (key hash, CM estimate) of its own
            # candidate at position axis_index via a one-hot psum — the
            # whole mesh's candidate list in one d-length pair
            slot = jnp.arange(cap) == X.axis_index()
            zero = jnp.int64(0)
            cols["__hothash"] = TV(
                X.psum(jnp.where(slot, h[cand].astype(jnp.int64), zero)),
                None, T.INT64, None)
            cols["__hotest"] = TV(
                X.psum(jnp.where(slot, est[cand], zero)),
                None, T.INT64, None)
            order += ["__hothash", "__hotest"]

        # replicated reductions: keep device 0's copy live, like
        # PSumAggExec, so the result reads back once
        keep = X.axis_index() == 0
        mask = jnp.broadcast_to(keep, (cap,))
        return Pipe(cols, mask, order)

    def node_string(self):
        return f"ExchangeStats[{self.exchange.node_string()}]"

    def plan_key(self):
        return ("ExchangeStats", self.sketch_registers, self.key_stats,
                self.cm_depth, self.cm_width, self.exchange.plan_key())


@dataclass(eq=False)
class BroadcastExchangeExec(P.PhysicalPlan):
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        return X.broadcast_gather(child_pipes[0])

    def plan_key(self):
        return ("BroadcastExchange", self.child.plan_key())


@dataclass(eq=False)
class SinglePartitionExchangeExec(P.PhysicalPlan):
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        return X.to_single_partition(child_pipes[0])

    def plan_key(self):
        return ("SingleExchange", self.child.plan_key())


@dataclass(eq=False)
class DistSampleExec(P.PhysicalPlan):
    """Bernoulli sample with the device index folded into the PRNG key —
    each shard draws independently (Spark seeds per partition the same
    way: RDD.sample's per-split XORShift seed)."""

    fraction: float
    seed: int
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                 X.axis_index())
        u = jax.random.uniform(key, (pipe.capacity,))
        return Pipe(pipe.cols, pipe.mask & (u < self.fraction), pipe.order)

    def plan_key(self):
        return ("DistSample", self.fraction, self.seed,
                self.child.plan_key())


@dataclass(eq=False)
class DistLimitExec(P.PhysicalPlan):
    """Global limit without gathering: each device computes its rows'
    GLOBAL live-rank as local-rank + exclusive prefix of earlier devices'
    live counts (one tiny all_gather of scalars), then masks. The
    reference runs limit as a separate single-partition stage
    (limit.scala GlobalLimitExec after a shuffle); here it is one
    collective of D int64s."""

    n: int
    offset: int
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        d = X.axis_size()
        me = X.axis_index()
        local = pipe.mask.astype(jnp.int64)
        count = local.sum()[None]
        all_counts = jax.lax.all_gather(count, X.DATA_AXIS, tiled=True)
        prefix = jnp.where(jnp.arange(d) < me, all_counts, 0).sum()
        rank = jnp.cumsum(local) - 1 + prefix
        keep = pipe.mask & (rank >= self.offset) & (
            rank < self.offset + self.n)
        return Pipe(pipe.cols, keep, pipe.order)

    def node_string(self):
        return f"DistLimit[{self.n}]"

    def plan_key(self):
        return ("DistLimit", self.n, self.offset, self.child.plan_key())


# ---- distributed aggregation ------------------------------------------------


def _merged_agg(agg: E.AggregateExpression, env: Env, seg, mask,
                num_segments: int, capacity: int) -> TV:
    """One aggregate, locally reduced per segment then merged across the
    mesh with psum/pmin/pmax — the partial->final two-phase plan
    (reference: aggregate/AggUtils.scala:33 map-side combine + shuffled
    merge) collapsed into a single program with an ICI collective as the
    phase boundary."""
    if isinstance(agg, E.Count) and agg.child is None:
        return TV(X.psum(K.seg_count(seg, mask, num_segments)), None,
                  T.INT64, None)

    child = agg.child  # type: ignore[attr-defined]
    tv = C.evaluate(child, env)
    ok = mask & tv.valid_or_true(capacity)
    if getattr(agg, "distinct", False):
        # Local dedup + psum is exact ONLY when equal values are
        # co-resident; the planner guarantees it by hash-exchanging on
        # the distinct child (MeshExecutor._plan_aggregate) before this
        # operator runs.
        ok = ok & _distinct_mask_cached(env, agg.child, tv, seg, ok)
    cnt = X.psum(K.seg_count(seg, ok, num_segments))
    # dedup keeps >= 1 head per non-empty group, so post-dedup positivity
    # matches pre-dedup — no separate psum needed
    any_valid = cnt > 0

    if isinstance(agg, E.Count):
        return TV(cnt, None, T.INT64, None)
    if isinstance(agg, E.Sum):
        if isinstance(tv.dtype, T.DecimalType):
            s = X.psum(K.seg_sum(tv.data, seg, ok, num_segments))
            return TV(s, any_valid, P.decimal_sum_type(tv.dtype), None)
        out_dt = T.INT64 if tv.dtype.is_integral else tv.dtype
        data = tv.data.astype(C._jnp_dtype(out_dt))
        s = X.psum(K.seg_sum(data, seg, ok, num_segments))
        return TV(s, any_valid, out_dt, None)
    if isinstance(agg, E.Avg):
        if isinstance(tv.dtype, T.DecimalType):
            total = X.psum(K.seg_sum(tv.data, seg, ok, num_segments))
            data, out_dt = P.decimal_avg(total, cnt, tv.dtype)
            return TV(data, any_valid, out_dt, None)
        s = X.psum(K.seg_sum(tv.data.astype(jnp.float64), seg, ok,
                             num_segments))
        return TV(s / jnp.maximum(cnt, 1), any_valid, T.FLOAT64, None)
    if isinstance(agg, E.Min):
        return TV(X.pmin(K.seg_min(tv.data, seg, ok, num_segments)),
                  any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.Max):
        return TV(X.pmax(K.seg_max(tv.data, seg, ok, num_segments)),
                  any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.StddevVariance):
        x = tv.data.astype(jnp.float64)
        c = cnt.astype(jnp.float64)
        s = X.psum(K.seg_sum(x, seg, ok, num_segments))
        s2 = X.psum(K.seg_sum(x * x, seg, ok, num_segments))
        m2 = jnp.maximum(s2 - (s * s) / jnp.maximum(c, 1.0), 0.0)
        kind = agg.kind
        denom = c - 1.0 if kind.endswith("_samp") else c
        var = m2 / jnp.maximum(denom, 1.0)
        data = jnp.sqrt(var) if kind.startswith("stddev") else var
        enough = c >= (2.0 if kind.endswith("_samp") else 1.0)
        return TV(data, any_valid & enough, T.FLOAT64, None)
    if isinstance(agg, E.First):
        use = ok if agg.ignore_nulls else mask
        data, found = K.seg_first(tv.data, seg, use, num_segments, capacity)
        if tv.validity is not None:
            vfirst, _ = K.seg_first(tv.valid_or_true(capacity), seg, use,
                                    num_segments, capacity)
        else:
            vfirst = jnp.ones((num_segments,), jnp.bool_)
        # choose the lowest device index that found a first row
        d = X.axis_size()
        me = X.axis_index()
        winner = X.pmin(jnp.where(found, me, d))
        mine = found & (me == winner)
        zero = jnp.zeros((), dtype=data.dtype)
        data = X.psum(jnp.where(mine, data, zero))
        valid = X.psum(jnp.where(mine, vfirst, False).astype(jnp.int32)) > 0
        return TV(data, (winner < d) & valid, tv.dtype, tv.dictionary)
    raise NotImplementedError(f"distributed aggregate {agg!r}")


@dataclass(eq=False)
class PSumAggExec(P.PhysicalPlan):
    """Direct-path aggregation over the mesh: dense group ids from
    trace-time key cardinalities, segment-reduce locally, psum-merge
    across devices — no shuffle at all. This is the north-star operator
    (SURVEY.md §2 'Partial/final aggregation'). Output lives on device 0
    (global arrays masked elsewhere)."""

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return P.HashAggregateExec(self.groupings, self.aggregates,
                                   self.child).schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]
        codes, validities, cards = P.group_key_codes(key_tvs)

        if not key_tvs:
            seg = jnp.zeros((cap,), dtype=jnp.int32)
            num_segments = 1
        else:
            seg, num_segments = K.pack_codes(codes, validities, cards)
            seg = seg.astype(jnp.int32)

        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [_merged_agg(a, env, seg, pipe.mask, num_segments, cap)
                   for a in agg_calls]

        present = X.psum(K.seg_count(seg, pipe.mask, num_segments)) > 0
        if not key_tvs:
            out_mask = jnp.ones((1,), dtype=jnp.bool_)
            out_keys: List[TV] = []
        else:
            out_mask = present
            nullable = [v is not None for v in validities]
            unpacked = K.unpack_code(jnp.arange(num_segments), cards, nullable)
            out_keys = []
            for (code, valid), tv in zip(unpacked, key_tvs):
                data = code.astype(C._jnp_dtype(tv.dtype))
                out_keys.append(TV(data, valid, tv.dtype, tv.dictionary))
        # result is replicated; keep one copy (device 0)
        out_mask = jnp.where(X.axis_index() == 0, out_mask,
                             jnp.zeros_like(out_mask))
        agg_exec = P.HashAggregateExec(self.groupings, self.aggregates,
                                       self.child)
        return agg_exec._finalize(out_keys, agg_tvs, out_mask,
                                  max(1, num_segments))

    def node_string(self):
        return (f"PSumAgg[keys=[{', '.join(map(str, self.groupings))}], "
                f"out=[{', '.join(str(e) for e in self.aggregates)}]]")

    def plan_key(self):
        return ("PSumAgg", tuple(E.expr_key(g) for g in self.groupings),
                tuple(E.expr_key(a) for a in self.aggregates),
                self.child.plan_key())


@dataclass(eq=False)
class DistSortAggExec(P.PhysicalPlan):
    """General group-by after a hash exchange: each device owns whole
    groups, sorts locally, assigns group ids by change-flags. Fully
    traceable — the static segment count is the row capacity (every row
    its own group, worst case), so no host sync is needed inside the
    program (contrast: single-device sort-agg host-syncs the group count;
    reference contrast: TungstenAggregationIterator.scala:82 falls back
    to sort-based with spills)."""

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: P.PhysicalPlan
    #: adaptive-aggregation tag: "partial" marks the pre-exchange half
    #: of a partial->final plan (the node the runtime strategy switch
    #: may bypass or swap for a hash partial); None = ordinary
    phase: Optional[str] = None
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return P.HashAggregateExec(self.groupings, self.aggregates,
                                   self.child).schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        cap = pipe.capacity
        env = pipe.env()
        key_tvs = [C.evaluate(g, env) for g in self.groupings]

        spipe, sorted_keys, seg, ng = P.sorted_groups(pipe, key_tvs)
        env2 = spipe.env()
        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [P._compute_agg(a, env2, seg, spipe.mask, cap, cap,
                                  sorted_seg=True)
                   for a in agg_calls]
        out_keys = P.first_group_keys(sorted_keys, seg, spipe.mask, cap, cap,
                                      sorted_seg=True)
        out_mask = jnp.arange(cap) < ng
        agg_exec = P.HashAggregateExec(self.groupings, self.aggregates,
                                       self.child)
        return agg_exec._finalize(out_keys, agg_tvs, out_mask, cap)

    def node_string(self):
        return (f"DistSortAgg[keys=[{', '.join(map(str, self.groupings))}], "
                f"out=[{', '.join(str(e) for e in self.aggregates)}]]")

    def plan_key(self):
        return ("DistSortAgg", tuple(E.expr_key(g) for g in self.groupings),
                tuple(E.expr_key(a) for a in self.aggregates),
                self.phase, self.child.plan_key())


@dataclass(eq=False)
class DistRangeAggExec(DistSortAggExec):
    """The sort-based aggregation rung's final: the identical local
    sort-and-segment merge as DistSortAggExec, but the executor plans
    it over a RANGE exchange on the group keys instead of a hash
    exchange, so device order == global key order and the per-device
    lexsort completes a distributed global sort — the aggregate's
    output is key-ordered across the whole mesh for free, and a
    matching downstream global Sort collapses to a no-op (the executor
    marks the result batch ``sorted_by``; the sort-vs-hash trade of
    'sort-based group-by produces ordered output as a byproduct'). A
    distinct node so plan/trace cache keys and EXPLAIN output
    distinguish the rung from an ordinary hash-routed DistSortAgg."""

    def node_string(self):
        return (f"DistRangeAgg[keys=[{', '.join(map(str, self.groupings))}],"
                f" out=[{', '.join(str(e) for e in self.aggregates)}]]")

    def plan_key(self):
        return ("DistRangeAgg",) + super().plan_key()[1:]


# ---- whole-query native fusion ----------------------------------------------


def capacity_ladder(bucket: int, variants: int, worst: int,
                    devices: int = 1) -> Tuple[int, ...]:
    """The precompiled capacity rungs one fused span bakes as
    ``lax.switch`` branches, anchored at the BALANCED receive load:
    a well-spread exchange over ``devices`` destinations delivers
    ~worst/devices rows to the hottest one, so the top working rung is
    ceil(worst/devices) rounded up to the adaptive capacity bucket
    plus ONE bucket of headroom (without the headroom, a load one row
    past balanced spills to the next rung — 4x the buffer for a
    rounding miss). Below the anchor the rungs refine geometrically /4
    (bucket-rounded, same headroom) for sparse loads — aggregation
    partials after local dedup carry far fewer live rows than the
    producer's static capacity. The worst case (every live row routed
    to one destination) is always the last rung, so any measured
    incoming count is covered — the fused program can never drop a
    live row the staged path would keep. The band BETWEEN anchor and
    worst gets no rungs on purpose: range exchanges are balanced by
    equi-depth sampling, and skewed hash aggregations bail out to the
    staged skew pre-split before fusion — loads up there are the rare
    case the worst rung exists for."""
    bucket = max(1, int(bucket))
    variants = max(1, int(variants))
    worst = max(1, int(worst))
    d = max(1, int(devices))
    anchor = -(-worst // d)                            # balanced load
    anchor = -(-anchor // bucket) * bucket + bucket    # round up + headroom
    rungs: List[int] = [worst]
    c = min(anchor, worst)
    while len(rungs) < variants and c < rungs[-1]:
        rungs.append(c)
        nxt = -(-c // 4)                               # ceil(c / 4)
        nxt = -(-nxt // bucket) * bucket + bucket
        if nxt >= c:
            break
        c = nxt
    return tuple(reversed(rungs))


@dataclass(eq=False)
class FusedSpanExec(P.PhysicalPlan):
    """One adaptive exchange + consumer pair compiled as a single
    on-device span — the whole-query fusion building block (the XLA-
    native Flare move, arXiv 1703.08219: compile the operator boundary
    away instead of interpreting it).

    The staged path runs FOUR dispatches with a host sync in the
    middle: producer stage, ExchangeStatsExec stage + host fetch of
    2*d int64s, the exchange re-run at the measured capacity, then the
    re-traced consumer stage. Here the SAME stats computation
    (seg_count of the routing targets, psum across the mesh) stays on
    device and a ``lax.switch`` over the capacity ladder picks the
    rung: each branch runs the collective exchange at ITS rung's
    slice/receive capacities, traces the consumer there, and pads the
    result back to the common worst-case shape. Putting the collective
    inside the branches is safe because the branch index derives from
    psum'd counts — replicated bit-identically across the mesh — so
    every device provably takes the same branch and the all_to_all
    pairs up; it is what lets the fused program ship rung-sized ICI
    buffers instead of worst-case ones, matching the staged path's
    measured compaction to within one ladder step (4x).

    Byte-identity with the staged path holds because every transform
    is order-stable: the exchange's live-row sequence is independent
    of slice/out capacity (stable argsort-by-destination + stable
    compaction), the whitelisted consumers (SortExec, DistSortAggExec)
    are capacity-preserving and capacity-independent on live rows, and
    the padding rows are masked dead — collect never sees them. The
    executor only builds this node when the pair's ONLY adaptive
    decision is capacity; anything host-bound (skew fan, agg strategy
    crossover, sort elision) bails out to staged execution first
    (executor._try_fuse)."""

    #: the consumer node, child == ``exchange`` (kept nested so schema
    #: derivation and plan keys need no placeholder surgery; trace()
    #: feeds it pipes directly and never walks the child link)
    consumer: P.PhysicalPlan
    #: the adaptive exchange (hash/range/round-robin), child == producer
    exchange: P.PhysicalPlan
    #: capacity-ladder base (spark.tpu.adaptive.capacityBucket)
    bucket: int
    #: max ladder rungs (spark.tpu.fusion.maxBucketVariants)
    variants: int
    #: downstream chain operators applied INSIDE this span's branches,
    #: in dataflow order: row-preserving interstitials (Project/Filter)
    #: and further FusedSpanExec pairs. Nesting the downstream pairs
    #: inside the upstream branches is what keeps every intermediate
    #: shape RUNG-sized: the chained span's routing (target hashing,
    #: range sampling, argsort) traces over the selected rung's
    #: capacity instead of the worst-case padding — only the single
    #: final leaf pads to the chain's common output shape. An empty
    #: tail is a plain one-pair span.
    tail: Tuple[P.PhysicalPlan, ...] = ()
    #: speculative rung-sized OUTPUT, set by the executor only when
    #: this span is the plan root (nothing above that could touch the
    #: sentinel row). Instead of padding the leaves to the worst case
    #: — which makes output materialization and collection scale with
    #: a capacity real loads never reach — the leaves emit at the
    #: ladder anchor (+12.5% sampling margin) plus ONE sentinel slot
    #: whose mask bit says "live rows were sliced off". The executor
    #: reads the sentinel from the mask it fetches anyway; when set it
    #: discards the result and re-runs the staged path (typed
    #: ``overflow`` bailout), so byte-identity is preserved without
    #: worst-case-shaped outputs.
    speculate: bool = False
    traceable = True

    def children(self):
        return self.exchange.children()

    @property
    def schema(self) -> Schema:
        return self.tail[-1].schema if self.tail else self.consumer.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        d = X.axis_size()
        # a producer padded to ITS worst case (an unmerged upstream
        # fused span) carries a tighter total-live-rows bound than
        # d * capacity — using it keeps chained buffers at
        # O(total rows) instead of O(d^k * rows)
        worst0 = d * pipe.capacity
        if pipe.rows_bound is not None:
            worst0 = min(worst0, int(pipe.rows_bound))
        ladder0 = capacity_ladder(self.bucket, self.variants, worst0, d)
        spec = self.speculate
        if spec and len(ladder0) > 1:
            # speculative output capacity: the ladder anchor plus a
            # 12.5% sampling margin (range-exchange bounds come from
            # samples; a hot destination can land a few percent past
            # balanced without being genuinely skewed). A single-rung
            # ladder keeps the worst-case shape — the sentinel is then
            # constant-dead and the executor check is trivially false
            b = max(1, int(self.bucket))
            f_out = min(worst0,
                        -(-(ladder0[-2] * 9 // 8) // b) * b)
        else:
            f_out = worst0
        meta: dict = {}

        def leaf(out: Pipe):
            # every nested switch path returns this one common shape:
            # f_out slots plus (speculating) one sentinel slot whose
            # mask bit records that live rows were sliced off — the
            # executor turns that into a staged re-run. Host-side
            # capture at switch-build time: every leaf traces eagerly,
            # so the dtype/dictionary metadata the pytree return
            # strips is available to rebuild the Pipe
            if out.capacity > f_out:
                over = jnp.any(out.mask[f_out:])
                out = _slice_pipe(out, f_out)
            else:
                over = jnp.zeros((), dtype=jnp.bool_)
            out = _pad_pipe(out, f_out + 1 if spec else f_out)
            mask = out.mask.at[f_out].set(over) if spec else out.mask
            meta.setdefault("order", tuple(out.order))
            meta.setdefault("tv", {n: (tv.dtype, tv.dictionary)
                                   for n, tv in out.cols.items()})
            return (mask,
                    {n: (out.cols[n].data, out.cols[n].validity)
                     for n in out.order})

        def run_ops(p: Pipe, ops):
            if not ops:
                return leaf(p)
            op, rest = ops[0], ops[1:]
            if isinstance(op, FusedSpanExec):
                return pair(p, op, rest)
            return run_ops(op.trace([p]), rest)

        def pair(p: Pipe, span: "FusedSpanExec", rest):
            # the staged ExchangeStatsExec computation, kept on
            # device: per-destination live counts, psum'd — max over
            # destinations is exactly the staged path's measured
            # out-capacity input
            target = span.exchange._target(p, d)
            local = K.seg_count(
                jnp.clip(target, 0, d - 1).astype(jnp.int32), p.mask, d)
            max_in = jnp.max(X.psum(local).astype(jnp.int64))
            # total live rows through the chain never grow (the
            # whitelisted consumers are Sort/DistSortAgg, interstitials
            # Project/Filter), so worst0 bounds every downstream span
            ladder = capacity_ladder(span.bucket, span.variants,
                                     min(d * p.capacity, worst0), d)

            def rung(ocap: int):
                def branch(_):
                    # collective INSIDE the branch, at the rung's
                    # capacities: one sender's slice to a destination
                    # can never exceed that destination's total
                    # incoming rows, so min(cap, ocap) is a safe slice
                    # bound whenever the receive rung ocap covers the
                    # measured max_in — which branch selection
                    # guarantees
                    sub = X.exchange(p, target,
                                     min(p.capacity, ocap), ocap)
                    return run_ops(span.consumer.trace([sub]), rest)
                return branch

            arr = jnp.asarray(ladder, dtype=jnp.int64)
            idx = jnp.clip(jnp.sum((arr < max_in).astype(jnp.int32)),
                           0, len(ladder) - 1)
            return jax.lax.switch(idx, [rung(c) for c in ladder], 0)

        mask, flat = pair(pipe, self, tuple(self.tail))
        cols = {n: TV(flat[n][0], flat[n][1], *meta["tv"][n])
                for n in meta["order"]}
        # row counts never grow through the chain, so total live rows
        # out <= total live rows in <= worst0
        return Pipe(cols, mask, list(meta["order"]), rows_bound=worst0)

    def node_string(self):
        chain = "".join(" -> " + (t.consumer.node_string()
                                  if isinstance(t, FusedSpanExec)
                                  else t.node_string())
                        for t in self.tail)
        return (f"FusedSpan[bucket={self.bucket}, "
                f"variants={self.variants}, "
                f"consumer={self.consumer.node_string()}{chain}]")

    def plan_key(self):
        # structural fingerprint of the WHOLE fused span plus the
        # bucket-ladder parameters: the jit stage cache and the
        # compile-store digest both key on this, so a conf change to
        # the ladder recompiles instead of replaying a mismatched
        # executable
        return ("FusedSpan", self.bucket, self.variants,
                self.speculate, self.consumer.plan_key(),
                self.exchange.plan_key(),
                tuple(t.plan_key() for t in self.tail))


def _slice_pipe(pipe: Pipe, capacity: int) -> Pipe:
    """Truncate a pipe to its first ``capacity`` slots (live rows past
    the cut are LOST — callers must detect that and fall back; see
    FusedSpanExec speculative output)."""
    cols = {
        name: TV(tv.data[:capacity],
                 None if tv.validity is None else tv.validity[:capacity],
                 tv.dtype, tv.dictionary)
        for name, tv in pipe.cols.items()
    }
    return Pipe(cols, pipe.mask[:capacity], pipe.order)


def _pad_pipe(pipe: Pipe, capacity: int) -> Pipe:
    """Grow a pipe to ``capacity`` slots with dead rows (mask False, so
    collect and every mask-respecting consumer ignore them). Needed so
    all ladder branches return one common static shape."""
    cap = pipe.capacity
    if cap >= int(capacity):
        return pipe
    n = int(capacity) - cap

    def grow(a, fill):
        pad = ((0, n),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, pad, constant_values=fill)

    cols = {
        name: TV(grow(tv.data, 0),
                 None if tv.validity is None else grow(tv.validity, False),
                 tv.dtype, tv.dictionary)
        for name, tv in pipe.cols.items()
    }
    return Pipe(cols, grow(pipe.mask, False), pipe.order)


@dataclass(eq=False)
class DistHashPartialAggExec(P.PhysicalPlan):
    """Hash-based partial aggregation over a RUNTIME-MEASURED key
    domain: the stats stage measured each key's global [min, max] (and
    nulls-present), so keys range-compress to collision-free packed
    codes and the partials are dense segment reductions over
    num_segments = the measured domain — no sort, no host sync, and
    the reductions route through the measured selection table
    (<= 64 XLA fused, 64 < K <= 1024 the Pallas one-pass kernel; see
    ops/pallas_agg.py). This is the runtime analogue of the static
    direct path in physical/operators.HashAggregateExec, unlocked for
    int keys whose cardinality only the data knows.

    Output schema/order contract: identical to the sort-based partial
    (key aliases + partial accumulators), so the downstream exchange
    and final merge are strategy-oblivious. Per-group values are
    byte-identical to the sort partial for strategy-legal aggregates
    (legality.strategy_verdict); only row order and capacity differ,
    and the final merge re-groups anyway."""

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: P.PhysicalPlan
    key_mins: Tuple[int, ...] = ()    # measured per-key global min
    key_ranges: Tuple[int, ...] = ()  # measured value range (max-min+1)
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return P.HashAggregateExec(self.groupings, self.aggregates,
                                   self.child).schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        cap = pipe.capacity
        env = pipe.env()
        key_tvs = [C.evaluate(g, env) for g in self.groupings]

        codes, validities, cards = [], [], []
        for tv, mn, rg in zip(key_tvs, self.key_mins, self.key_ranges):
            # range compression: measured min/range make the clip a
            # no-op for every live row (the measurement ran over these
            # exact arrays); pack_codes adds the null slot per key
            codes.append(jnp.clip(tv.data.astype(jnp.int64) - mn, 0,
                                  rg - 1))
            validities.append(tv.validity)
            cards.append(int(rg))
        seg, num_segments = K.pack_codes(codes, validities, cards)
        seg = seg.astype(jnp.int32)
        num_segments = max(1, int(num_segments))

        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [P._compute_agg(a, env, seg, pipe.mask, num_segments,
                                  cap)
                   for a in agg_calls]

        # LOCAL partials: each device keeps its own groups (no psum) —
        # the downstream exchange routes them to the final merge
        out_mask = K.seg_count(seg, pipe.mask, num_segments) > 0
        nullable = [v is not None for v in validities]
        unpacked = K.unpack_code(jnp.arange(num_segments), cards, nullable)
        out_keys = []
        for (code, valid), tv, mn in zip(unpacked, key_tvs,
                                         self.key_mins):
            data = (code + mn).astype(C._jnp_dtype(tv.dtype))
            out_keys.append(TV(data, valid, tv.dtype, tv.dictionary))
        agg_exec = P.HashAggregateExec(self.groupings, self.aggregates,
                                       self.child)
        return agg_exec._finalize(out_keys, agg_tvs, out_mask,
                                  num_segments)

    def node_string(self):
        return (f"DistHashPartialAgg[keys="
                f"[{', '.join(map(str, self.groupings))}], "
                f"domain={tuple(self.key_ranges)}]")

    def plan_key(self):
        return ("DistHashPartialAgg",
                tuple(E.expr_key(g) for g in self.groupings),
                tuple(E.expr_key(a) for a in self.aggregates),
                self.key_mins, self.key_ranges, self.child.plan_key())


# ---- distributed join -------------------------------------------------------


@dataclass(eq=False)
class _SchemaLeaf(P.PhysicalPlan):
    leaf_schema: Schema
    traceable = True

    @property
    def schema(self) -> Schema:
        return self.leaf_schema


def join_output_schema(left: Schema, right: Schema, how: str) -> Schema:
    return P.JoinExec(_SchemaLeaf(left), _SchemaLeaf(right), how, (), ()).schema


def packed_join_keys(lpipe: Pipe, rpipe: Pipe,
                     left_keys: Tuple[E.Expression, ...],
                     right_keys: Tuple[E.Expression, ...],
                     mins, ranges):
    """Pack equi-join keys into one int64 per row using STATIC per-key
    min/range stats (host-supplied from a stats pass — the AQE runtime
    statistics pattern, reference: adaptive/AdaptiveSparkPlanExec.scala:247).
    Strings pack via trace-time unified dictionaries. Collision-free by
    construction, unlike hashing. ``mins is None`` switches to the
    hash-combined fallback (wide int64 ranges); callers must then verify
    candidate pairs by exact key equality. Returns
    (lkey, lvalid, rkey, rvalid, prepped) where prepped holds the
    translated per-key arrays for verification."""
    hashed = mins is None
    lenv, renv = lpipe.env(), rpipe.env()
    lks = [C.evaluate(k, lenv) for k in left_keys]
    rks = [C.evaluate(k, renv) for k in right_keys]
    lcomb = jnp.zeros((lpipe.capacity,), dtype=jnp.int64)
    rcomb = jnp.zeros((rpipe.capacity,), dtype=jnp.int64)
    lvalid = jnp.ones((lpipe.capacity,), dtype=jnp.bool_)
    rvalid = jnp.ones((rpipe.capacity,), dtype=jnp.bool_)
    prepped = []
    for ki, (lt, rt) in enumerate(zip(lks, rks)):
        if isinstance(lt.dtype, T.StringType) or isinstance(rt.dtype, T.StringType):
            _, (tl, tr) = C.unify_dictionaries(
                (lt.dictionary or (), rt.dictionary or ()))
            ld = jnp.asarray(tl)[lt.data] if len(lt.dictionary or ()) else lt.data
            rd = jnp.asarray(tr)[rt.data] if len(rt.dictionary or ()) else rt.data
        else:
            ld = lt.data.astype(jnp.int64)
            rd = rt.data.astype(jnp.int64)
        prepped.append((ld, rd))
        if not hashed:
            mn, rg = mins[ki], ranges[ki]
            lcomb = lcomb * rg + jnp.clip(ld - mn, 0, rg - 1)
            rcomb = rcomb * rg + jnp.clip(rd - mn, 0, rg - 1)
        if lt.validity is not None:
            lvalid = lvalid & lt.validity
        if rt.validity is not None:
            rvalid = rvalid & rt.validity
    if hashed:
        lcomb, rcomb = P._hash_keys([p[0] for p in prepped],
                                    [p[1] for p in prepped])
    return lcomb, lvalid, rcomb, rvalid, prepped


@dataclass(eq=False)
class TopKeyExec(P.PhysicalPlan):
    """Per-device heavy-hitter probe: the most frequent key tuple in
    the device's local shard, with its local count (one output row per
    device). The detection pass for AQE skew SPLIT — the reference
    detects skew from shuffle-partition SIZES
    (adaptive/OptimizeSkewedJoin.scala:37); here row distribution is
    uniform by construction (row-sliced shards), so the hot KEY VALUE
    is detected instead and the executor splits the join around it."""

    keys: Tuple[E.Expression, ...]
    child: P.PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for i, k in enumerate(self.keys):
            inner = E.strip_alias(k)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            fields.append(Field(f"__hk{i}", k.data_type(cs), True,
                                dictionary))
        fields.append(Field("__cnt", T.INT64, nullable=False))
        return Schema(tuple(fields))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        cap = pipe.capacity
        env = pipe.env()
        key_tvs = [C.evaluate(k, env) for k in self.keys]
        spipe, sorted_keys, seg, _ = P.sorted_groups(pipe, key_tvs)
        cnt = K.seg_count(seg, spipe.mask, cap, sorted_seg=True)
        best = jnp.argmax(cnt)
        reps = P.first_group_keys(sorted_keys, seg, spipe.mask, cap, cap,
                                  sorted_seg=True)
        cols: Dict[str, TV] = {}
        order = []
        for i, tv in enumerate(reps):
            nm = f"__hk{i}"
            cols[nm] = TV(tv.data[best][None],
                          None if tv.validity is None
                          else tv.validity[best][None],
                          tv.dtype, tv.dictionary)
            order.append(nm)
        cols["__cnt"] = TV(cnt[best][None].astype(jnp.int64), None,
                           T.INT64, None)
        order.append("__cnt")
        return Pipe(cols, jnp.ones((1,), jnp.bool_), order)

    def node_string(self):
        return f"TopKey[{', '.join(map(str, self.keys))}]"

    def plan_key(self):
        return ("TopKey", tuple(E.expr_key(k) for k in self.keys),
                self.child.plan_key())


@dataclass(eq=False)
class JoinCountExec(P.PhysicalPlan):
    """Stats pass: per-device equi-join match count (capacity sizing for
    JoinApplyExec). Output: one int64 per device."""

    left: P.PhysicalPlan
    right: P.PhysicalPlan
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    mins: Tuple[int, ...]
    ranges: Tuple[int, ...]
    broadcast: bool
    traceable = True

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        return Schema((Field("cnt", T.INT64, nullable=False),))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        lpipe, rpipe = child_pipes
        if self.broadcast:
            rpipe = X.broadcast_gather(rpipe)
        lkey, lvalid, rkey, rvalid, _ = packed_join_keys(
            lpipe, rpipe, self.left_keys, self.right_keys,
            self.mins, self.ranges)
        rng = K.build_join_ranges(rkey, rpipe.mask & rvalid,
                                  lkey, lpipe.mask & lvalid)
        cnt = jnp.where(lpipe.mask & lvalid, rng.counts, 0).sum(
            dtype=jnp.int64)
        return Pipe({"cnt": TV(cnt[None], None, T.INT64, None)},
                    jnp.ones((1,), jnp.bool_), ["cnt"])

    def plan_key(self):
        return ("JoinCount", tuple(E.expr_key(k) for k in self.left_keys),
                tuple(E.expr_key(k) for k in self.right_keys),
                self.mins, self.ranges, self.broadcast,
                self.left.plan_key(), self.right.plan_key())


@dataclass(eq=False)
class JoinApplyExec(P.PhysicalPlan):
    """Per-device equi-join with a STATIC pair capacity (host-synced from
    JoinCountExec). After a hash exchange both sides of a key group are
    co-resident, so device-local sorted-build + searchsorted ranges +
    vectorized pair expansion produce exactly the reference's shuffled
    hash join semantics (ShuffledHashJoinExec.scala:38) — or, with
    broadcast=True, the broadcast hash join (BroadcastHashJoinExec.scala:40)."""

    left: P.PhysicalPlan
    right: P.PhysicalPlan
    how: str
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    condition: Optional[E.Expression]
    mins: Tuple[int, ...]
    ranges: Tuple[int, ...]
    pair_capacity: int
    broadcast: bool
    traceable = True

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        return join_output_schema(self.left.schema, self.right.schema,
                                  self.how)

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        lpipe, rpipe = child_pipes
        how = self.how
        if self.broadcast:
            rpipe = X.broadcast_gather(rpipe)
        if how == "cross":
            return self._cross(lpipe, rpipe)

        lkey, lvalid, rkey, rvalid, prepped = packed_join_keys(
            lpipe, rpipe, self.left_keys, self.right_keys,
            self.mins, self.ranges)
        hashed = self.mins is None
        ranges = K.build_join_ranges(rkey, rpipe.mask & rvalid,
                                     lkey, lpipe.mask & lvalid)

        if how in ("left_semi", "left_anti") and self.condition is None \
                and not hashed:
            has_match = ranges.counts > 0
            keep = lpipe.mask & (has_match if how == "left_semi"
                                 else ~has_match)
            return Pipe(lpipe.cols, keep, lpipe.order)

        cap = self.pair_capacity
        p_idx, b_idx, pair_mask = K.expand_join_pairs(ranges, cap)
        if hashed:
            pair_mask = pair_mask & P._verify_key_pairs(
                prepped, p_idx, b_idx, cap)

        # pair env always carries BOTH sides so semi/anti conditions can
        # reference the inner relation (names match Join.schema dedup)
        pair_names = P._pair_names(lpipe.order, rpipe.order)
        lnames = list(lpipe.order)
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_name, src_name in zip(pair_names[:len(lnames)], lnames):
            tv = lpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)
        for out_name, src_name in zip(pair_names[len(lnames):],
                                      rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)

        pair_ok = pair_mask
        if self.condition is not None:
            ctv = C.evaluate(self.condition, Env(cols, cap))
            pair_ok = pair_ok & ctv.data & ctv.valid_or_true(cap)

        if how == "inner":
            return Pipe(cols, pair_ok, order)

        matched = K.seg_count(p_idx, pair_ok, lpipe.capacity) > 0
        if how == "left_semi":
            return Pipe(lpipe.cols, lpipe.mask & matched, lpipe.order)
        if how == "left_anti":
            return Pipe(lpipe.cols, lpipe.mask & ~matched, lpipe.order)
        matched_b = (K.seg_count(b_idx, pair_ok, rpipe.capacity) > 0
                     if how in ("right", "full") else None)

        mask = pair_ok
        if how in ("left", "full"):
            cols, mask, order, _ = P.append_unmatched_left(
                cols, mask, order, lpipe, matched)
        if how in ("right", "full"):
            if self.broadcast:
                raise AssertionError(
                    "right/full outer join must not broadcast the build side")
            cols, mask, order, _ = P.append_unmatched_right(
                cols, mask, order, lpipe, rpipe, matched_b)
        return Pipe(cols, mask, order)

    def _cross(self, lpipe: Pipe, rpipe: Pipe) -> Pipe:
        """pair_capacity = per-device left capacity * global live right
        rows (host-computed)."""
        cap = self.pair_capacity
        rn = max(1, cap // max(1, lpipe.capacity))
        j = jnp.arange(cap)
        p_idx = jnp.clip(j // rn, 0, lpipe.capacity - 1)
        rperm = K.compaction_permutation(rpipe.mask)
        b_idx = rperm[jnp.clip(j % rn, 0, rpipe.capacity - 1)]
        live_r = jnp.cumsum(rpipe.mask.astype(jnp.int64))[-1]
        pair_mask = lpipe.mask[p_idx] & ((j % rn) < live_r)

        out_schema = self.schema
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_f, src_name in zip(out_schema.fields[:len(lpipe.order)],
                                   lpipe.order):
            tv = lpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        for out_f, src_name in zip(out_schema.fields[len(lpipe.order):],
                                   rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        if self.condition is not None:
            ctv = C.evaluate(self.condition, Env(cols, cap))
            pair_mask = pair_mask & ctv.data & ctv.valid_or_true(cap)
        return Pipe(cols, pair_mask, order)

    def node_string(self):
        ks = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys,
                                                  self.right_keys))
        tag = "broadcast" if self.broadcast else "partitioned"
        return f"DistJoin[{self.how}, {tag}, ({ks}), cond={self.condition}]"

    def plan_key(self):
        return ("JoinApply", self.how,
                tuple(E.expr_key(k) for k in self.left_keys),
                tuple(E.expr_key(k) for k in self.right_keys),
                None if self.condition is None else E.expr_key(self.condition),
                self.mins, self.ranges, self.pair_capacity, self.broadcast,
                self.left.plan_key(), self.right.plan_key())


@dataclass(eq=False)
class DistJoinBoundary(P.PhysicalPlan):
    """Planner marker: a join that the executor lowers into (exchange) +
    stats + count + apply stage programs. Not traceable — it is a stage
    boundary, exactly where the reference's DAGScheduler cuts stages
    (DAGScheduler.scala:1355 submitStage at ShuffleDependency edges)."""

    left: P.PhysicalPlan
    right: P.PhysicalPlan
    how: str
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    condition: Optional[E.Expression]
    traceable = False

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        if self.how in ("left_semi", "left_anti"):
            return self.left.schema
        return join_output_schema(self.left.schema, self.right.schema,
                                  self.how)

    def node_string(self):
        return f"JoinBoundary[{self.how}]"

    def plan_key(self):
        return ("JoinBoundary", self.how,
                tuple(E.expr_key(k) for k in self.left_keys),
                tuple(E.expr_key(k) for k in self.right_keys),
                None if self.condition is None else E.expr_key(self.condition),
                self.left.plan_key(), self.right.plan_key())

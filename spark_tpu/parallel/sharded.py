"""Sharded columnar batches: the distributed dataset representation.

The analogue of an RDD's partition set materialized in a BlockManager
(reference: core/.../rdd/RDD.scala, storage/BlockManager.scala:172) —
but instead of N partition objects scattered over executor JVM heaps,
a ShardedBatch is ONE logical set of flat device arrays laid out as
``(D * per_device_capacity,)`` and sharded over the mesh's ``data``
axis, so device d owns the contiguous slice d. XLA sees global arrays,
shard_map programs see the local slice — partition-count independence
falls out of the sharding instead of a partitioner class.

Row order convention: the flat array order IS the global row order.
Range-partitioned (sorted) outputs therefore read back correctly by
construction; unordered inputs are dealt round-robin for balance.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_tpu.columnar.batch import Batch, BatchData, ColumnData
from spark_tpu.parallel.mesh import DATA_AXIS, mesh_size
from spark_tpu.physical.kernels import bucket
from spark_tpu.types import Schema


class ShardedBatch:
    """schema + BatchData whose arrays are (D*cap,) sharded on ``data``."""

    __slots__ = ("schema", "data", "mesh", "per_device_capacity",
                 "sorted_by")

    def __init__(self, schema: Schema, data: BatchData, mesh: Mesh,
                 sorted_by=None):
        self.schema = schema
        self.data = data
        self.mesh = mesh
        d = mesh_size(mesh)
        total = int(data.row_mask.shape[0])
        assert total % d == 0, (total, d)
        self.per_device_capacity = total // d
        #: global order guarantee, or None: a tuple of
        #: (column_name, ascending, nulls_first) the FLAT ROW ORDER of
        #: this batch already satisfies across the whole mesh (e.g. the
        #: sort-based aggregation rung's range-partitioned, locally
        #: sorted output). Consumers (the executor's sort/range-
        #: exchange elision) may skip a global sort whose orders are a
        #: prefix-compatible match; purely advisory — dropping it is
        #: always correct.
        self.sorted_by = sorted_by

    @property
    def capacity(self) -> int:
        return int(self.data.row_mask.shape[0])

    def num_valid_rows(self) -> int:
        return int(np.asarray(self.data.row_mask).sum())

    @classmethod
    def from_batch(cls, batch: Batch, mesh: Mesh,
                   per_device_capacity: Optional[int] = None,
                   ) -> "ShardedBatch":
        """Split rows into contiguous blocks (device d owns source rows
        [d*p, (d+1)*p)) so the flat-order convention holds from the
        start — limit/first/show agree with the single-device engine.
        Source batches are live-prefix-packed (from_arrow/from_numpy), so
        contiguous blocks are also balanced; re-balancing of filtered
        intermediates is RoundRobinExchangeExec's job."""
        d = mesh_size(mesh)
        n = batch.capacity
        p = per_device_capacity or bucket(math.ceil(n / d), 128)
        src = np.arange(min(n, d * p))
        dest = src

        mask_np = np.zeros((d * p,), dtype=bool)
        mask_np[dest] = np.asarray(batch.data.row_mask)[src]
        sharding = NamedSharding(mesh, P(DATA_AXIS))

        cols = []
        for cd in batch.data.columns:
            data_np = np.zeros((d * p,), dtype=np.asarray(cd.data).dtype)
            data_np[dest] = np.asarray(cd.data)[src]
            validity = None
            if cd.validity is not None:
                v = np.zeros((d * p,), dtype=bool)
                v[dest] = np.asarray(cd.validity)[src]
                validity = jax.device_put(v, sharding)
            cols.append(ColumnData(jax.device_put(data_np, sharding),
                                   validity))
        return cls(batch.schema,
                   BatchData(tuple(cols),
                             jax.device_put(mask_np, sharding)),
                   mesh)

    def to_batch(self) -> Batch:
        """Gather to one host batch. Flat order = global row order."""
        cols = tuple(
            ColumnData(np.asarray(cd.data),
                       None if cd.validity is None else np.asarray(cd.validity))
            for cd in self.data.columns)
        import jax.numpy as jnp

        return Batch(self.schema,
                     BatchData(tuple(
                         ColumnData(jnp.asarray(c.data),
                                    None if c.validity is None
                                    else jnp.asarray(c.validity))
                         for c in cols),
                         jnp.asarray(np.asarray(self.data.row_mask))))

    def __repr__(self):
        return (f"ShardedBatch(D={mesh_size(self.mesh)}, "
                f"per_device={self.per_device_capacity}, "
                f"schema={list(self.schema.names)})")

"""The mesh executor: distributed planning + SPMD stage execution.

Replaces the whole reference control stack for a query — DAGScheduler
stage graph, TaskScheduler offers, executor task launch RPC, shuffle
fetch (reference: scheduler/DAGScheduler.scala:121 submitStage:1355,
TaskSchedulerImpl.scala:249, CoarseGrainedSchedulerBackend.scala:398) —
with: cut the plan at join boundaries, compile each cut to ONE
shard_map/jit SPMD program (exchanges ride inside as collectives), run
the programs in dependency order. "Task launch" is a single XLA
dispatch; there is nothing to serialize, offer, or fetch.

Join sizing follows the AQE pattern (reference:
adaptive/AdaptiveSparkPlanExec.scala:247 — materialize, look at stats,
re-plan): a stats pass gets key ranges, a count pass sizes the pair
capacity, then the join stage runs with static shapes.
"""

from __future__ import annotations

import contextvars
import dataclasses
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from spark_tpu import conf as CF
from spark_tpu import trace as _trace
from spark_tpu import types as T
from spark_tpu.columnar.batch import Batch
from spark_tpu.expr import expressions as E
from spark_tpu.parallel import operators as D
from spark_tpu.parallel.mesh import DATA_AXIS, mesh_size
from spark_tpu.parallel.sharded import ShardedBatch
from spark_tpu.physical import kernels as K
from spark_tpu.physical import operators as P
from spark_tpu.physical.operators import Pipe
from spark_tpu.plan import logical as L
from spark_tpu.types import Schema

_SPEC = PartitionSpec(DATA_AXIS)

#: jit cache for stage programs, keyed on (plan structure, mesh shape,
#: platform) — the CodeGenerator.compile cache analogue. Bounded:
#: spark.tpu.jit.stageCacheEntries, LRU beyond the cap.
from spark_tpu.storage.lru import LruDict  # noqa: E402

_DIST_STAGE_CACHE = LruDict("dist", CF.JIT_STAGE_CACHE_ENTRIES)

#: OOM-degradation override (recovery.py): a run that OOMed with
#: adaptive execution off retries once with it forced on — measured
#: post-exchange compaction is the cheapest rung of the ladder, ahead
#: of chunked re-planning. Contextvar, not conf: the retry must not
#: leak into concurrently scheduled queries sharing the session conf.
FORCE_ADAPTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "spark_tpu_force_adaptive", default=False)


#: the ONE HLL estimator (spark_tpu/sketch.py) — re-exported here so
#: existing callers (tests, physical/chunked.py historically) keep
#: resolving executor.hll_estimate
from spark_tpu.sketch import hll_estimate  # noqa: E402,F401

#: exchange kinds the AQE pass cuts into separate stages (broadcast /
#: single-partition exchanges use the all_gather data plane — there is
#: no (D, cap) routing buffer to shrink, so they stay fused)
_ADAPTIVE_EXCHANGES = (D.HashPartitionExchangeExec,
                       D.RoundRobinExchangeExec,
                       D.RangeExchangeExec)


def _exchange_op(ex: P.PhysicalPlan) -> str:
    if isinstance(ex, D.HashPartitionExchangeExec):
        return "hash"
    if isinstance(ex, D.RangeExchangeExec):
        return "range"
    if isinstance(ex, D.RoundRobinExchangeExec):
        return "roundrobin"
    return type(ex).__name__


def _count_exchange_nodes(plan: P.PhysicalPlan) -> int:
    n = int(isinstance(plan, _ADAPTIVE_EXCHANGES + (
        D.BroadcastExchangeExec, D.SinglePartitionExchangeExec)))
    return n + sum(_count_exchange_nodes(c) for c in plan.children())


def _exactly_remergeable(consumer: "D.DistSortAggExec",
                         schema: Schema) -> bool:
    """True when the consumer's aggregate list can be re-applied to its
    own output byte-identically — the precondition for the skew fan's
    pre-merge. The rule set (integer Sum associative under wraparound,
    non-float Min/Max order-free, everything else illegal) is shared
    with the static analyzer and incremental merges: see
    analysis/legality.py."""
    from spark_tpu.analysis import legality

    return bool(legality.remerge_verdict_cols(consumer.aggregates,
                                              schema))


def _project_sorted_by(sorted_by, exprs):
    """Translate a ShardedBatch ``sorted_by`` guarantee through a
    row-wise projection: every ordered column must survive (as a bare
    Col or Alias(Col)) under its projected name, else the guarantee is
    dropped — a partial translation would let a later sort elide on a
    prefix whose tie order the static plan resolves differently."""
    if not sorted_by:
        return None
    out = []
    for name, asc, nf in sorted_by:
        for e in exprs:
            c = E.strip_alias(e)
            if isinstance(c, E.Col) and c.col_name == name:
                out.append((e.name, asc, nf))
                break
        else:
            return None
    return tuple(out)


def _sorted_by_satisfies(sorted_by, orders) -> bool:
    """True when a batch's ``sorted_by`` guarantee makes a global sort
    by ``orders`` a no-op. Requires an EXACT pairwise match over the
    full tuple (bare Col orders, same ascending/nulls placement): equal
    length means the order is total over the guaranteed columns — on
    unique-key aggregate output there are no ties left for the skipped
    sort to break differently from the static plan."""
    if not sorted_by or len(orders) != len(sorted_by):
        return False
    for o, (name, asc, nf) in zip(orders, sorted_by):
        c = E.strip_alias(o.child)
        if not (isinstance(c, E.Col) and c.col_name == name):
            return False
        if bool(o.ascending) != bool(asc) \
                or bool(o.nulls_first_resolved) != bool(nf):
            return False
    return True


class _FusionOverflow(Exception):
    """A speculative fused program sliced off live rows (sentinel mask
    bit set): the load was genuinely skewed past the ladder anchor.
    The result is discarded and the staged path re-runs — byte
    identity is preserved, at double cost for the rare skewed query."""


class _FusionBailout(Exception):
    """A whole-query fusion attempt hit a decision that genuinely
    needs the host (typed ``reason`` lands in the ``fusion_bailout``
    metric event); execution degrades to the staged adaptive path."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(reason)


def _collect_fused(plan: P.PhysicalPlan,
                   out: List["D.FusedSpanExec"]) -> None:
    if isinstance(plan, D.FusedSpanExec):
        out.append(plan)
        for t in plan.tail:  # merged chains nest further pairs here
            if isinstance(t, D.FusedSpanExec):
                out.append(t)
    for c in plan.children():
        _collect_fused(c, out)


def _walk_plan(plan: P.PhysicalPlan):
    yield plan
    for c in plan.children():
        yield from _walk_plan(c)


@dataclass(eq=False)
class _ShardSlot(P.PhysicalPlan):
    """Leaf placeholder inside cached stage closures (mirror of
    planner._ScanSlot): schema only, data arrives as arguments."""

    scan_schema: Schema
    traceable = True

    @property
    def schema(self):
        return self.scan_schema


def _collect_shard_scans(plan: P.PhysicalPlan,
                         out: List[D.ShardScanExec]) -> None:
    if isinstance(plan, D.ShardScanExec):
        out.append(plan)
        return
    for c in plan.children():
        _collect_shard_scans(c, out)


def _strip_leaves(plan: P.PhysicalPlan) -> P.PhysicalPlan:
    if isinstance(plan, D.ShardScanExec):
        return _ShardSlot(plan.schema)
    fields = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        fields[f.name] = _strip_leaves(v) if isinstance(
            v, P.PhysicalPlan) else v
    return dataclasses.replace(plan, **fields)


def _fully_traceable(plan: P.PhysicalPlan) -> bool:
    if isinstance(plan, D.ShardScanExec):
        return True
    return (plan.traceable and not plan.has_blocking_exprs()
            and all(_fully_traceable(c) for c in plan.children()))


@dataclass(eq=False)
class _CompactExec(P.PhysicalPlan):
    """Shrink per-device capacity to a host-chosen static size (live rows
    compact to the front). The pressure valve between stages —
    CoalesceShufflePartitions analogue.

    ``sliced`` is the fast path for outputs whose live rows already sit
    within the first ``new_capacity`` slots on every device (exchange
    and fused-span outputs are front-compacted by construction — the
    compaction inside the exchange and the consumer both emit live rows
    first, and worst-case padding only appends dead rows). A plain
    slice then replaces the O(p log p) stable argsort over the PADDED
    capacity with an O(new_capacity) copy; live-row order is untouched,
    so the result is byte-identical. The caller proves slice-safety
    from the mask readback it already does (_maybe_compact)."""

    new_capacity: int
    child: P.PhysicalPlan
    sliced: bool = False
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        from spark_tpu.expr.compiler import TV

        pipe = child_pipes[0]
        if self.sliced:
            cols = {
                name: TV(tv.data[: self.new_capacity],
                         None if tv.validity is None
                         else tv.validity[: self.new_capacity],
                         tv.dtype, tv.dictionary)
                for name, tv in pipe.cols.items()
            }
            return Pipe(cols, pipe.mask[: self.new_capacity], pipe.order)
        perm = K.compaction_permutation(pipe.mask)
        idx = perm[: self.new_capacity]
        cols = {
            name: TV(tv.data[idx],
                     None if tv.validity is None else tv.validity[idx],
                     tv.dtype, tv.dictionary)
            for name, tv in pipe.cols.items()
        }
        return Pipe(cols, pipe.mask[idx], pipe.order)

    def plan_key(self):
        return ("Compact", self.new_capacity, self.sliced,
                self.child.plan_key())


def _row_width(schema: Schema) -> int:
    """Device bytes per row (data + validity) from the schema."""
    from spark_tpu.expr.compiler import _jnp_dtype

    width = 0
    for f in schema.fields:
        try:
            width += np.dtype(_jnp_dtype(f.dtype)).itemsize
        except Exception:
            width += 8
        if f.nullable:
            width += 1
    return width


def _estimated_bytes(sb) -> int:
    """Estimated device bytes of a join build side: total capacity x
    per-row width from the schema (the size estimate the reference takes
    from plan statistics, SizeInBytesOnlyStatsPlanVisitor)."""
    return int(sb.capacity) * _row_width(sb.schema)


def _decode_key_value(raw, field):
    """Device key value -> python literal (host side of the hot-key
    detection pass): dictionary codes decode to strings, dates/decimals
    to their python types, everything else to plain ints/floats."""
    if field.dictionary is not None:
        code = int(raw)
        return (field.dictionary[code]
                if 0 <= code < len(field.dictionary) else None)
    if isinstance(field.dtype, T.DateType):
        return T.days_to_date(int(raw))
    if isinstance(field.dtype, T.DecimalType):
        import decimal

        return decimal.Decimal(int(raw)).scaleb(-field.dtype.scale)
    if hasattr(raw, "item"):
        return raw.item()
    return raw


def _hot_key_pred(keys, hot) -> E.Expression:
    """OR over hot candidates of AND(key == literal)."""
    ors = None
    for vals in hot:
        ands = None
        for k, v in zip(keys, vals):
            c = E.Cmp("==", k, E.Literal(v))
            ands = c if ands is None else E.And(ands, c)
        ors = ands if ors is None else E.Or(ors, ands)
    return ors


def _null_any(keys) -> E.Expression:
    out = None
    for k in keys:
        c = E.IsNull(k)
        out = c if out is None else E.Or(out, c)
    return out


class MeshExecutor:
    """Plans and runs logical plans over a device mesh."""

    def __init__(self, mesh: Mesh, broadcast_threshold: Optional[int] = None,
                 conf=None):
        from spark_tpu import conf as _conf

        self.mesh = mesh
        self.d = mesh_size(mesh)
        self.conf = conf if conf is not None else _conf.RuntimeConf()
        #: bytes under which a join build side is broadcast (reference:
        #: SQLConf spark.sql.autoBroadcastJoinThreshold, in BYTES). The
        #: legacy row-count argument overrides when given (tests).
        self.broadcast_threshold = broadcast_threshold
        # weak keys: entries die with their Batch, and a live entry pins
        # its key so the mapping can never alias a recycled object
        import weakref

        self._relation_cache = weakref.WeakKeyDictionary()

    # ---- public entry points -----------------------------------------------

    def execute_logical(self, plan: L.LogicalPlan,
                        optimize: bool = True) -> Batch:
        from spark_tpu.plan.optimizer import optimize as opt

        lp = opt(plan) if optimize else plan
        return self.run(self.plan(lp)).to_batch()

    # ---- logical -> distributed physical -----------------------------------

    def plan(self, plan: L.LogicalPlan) -> P.PhysicalPlan:
        d = self.d
        if isinstance(plan, L.Relation):
            return D.ShardScanExec(self._shard_relation(plan.batch))
        if isinstance(plan, L.UnresolvedScan):
            return D.ShardScanExec(self._shard_relation(
                plan.source.read(plan.columns, plan.filters)))
        if isinstance(plan, L.Range):
            n = plan.num_rows
            p = K.bucket(math.ceil(max(1, n) / d), 128)
            return D.DistRangeExec(plan.start, plan.end, plan.step, n, p,
                                   plan.col_name)
        if isinstance(plan, L.Project):
            return P.ProjectExec(plan.exprs, self.plan(plan.child))
        if isinstance(plan, L.Filter):
            return P.FilterExec(plan.condition, self.plan(plan.child))
        if isinstance(plan, L.Sample):
            return D.DistSampleExec(plan.fraction, plan.seed,
                                    self.plan(plan.child))
        if isinstance(plan, L.Aggregate):
            return self._plan_aggregate(plan.groupings, plan.aggregates,
                                        self.plan(plan.child))
        if isinstance(plan, L.Distinct):
            cols = tuple(E.Col(n) for n in plan.schema.names)
            return self._plan_aggregate(cols, cols, self.plan(plan.child))
        if isinstance(plan, L.Sort):
            child = self.plan(plan.child)
            return P.SortExec(plan.orders,
                              D.RangeExchangeExec(plan.orders, child))
        if isinstance(plan, L.Limit):
            return D.DistLimitExec(plan.n, plan.offset, self.plan(plan.child))
        if isinstance(plan, L.SubqueryAlias):
            return self.plan(plan.child)
        if isinstance(plan, L.Repartition):
            child = self.plan(plan.child)
            if plan.keys:
                return D.HashPartitionExchangeExec(plan.keys, child)
            return D.RoundRobinExchangeExec(child)
        if isinstance(plan, L.Union):
            return P.UnionExec(self.plan(plan.left), self.plan(plan.right))
        if isinstance(plan, L.Join):
            return D.DistJoinBoundary(self.plan(plan.left),
                                      self.plan(plan.right), plan.how,
                                      plan.left_keys, plan.right_keys,
                                      plan.condition)
        if isinstance(plan, L.Window):
            # hash-exchange on the partition keys so every partition
            # lives whole on one device, then the ordinary local window
            # operator (reference: WindowExec.scala:87
            # requiredChildDistribution = ClusteredDistribution;
            # EnsureRequirements inserts the same shuffle)
            from spark_tpu.physical.window import WindowExec

            child = self.plan(plan.child)
            # exchanging on the key SET co-locates partitions for every
            # spec that uses the same keys in any order (the local
            # operator re-groups per spec anyway). DIFFERENT key sets
            # chain: one exchange + local window PER set, later stages
            # running over the previous stage's output — the same
            # cascade EnsureRequirements produces for mixed window
            # specs (WindowExec.scala:87 ClusteredDistribution)
            groups: list = []  # (frozen key set, keys, [exprs])
            for e in plan.window_exprs:
                p = E.strip_alias(e).partition_by
                fs = frozenset(E.expr_key(k) for k in p)
                for g in groups:
                    if g[0] == fs:
                        g[2].append(e)
                        break
                else:
                    groups.append((fs, p, [e]))
            cur = child
            for _, keys, exprs in groups:
                ex = (D.HashPartitionExchangeExec(tuple(keys), cur)
                      if keys else D.SinglePartitionExchangeExec(cur))
                cur = WindowExec(tuple(exprs), ex)
            if len(groups) > 1:
                # restore the logical output column order (window cols
                # were appended per chained stage)
                cur = P.ProjectExec(
                    tuple(E.Col(n) for n in plan.schema.names), cur)
            return cur
        raise NotImplementedError(
            f"no distributed plan for {type(plan).__name__}")

    def _plan_aggregate(self, groupings, aggregates,
                        child: P.PhysicalPlan) -> P.PhysicalPlan:
        from spark_tpu.physical.operators import rewrite_agg_outputs

        _, agg_calls = rewrite_agg_outputs(groupings, aggregates)
        distinct_aggs = [a for a in agg_calls
                         if getattr(a, "distinct", False)]
        if distinct_aggs and not groupings:
            # Global DISTINCT: exchange on the distinct child so each
            # value lives on exactly one device, then psum the deduped
            # partials (reference: RewriteDistinctAggregates.scala:1
            # plans an extra shuffle level; here it is one hash
            # exchange). All DISTINCT aggs must share one child set.
            key_sets = {tuple(E.expr_key(c) for c in a.children())
                        for a in distinct_aggs}
            if len(key_sets) > 1:
                # SPLIT per distinct child set (the reference rewrites
                # through an Expand, RewriteDistinctAggregates.scala:1;
                # here each set gets its OWN exchange+psum sub-aggregate
                # and the 1-row results cross-join back together)
                return self._plan_multi_distinct(groupings, aggregates,
                                                 agg_calls, child)
            ex = D.HashPartitionExchangeExec(
                tuple(distinct_aggs[0].children()), child)
            return D.PSumAggExec(groupings, aggregates, ex)
        probe = P.HashAggregateExec(groupings, aggregates, child)
        if not distinct_aggs and (probe._static_direct_ok() or not groupings):
            # no shuffle: local partial + psum merge
            return D.PSumAggExec(groupings, aggregates, child)
        if not distinct_aggs:
            # map-side combine (reference: AggUtils partial/final split):
            # local partial aggregation BEFORE the exchange collapses a
            # hot key to ONE row per device — a 90%-one-key distribution
            # exchanges D rows instead of the whole table (the skew
            # guard OptimizeSkewedJoin provides for joins).
            from spark_tpu.plan.incremental import AggSpec

            try:
                spec = AggSpec(tuple(groupings), tuple(aggregates))
            except NotImplementedError:
                spec = None
            if spec is not None:
                key_aliases = tuple(
                    E.Alias(g, n) for g, n
                    in zip(spec.groupings_exec, spec.key_names))
                partial = D.DistSortAggExec(
                    tuple(spec.groupings_exec),
                    key_aliases + tuple(spec.partials), child,
                    phase="partial")
                ex = D.HashPartitionExchangeExec(
                    tuple(E.Col(n) for n in spec.key_names), partial)
                key_cols = tuple(E.Col(n) for n in spec.key_names)
                final = D.DistSortAggExec(
                    key_cols,
                    tuple(E.Alias(E.Col(n), n) for n in spec.key_names)
                    + tuple(spec.merges), ex)
                return P.ProjectExec(tuple(spec.outputs), final)
        # exchange on the grouping keys -> whole groups (and for DISTINCT
        # all their values) live on one device; local sort-agg is exact.
        ex = D.HashPartitionExchangeExec(tuple(groupings), child)
        return D.DistSortAggExec(groupings, aggregates, ex)

    def _plan_multi_distinct(self, groupings, aggregates, agg_calls,
                             child: P.PhysicalPlan) -> P.PhysicalPlan:
        """Global aggregate mixing DISTINCT aggregates over DIFFERENT
        columns (and any non-distinct aggregates): one exchange+psum
        sub-aggregate per distinct child set, cross-joined 1-row
        results, final projection restoring the output expressions
        (reference: RewriteDistinctAggregates.scala:1 Expand rewrite)."""
        from spark_tpu.physical.operators import rewrite_agg_outputs

        outputs, _ = rewrite_agg_outputs(groupings, aggregates)
        buckets: dict = {}  # child-key-set (or None) -> [(idx, call)]
        for i, call in enumerate(agg_calls):
            k = (tuple(E.expr_key(c) for c in call.children())
                 if getattr(call, "distinct", False) else None)
            buckets.setdefault(k, []).append((i, call))
        sub_plans = []
        for k, items in buckets.items():
            aliases = tuple(E.Alias(call, f"__agg{i}")
                            for i, call in items)
            if k is None:
                sub_plans.append(D.PSumAggExec((), aliases, child))
            else:
                ex = D.HashPartitionExchangeExec(
                    tuple(items[0][1].children()), child)
                sub_plans.append(D.PSumAggExec((), aliases, ex))
        combined = sub_plans[0]
        for sp in sub_plans[1:]:
            combined = D.DistJoinBoundary(combined, sp, "cross",
                                          (), (), None)
        return P.ProjectExec(tuple(outputs), combined)

    def _shard_relation(self, batch) -> ShardedBatch:
        if isinstance(batch, ShardedBatch):
            # already globally placed (multi-host addressable-shard
            # feeding, multihost.sharded_batch_from_local): every
            # process contributed its OWN rows — no host gathering, no
            # single-process placement assumptions
            return batch
        sb = self._relation_cache.get(batch)
        if sb is None:
            sb = ShardedBatch.from_batch(batch, self.mesh)
            self._relation_cache[batch] = sb
        return sb

    # ---- execution ----------------------------------------------------------

    def run(self, plan: P.PhysicalPlan) -> ShardedBatch:
        plan = self._materialize_boundaries(plan)
        if self._adaptive_enabled():
            if self._fusion_enabled():
                fused = self._try_fuse(plan)
                if fused is not None:
                    sb = self._run_fused(*fused)
                    if sb is not None:
                        return sb
                    # speculative overflow: fall through to staged
            plan = self._materialize_exchanges(plan)
        if isinstance(plan, D.ShardScanExec):
            return plan.sharded
        if not _fully_traceable(plan):
            raise NotImplementedError(
                "plan contains host-only (arrow UDF) expressions, which "
                "the mesh executor cannot trace; run on the "
                "single-device engine or use a jax UDF:\n"
                + plan.tree_string())
        return self._run_stage(plan)

    def _adaptive_enabled(self) -> bool:
        if FORCE_ADAPTIVE.get():
            return True
        try:
            return bool(self.conf.get(CF.ADAPTIVE_ENABLED))
        except Exception:
            return False

    def _fusion_enabled(self) -> bool:
        try:
            return bool(self.conf.get(CF.FUSION_ENABLED))
        except Exception:
            return False

    # ---- whole-query native fusion ------------------------------------------

    def _try_fuse(self, plan: P.PhysicalPlan):
        """Tentpole of the whole-query fusion pass: when every adaptive
        exchange in ``plan`` pairs with a consumer whose ONLY host
        dependency is the capacity stats fetch, rewrite the pairs into
        FusedSpanExec nodes so the whole multi-exchange plan compiles
        and runs as ONE XLA program with zero inter-stage host sync
        (the on-device lax.switch over the capacity ladder replaces the
        staged ExchangeStatsExec round-trip). Returns (plan', n_spans)
        or None — None means take the staged path, with a typed
        ``fusion_bailout`` event whenever a decision genuinely needed
        the host."""
        from spark_tpu import faults, metrics

        if not _fully_traceable(plan):
            return None  # both paths reject it; let staged raise
        if not any(isinstance(p, _ADAPTIVE_EXCHANGES)
                   for p in _walk_plan(plan)):
            return None  # nothing to fuse, nothing to bail out of
        if FORCE_ADAPTIVE.get():
            # the OOM-degradation retry wants the staged compaction
            # rungs — measured capacities, not worst-case fused buffers
            self._fusion_bailout("oom_ladder",
                                 "FORCE_ADAPTIVE retry in flight")
            return None
        try:
            fused, n_spans = self._fuse_rewrite(plan)
        except _FusionBailout as b:
            self._fusion_bailout(b.reason, b.detail)
            return None
        if isinstance(fused, D.FusedSpanExec):
            # root span: nothing above could consume the sentinel row,
            # so the program may emit a speculative rung-sized output
            # (overflow re-runs staged — see FusedSpanExec.speculate)
            fused = dataclasses.replace(fused, speculate=True)
        try:
            # fault seam: the plan is judged fusible, the span not yet
            # built — ANY kind degrades to staged execution (the fused
            # program is pure plan rewriting; staged computes the
            # identical bytes)
            faults.inject("fusion.decide", self.conf)
        except faults.InjectedFault as e:
            metrics.note_fusion("fault_fallbacks")
            metrics.record("fault_recovered", point="fusion.decide",
                           fault=e.kind, action="staged")
            self._fusion_bailout("fault_injected", e.kind)
            return None
        return fused, n_spans

    def _fuse_rewrite(self, plan: P.PhysicalPlan):
        """Rewrite adaptive exchange + consumer pairs into fused spans;
        raises _FusionBailout on the first host-required decision. Bare
        adaptive exchanges (no whitelisted consumer) stay inline — the
        non-adaptive engine already runs them at static capacity inside
        one program, byte-identically; they just skip the staged
        compaction (``_maybe_compact`` still shrinks the final output).
        Mirrors ``_materialize_exchanges``'s pair detection exactly, so
        a plan fuses if and only if the staged path would have made
        nothing but capacity decisions for it."""
        from spark_tpu.analysis import legality

        bucket = max(1, int(self.conf.get(CF.ADAPTIVE_CAPACITY_BUCKET)))
        variants = max(1, int(self.conf.get(CF.FUSION_MAX_BUCKET_VARIANTS)))
        spans = [0]

        def pair(consumer: P.PhysicalPlan,
                 ex: P.PhysicalPlan) -> "D.FusedSpanExec":
            producer = rewrite(ex.child)
            new_ex = dataclasses.replace(ex, child=producer)
            spans[0] += 1
            span = D.FusedSpanExec(
                consumer=dataclasses.replace(consumer, child=new_ex),
                exchange=new_ex, bucket=bucket, variants=variants)
            # chain merge: when this pair's producer is another fused
            # span reached only through row-preserving interstitials,
            # nest this pair INSIDE the upstream span's branches (its
            # ``tail``) instead of consuming the upstream's worst-case-
            # padded output — every intermediate stays rung-sized and
            # the chain still compiles to ONE switch tree / program
            inters: List[P.PhysicalPlan] = []
            node = producer
            while isinstance(node, (P.ProjectExec, P.FilterExec)):
                inters.append(node)
                node = node.child
            if isinstance(node, D.FusedSpanExec):
                return dataclasses.replace(
                    node, tail=node.tail + tuple(reversed(inters))
                    + (span,))
            return span

        def rewrite(p: P.PhysicalPlan) -> P.PhysicalPlan:
            if (isinstance(p, D.DistSortAggExec)
                    and isinstance(p.child, D.HashPartitionExchangeExec)):
                ex = p.child
                if (isinstance(ex.child, D.DistSortAggExec)
                        and ex.child.phase == "partial"
                        and ex.child.groupings
                        and self._agg_adaptive_enabled()
                        and legality.strategy_verdict(
                            ex.child.aggregates,
                            ex.child.child.schema).ok):
                    # a legal strategy crossover needs the host sketch
                    # fetch; a PINNED pair (float partials) has only
                    # the capacity decision left and falls through
                    raise _FusionBailout(
                        "agg_strategy",
                        "strategy crossover needs the host sketch fetch")
                if self.d > 1 and _exactly_remergeable(p, ex.child.schema):
                    # a re-mergeable merge could skew-fan: hot
                    # destinations are elected on the host and retraced
                    # with static fan_destinations
                    raise _FusionBailout(
                        "skew_presplit",
                        "re-mergeable consumer: destination skew fan "
                        "is a host decision")
                return pair(p, ex)
            if (isinstance(p, P.SortExec)
                    and isinstance(p.child, D.RangeExchangeExec)):
                ex = p.child
                sorted_by = None
                if isinstance(ex.child, D.ShardScanExec):
                    sorted_by = ex.child.sharded.sorted_by
                elif (isinstance(ex.child, P.ProjectExec)
                        and isinstance(ex.child.child, D.ShardScanExec)):
                    sorted_by = _project_sorted_by(
                        ex.child.child.sharded.sorted_by, ex.child.exprs)
                if sorted_by and _sorted_by_satisfies(sorted_by, p.orders):
                    # the staged path skips the whole Sort stage on the
                    # producer's order guarantee — a host metadata
                    # decision the fused program cannot make
                    raise _FusionBailout(
                        "sort_elide",
                        "producer order guarantee elides the sort")
                return pair(p, ex)
            fields = {}
            changed = False
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, P.PhysicalPlan):
                    nv = rewrite(v)
                    changed |= nv is not v
                    fields[f.name] = nv
                else:
                    fields[f.name] = v
            if isinstance(p, _ADAPTIVE_EXCHANGES):
                spans[0] += 1  # bare exchange, kept inline
            return dataclasses.replace(p, **fields) if changed else p

        return rewrite(plan), spans[0]

    def _fusion_bailout(self, reason: str, detail: str = "") -> None:
        from spark_tpu import metrics

        metrics.note_fusion("bailouts")
        metrics.record("fusion_bailout", reason=reason, detail=detail)

    def _run_fused(self, plan: P.PhysicalPlan,
                   n_spans: int) -> Optional[ShardedBatch]:
        from spark_tpu import metrics

        try:
            with _trace.span("stage.fused", spans=n_spans,
                             devices=self.d):
                sb = self._run_stage(plan)
        except _FusionOverflow:
            # the speculative output sliced off live rows: the load is
            # genuinely skewed past the ladder anchor — discard and
            # re-run staged (byte-identical, the skew fan and measured
            # capacities belong to the host there anyway)
            self._fusion_bailout(
                "overflow", "live rows past the speculative output "
                "capacity; staged re-run")
            return None
        metrics.note_fusion("fused_programs")
        metrics.note_fusion("fused_spans", n_spans)
        metrics.record("fusion", spans=n_spans, devices=self.d,
                       capacity=sb.per_device_capacity)
        metrics.set_gauge("fusion.last_spans", n_spans)
        metrics.set_gauge("fusion.last_devices", self.d)
        return sb

    # ---- adaptive execution (AQE over the mesh) -----------------------------

    def _materialize_exchanges(self, plan: P.PhysicalPlan) -> P.PhysicalPlan:
        """The AdaptiveSparkPlanExec loop (reference:
        adaptive/AdaptiveSparkPlanExec.scala:247 createQueryStages):
        cut the fused program at hash/range/round-robin exchange
        boundaries, run each producer side as its own stage, measure it
        (ExchangeStatsExec), and splice the exchanged result back in as
        a ShardScan leaf — so every consumer re-traces against the
        measured, bucket-rounded capacity instead of the static D*cap
        worst case. A final-merge aggregate sitting directly on its
        exchange is intercepted as a pair: that is where a skewed
        destination can fan + pre-merge (see _exchange_with_stats)."""
        if (isinstance(plan, D.DistSortAggExec)
                and isinstance(plan.child, D.HashPartitionExchangeExec)):
            if (isinstance(plan.child.child, D.DistSortAggExec)
                    and plan.child.child.phase == "partial"
                    and plan.child.child.groupings
                    and self._agg_adaptive_enabled()):
                return self._adaptive_aggregate(
                    final=plan, ex=plan.child, partial=plan.child.child)
            sb = self._run_adaptive_exchange(plan.child, consumer=plan)
            return dataclasses.replace(plan, child=D.ShardScanExec(sb))
        if (isinstance(plan, P.SortExec)
                and isinstance(plan.child, D.RangeExchangeExec)):
            # global sort = local sort over a range exchange. When the
            # exchange elides (the producer already carries a TOTAL
            # key order matching these exact orders — no ties for the
            # skipped shuffle to break differently), the local sort is
            # the identity on its prefix-packed input: skip the whole
            # Sort stage, not just the exchange
            sb = self._run_adaptive_exchange(plan.child)
            if _sorted_by_satisfies(sb.sorted_by, plan.orders):
                return D.ShardScanExec(sb)
            return dataclasses.replace(plan, child=D.ShardScanExec(sb))
        if isinstance(plan, _ADAPTIVE_EXCHANGES):
            return D.ShardScanExec(self._run_adaptive_exchange(plan))
        fields = {}
        changed = False
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, P.PhysicalPlan):
                nv = self._materialize_exchanges(v)
                changed |= nv is not v
                fields[f.name] = nv
            else:
                fields[f.name] = v
        return dataclasses.replace(plan, **fields) if changed else plan

    def _run_adaptive_exchange(self, ex: P.PhysicalPlan,
                               consumer=None) -> ShardedBatch:
        """Run the producer side of one exchange as its own stage, then
        the exchange itself under measured capacity bounds — unless the
        producer's batch already carries a ``sorted_by`` guarantee that
        satisfies a range exchange's orders (the sort-based aggregation
        rung's key-ordered output): then the whole global sort shuffle
        collapses to a no-op and the batch passes through."""
        from spark_tpu import metrics

        child = self._materialize_exchanges(ex.child)
        child_sb = self._producer_batch(child)
        if (isinstance(ex, D.RangeExchangeExec)
                and _sorted_by_satisfies(child_sb.sorted_by, ex.orders)):
            metrics.record("aqe", decision="sort_elide", op="range",
                           orders=tuple(s[0] for s in child_sb.sorted_by))
            metrics.note_agg("sort_elided")
            return child_sb
        return self._exchange_with_stats(ex, child_sb, consumer=consumer)

    def _producer_batch(self, child: P.PhysicalPlan) -> ShardedBatch:
        """Materialized producer plan -> ShardedBatch, carrying a
        ``sorted_by`` order guarantee through a row-wise projection of
        an already-ordered scan (projections are 1:1 and keep row
        order, so the guarantee survives under the projected names)."""
        if isinstance(child, D.ShardScanExec):
            return child.sharded
        sorted_by = None
        if (isinstance(child, P.ProjectExec)
                and isinstance(child.child, D.ShardScanExec)):
            sorted_by = _project_sorted_by(
                child.child.sharded.sorted_by, child.exprs)
        sb = self.run(child)
        if sorted_by:
            sb.sorted_by = sorted_by
        return sb

    def _exchange_with_stats(self, ex: P.PhysicalPlan,
                             child_sb: ShardedBatch, consumer=None,
                             allow_skew: bool = True) -> ShardedBatch:
        from spark_tpu import metrics

        d = self.d
        ex = dataclasses.replace(ex, child=D.ShardScanExec(child_sb))
        # the AQE host round-trip ROADMAP item 3 wants gone: one span
        # per stats stage + device->host fetch quantifies it per query
        with _trace.span("exchange.stats", op=_exchange_op(ex)):
            stats_sb = self._run_stage(D.ExchangeStatsExec(ex))
            # replicated psum/pmax: the flat layout puts device 0's
            # copy first; one host fetch of 2*d int64s total
            incoming = np.asarray(
                stats_sb.data.columns[0].data)[:d].astype(np.int64)
            maxslice = np.asarray(
                stats_sb.data.columns[1].data)[:d].astype(np.int64)
        bucket = max(1, int(self.conf.get(CF.ADAPTIVE_CAPACITY_BUCKET)))

        if (allow_skew and consumer is not None and d > 1
                and isinstance(ex, D.HashPartitionExchangeExec)
                and incoming.size):
            factor = int(self.conf.get(CF.ADAPTIVE_SKEW_FACTOR))
            min_rows = int(self.conf.get(CF.ADAPTIVE_SKEW_MIN_ROWS))
            med = float(np.median(incoming))
            hot = [int(j) for j in range(d)
                   if int(incoming[j]) >= min_rows
                   and float(incoming[j]) > factor * max(1.0, med)]
            if hot and _exactly_remergeable(consumer, child_sb.schema):
                metrics.record(
                    "aqe", decision="skew_split", op=_exchange_op(ex),
                    hot=tuple(hot), max_incoming=int(incoming.max()),
                    median=med, factor=factor)
                # fan: hot destinations' rows stay on their balanced
                # source devices; pre-merge collapses them to one row
                # per (device, group); only the merged groups take the
                # second (now un-skewed) exchange into the final merge
                fanned = dataclasses.replace(
                    ex, fan_destinations=tuple(hot))
                fanned_sb = self._exchange_with_stats(
                    fanned, child_sb, consumer=None, allow_skew=False)
                pre_sb = self._run_stage(dataclasses.replace(
                    consumer, child=D.ShardScanExec(fanned_sb)))
                plain = dataclasses.replace(ex, fan_destinations=None)
                return self._exchange_with_stats(
                    plain, pre_sb, consumer=None, allow_skew=False)

        max_in = int(incoming.max()) if incoming.size else 0
        max_sl = int(maxslice.max()) if maxslice.size else 0
        out_cap = K.bucket(max(1, max_in), bucket)
        slice_cap = min(child_sb.per_device_capacity,
                        K.bucket(max(1, max_sl), min(bucket, 128)))
        sb = self._run_stage(dataclasses.replace(
            ex, slice_capacity=slice_cap, out_capacity=out_cap))
        metrics.record_exchange(
            op=_exchange_op(ex), mode="adaptive", devices=d,
            rows=int(incoming.sum()),
            capacity_before=d * child_sb.per_device_capacity,
            capacity_after=sb.per_device_capacity,
            slice_capacity=slice_cap,
            buffer_bytes=d * slice_cap * _row_width(child_sb.schema))
        return sb

    # ---- runtime-adaptive aggregation ---------------------------------------

    def _agg_adaptive_enabled(self) -> bool:
        try:
            return bool(self.conf.get(CF.ADAPTIVE_AGG_ENABLED))
        except Exception:
            return True

    #: see the module-level hll_estimate — kept as a staticmethod so
    #: existing callers/tests keep working while the hybrid hash join
    #: shares the estimator without instantiating an executor
    _hll_estimate = staticmethod(hll_estimate)

    def _adaptive_aggregate(self, final: "D.DistSortAggExec",
                            ex: "D.HashPartitionExchangeExec",
                            partial: "D.DistSortAggExec") -> P.PhysicalPlan:
        """Runtime strategy switch for a partial->final aggregate pair.

        One extended stats stage over the RAW rows (the exchange the
        bypass strategy would run) measures, in a single fetch:
        routing counts (``__incoming``/``__maxslice``), an HLL distinct
        sketch over the group keys (``__ndvreg``), per-key global
        min/max/null counts (``__kmin``/``__kmax``/``__knull``), and a
        Count-Min heavy-hitter probe (``__hothash``/``__hotest``). The
        host then picks, per aggregate:

        - ``presplit`` the Count-Min probe found a KEY whose frequency
          alone overloads a device AND the crossover elected a raw-row
          exchange (bypass/sort — the strategies a hot key actually
          imbalances; partial/hash collapse it to one row per device
          first): salt the hot keys' raw rows round-robin over ALL
          devices BEFORE the exchange (salted sub-keys), partial-merge
          the salted shards, and exchange the now-balanced partials
          into the final merge — the source-side dual of the
          destination-reactive skew fan, acting before the imbalance
          instead of after it.
        - ``bypass``  estimated NDV ~ live rows, bounded key domain:
          pre-aggregation cannot shrink anything, so skip it —
          exchange raw rows by key straight to the final-equivalent
          aggregate (the partial node re-rooted on the exchanged rows;
          schemas are identical by the AggSpec alias contract).
        - ``sort``    estimated NDV ~ live rows AND the packed key
          domain is huge or unbounded (legality.strategy_crossover):
          range-partition the raw rows on the group keys and run one
          sorted segmented merge per device (DistRangeAggExec) — a
          distributed sort-aggregate whose output is key-ordered
          across the whole mesh, so a matching downstream global Sort
          elides entirely (_run_adaptive_exchange).
        - ``hash``    small measured key domain: swap the sort partial
          for DistHashPartialAggExec over measured packed codes (dense
          segment reductions through the measured selection table).
        - ``partial`` the static sort partial->final plan — always the
          fallback, and the byte-identity baseline.

        Aggregates outside legality.strategy_verdict (float Sum/Avg
        partials, float Min/Max) pin to ``partial``; every legal
        strategy is byte-identical to it (exact integer merges are
        associative+commutative, routing depends only on key values,
        and the final merge re-sorts per device — and pre-splitting in
        particular only re-partitions rows the partials are invariant
        to), pinned by the on/off x strategy sweep in
        tests/test_agg_adaptive.py.

        The sketches are advisory: ANY injected fault at
        ``agg.strategy`` (even 'corrupt' — the estimate is discarded,
        never merged into results) degrades to the static plan, and
        ``agg.presplit`` does the same for an elected pre-split, whole
        candidate list discarded."""
        from spark_tpu import faults, metrics
        from spark_tpu.analysis import legality

        d = self.d
        child = self._materialize_exchanges(partial.child)
        if isinstance(child, D.ShardScanExec):
            child_sb = child.sharded
        else:
            child_sb = self.run(child)

        # the raw-row exchange bypass would run; also the stats carrier
        raw_ex = D.HashPartitionExchangeExec(
            tuple(partial.groupings), D.ShardScanExec(child_sb))

        r = int(self.conf.get(CF.ADAPTIVE_AGG_SKETCH_REGISTERS))
        r = max(16, min(4096, r))
        if r & (r - 1):
            r = 1 << (r.bit_length() - 1)  # round down to a power of 2
        # per-key min/max only helps when every key range-compresses to
        # int64 codes exactly (ints, bools, dates, decimals, dictionary
        # strings — everything but floats)
        nk = len(partial.groupings)
        try:
            for g in partial.groupings:
                dt = legality._np_dtype(
                    E.strip_alias(g).data_type(partial.child.schema))
                if np.issubdtype(dt, np.floating):
                    nk = 0
                    break
        except Exception:
            nk = 0

        cmd = max(1, min(len(D._CM_SEEDS),
                         int(self.conf.get(CF.ADAPTIVE_AGG_CM_DEPTH))))
        cmw = max(64, min(1 << 16,
                          int(self.conf.get(CF.ADAPTIVE_AGG_CM_WIDTH))))
        if cmw & (cmw - 1):
            cmw = 1 << (cmw.bit_length() - 1)
        use_cm = d > 1  # pre-splitting needs somewhere to spread to

        with _trace.span("agg.decide", node=final.node_string()):
            stats_sb = self._run_stage(D.ExchangeStatsExec(
                raw_ex, sketch_registers=r, key_stats=nk,
                cm_depth=cmd if use_cm else 0,
                cm_width=cmw if use_cm else 0))
            cols = stats_sb.data.columns
            incoming = np.asarray(cols[0].data)[:d].astype(np.int64)
            maxslice = np.asarray(cols[1].data)[:d].astype(np.int64)
            rows = int(incoming.sum())

            verdict = legality.strategy_verdict(partial.aggregates,
                                                partial.child.schema)
            forced = str(self.conf.get(CF.ADAPTIVE_AGG_STRATEGY)).lower()

            ndv = 0
            ratio = 0.0
            mins: Tuple[int, ...] = ()
            ranges: Tuple[int, ...] = ()
            domain = 0
            hot_hashes: Tuple[int, ...] = ()
            try:
                # fault seam: everything the sketches feed the decision
                # sits inside this block, so an injected failure of ANY
                # kind degrades to the static plan, estimates discarded
                faults.inject("agg.strategy", self.conf)
                registers = np.asarray(cols[2].data)[:r].astype(np.int64)
                ndv = min(rows, int(round(self._hll_estimate(registers))))
                ratio = (ndv / rows) if rows else 0.0
                ci = 3
                if nk and rows:
                    kmin = np.asarray(cols[ci].data)[:nk].astype(np.int64)
                    kmax = np.asarray(
                        cols[ci + 1].data)[:nk].astype(np.int64)
                    if bool(np.all(kmin <= kmax)):
                        mins = tuple(int(v) for v in kmin)
                        ranges = tuple(int(mx - mn + 1)
                                       for mn, mx in zip(kmin, kmax))
                        domain = 1
                        for rg in ranges:
                            domain *= rg + 1  # + null slot per key
                            if domain > (1 << 62):
                                domain = 1 << 62
                                break
                ci += 3 if nk else 0
                if use_cm and rows:
                    hh = np.asarray(
                        cols[ci].data)[:d].astype(np.int64)
                    he = np.asarray(
                        cols[ci + 1].data)[:d].astype(np.int64)
                    # hot = one KEY alone would overload a device: its
                    # CM estimate tops the fair per-device share by the
                    # presplit factor (CM overestimates, never misses,
                    # so a collision can only salt a cold key — which
                    # the partials' partition-invariance makes free)
                    cut = max(
                        int(self.conf.get(
                            CF.ADAPTIVE_AGG_PRESPLIT_MIN_ROWS)),
                        int(self.conf.get(
                            CF.ADAPTIVE_AGG_PRESPLIT_FACTOR))
                        * max(1, rows // d))
                    hot_hashes = tuple(sorted(
                        {int(h) for h, e in zip(
                            hh.astype(np.uint64), he)
                         if int(e) >= cut}))
                sketch_ok = True
            except faults.InjectedFault as e:
                metrics.note_agg("sketch_failures")
                metrics.record("fault_recovered", point="agg.strategy",
                               fault=e.kind,
                               action="static_partial_final")
                sketch_ok = False

            hash_ok = bool(ranges) and 0 < domain <= int(
                self.conf.get(CF.ADAPTIVE_AGG_HASH_DOMAIN_LIMIT))
            presplit_ok = bool(hot_hashes) and d > 1
            if not sketch_ok:
                strategy, mode = "partial", "fallback"
            elif not verdict.ok:
                strategy, mode = "partial", "pinned"
                metrics.note_agg("pinned")
            elif forced in ("partial", "bypass", "hash", "sort",
                            "presplit"):
                # an unexecutable forced choice falls back to partial
                # (the conf doc promises forcing never breaks a query)
                strategy = forced
                if (forced == "hash" and not hash_ok) \
                        or (forced == "presplit" and not presplit_ok):
                    strategy = "partial"
                mode = "forced"
                metrics.note_agg("forced")
            elif rows:
                strategy = legality.strategy_crossover(
                    ratio, domain if ranges else -1,
                    float(self.conf.get(
                        CF.ADAPTIVE_AGG_BYPASS_NDV_RATIO)),
                    int(self.conf.get(
                        CF.ADAPTIVE_AGG_HASH_DOMAIN_LIMIT)),
                    int(self.conf.get(
                        CF.ADAPTIVE_AGG_SORT_DOMAIN_WIDTH)))
                mode = "auto"
                # pre-splitting only beats the alternatives when the
                # elected strategy exchanges RAW rows (bypass routes a
                # hot key's every row to one destination; the sort
                # rung's range partition owns it on one device). The
                # partial/hash strategies already collapse a hot key to
                # ONE row per device before their exchange — salting
                # would add a whole extra exchange for nothing.
                if strategy in ("bypass", "sort") and presplit_ok:
                    strategy = "presplit"
            else:
                strategy, mode = "partial", "auto"

            if strategy == "presplit":
                # second seam: the candidate list is pure advice — an
                # injected fault of ANY kind discards it whole and
                # degrades to the static partial->final plan
                try:
                    faults.inject("agg.presplit", self.conf)
                except faults.InjectedFault as e:
                    metrics.note_agg("presplit_failures")
                    metrics.record("fault_recovered",
                                   point="agg.presplit", fault=e.kind,
                                   action="static_partial_final")
                    strategy, mode = "partial", "presplit_fallback"

        metrics.record("agg", strategy=strategy, mode=mode, ndv=int(ndv),
                       rows=rows, ratio=round(ratio, 4),
                       domain=int(domain), devices=d,
                       hot_keys=len(hot_hashes),
                       node=final.node_string())
        metrics.note_agg(strategy)
        metrics.set_gauge("agg.last_ndv", int(ndv))
        metrics.set_gauge("agg.last_rows", rows)
        metrics.set_gauge("agg.last_strategy", strategy)

        if strategy == "bypass":
            # raw rows straight to their group's device under the
            # already-measured bounds; the partial node re-rooted on the
            # exchanged rows IS the final aggregate (AggSpec gives
            # partials and merges the same aliases and dtypes)
            bucket = max(1, int(self.conf.get(CF.ADAPTIVE_CAPACITY_BUCKET)))
            max_in = int(incoming.max()) if incoming.size else 0
            max_sl = int(maxslice.max()) if maxslice.size else 0
            out_cap = K.bucket(max(1, max_in), bucket)
            slice_cap = min(child_sb.per_device_capacity,
                            K.bucket(max(1, max_sl), min(bucket, 128)))
            sb = self._run_stage(dataclasses.replace(
                raw_ex, slice_capacity=slice_cap, out_capacity=out_cap))
            metrics.record_exchange(
                op="hash", mode="adaptive", devices=d, rows=rows,
                capacity_before=d * child_sb.per_device_capacity,
                capacity_after=sb.per_device_capacity,
                slice_capacity=slice_cap,
                buffer_bytes=d * slice_cap * _row_width(child_sb.schema))
            return dataclasses.replace(
                partial, child=D.ShardScanExec(sb), phase=None)

        if strategy == "sort":
            # the sort rung: range-partition the RAW rows on the group
            # keys (equal keys co-locate and devices own disjoint key
            # ranges), then one per-device sort-and-segment merge
            # completes a distributed sort-aggregate — output is
            # key-ordered across the mesh, marked on the batch so a
            # matching downstream global Sort elides entirely
            with _trace.span("agg.sort", rows=rows, ndv=int(ndv)):
                orders = tuple(E.SortOrder(E.strip_alias(g))
                               for g in partial.groupings)
                range_ex = D.RangeExchangeExec(
                    orders, D.ShardScanExec(child_sb))
                ex_sb = self._exchange_with_stats(range_ex, child_sb)
                out_sb = self._run_stage(D.DistRangeAggExec(
                    tuple(partial.groupings),
                    tuple(partial.aggregates),
                    D.ShardScanExec(ex_sb)))
                out_sb.sorted_by = self._agg_sorted_by(partial)
            return D.ShardScanExec(out_sb)

        if strategy == "presplit":
            # hot KEYS spread over every device BEFORE the exchange
            # (salted sub-keys), partial-merge the salted shards, then
            # the now-balanced partials take the ordinary exchange into
            # the final merge — the source-side dual of the skew fan,
            # acting on hot KEYS before the imbalance instead of hot
            # DESTINATIONS after it
            with _trace.span("agg.presplit", hot=len(hot_hashes),
                             rows=rows):
                salted = dataclasses.replace(
                    raw_ex, presplit_hashes=hot_hashes)
                salted_sb = self._exchange_with_stats(
                    salted, child_sb, consumer=None, allow_skew=False)
                pre_sb = self._run_stage(dataclasses.replace(
                    partial, child=D.ShardScanExec(salted_sb)))
                sb = self._exchange_with_stats(
                    ex, pre_sb, consumer=None, allow_skew=False)
            return dataclasses.replace(final,
                                       child=D.ShardScanExec(sb))

        if strategy == "hash":
            pre: P.PhysicalPlan = D.DistHashPartialAggExec(
                tuple(partial.groupings), tuple(partial.aggregates),
                D.ShardScanExec(child_sb), key_mins=mins,
                key_ranges=ranges)
        else:
            pre = dataclasses.replace(
                partial, child=D.ShardScanExec(child_sb))
        sb = self._run_adaptive_exchange(
            dataclasses.replace(ex, child=pre), consumer=final)
        return dataclasses.replace(final, child=D.ShardScanExec(sb))

    def _agg_sorted_by(self, partial: "D.DistSortAggExec"):
        """The ``sorted_by`` guarantee of the sort rung's output under
        the partial's ``__k{i}`` key aliases, or None when the key
        types cannot carry one: dictionary strings range-partition by
        RANK but sort locally by CODE, so the rung's output is grouped
        correctly yet not globally string-ordered; floats never reach
        here (strategy pinned) but are excluded anyway. Integer-coded
        orderable keys (ints, bools, dates, decimals) qualify — their
        code order IS their value order on both sides."""
        from spark_tpu.analysis import legality

        out = []
        for i, g in enumerate(partial.groupings):
            try:
                dt_engine = E.strip_alias(g).data_type(
                    partial.child.schema)
                dt = legality._np_dtype(dt_engine)
            except Exception:
                return None
            if isinstance(dt_engine, T.StringType) \
                    or np.issubdtype(dt, np.floating):
                return None
            alias = partial.aggregates[i]
            if not (isinstance(alias, E.Alias)
                    and E.expr_key(alias.child) == E.expr_key(
                        E.strip_alias(g))):
                return None
            out.append((alias.name, True, True))
        return tuple(out)

    def _materialize_boundaries(self, plan: P.PhysicalPlan) -> P.PhysicalPlan:
        if isinstance(plan, D.DistJoinBoundary):
            return D.ShardScanExec(self._run_join(plan))
        fields = {}
        changed = False
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, P.PhysicalPlan):
                nv = self._materialize_boundaries(v)
                changed |= nv is not v
                fields[f.name] = nv
            else:
                fields[f.name] = v
        return dataclasses.replace(plan, **fields) if changed else plan

    def _run_stage(self, plan: P.PhysicalPlan) -> ShardedBatch:
        from spark_tpu import metrics, trace

        with trace.span("stage.run", op=type(plan).__name__), \
                metrics.stage_timer("stage", mesh=self.d,
                                    node=plan.node_string()):
            sb = self._run_stage_inner(plan)
        # measured output footprint: scheduler admission prefers these
        # over static row-count estimates once a plan has run once
        # (scheduler/admission.note_measured_bytes, fed by
        # DataFrame._execute from the query's stage_bytes events)
        metrics.record("stage_bytes",
                       bytes=int(sb.capacity) * _row_width(sb.schema))
        return sb

    def _run_stage_inner(self, plan: P.PhysicalPlan) -> ShardedBatch:
        scans: List[D.ShardScanExec] = []
        _collect_shard_scans(plan, scans)
        key = (plan.plan_key(), self.d, self.mesh.devices.flat[0].platform)
        entry = _DIST_STAGE_CACHE.get(key)
        if entry is None:
            schema_box: dict = {}
            skeleton = _strip_leaves(plan)

            def local_fn(leaf_datas):
                it = iter(leaf_datas)

                def go(p: P.PhysicalPlan) -> Pipe:
                    if isinstance(p, _ShardSlot):
                        return Pipe.from_batch_data(p.scan_schema, next(it))
                    pipes = [go(c) for c in p.children()]
                    return p.trace(pipes)

                batch = go(skeleton).to_batch()
                schema_box["schema"] = batch.schema
                return batch.data

            if hasattr(jax, "shard_map"):
                smapped = jax.shard_map(local_fn, mesh=self.mesh,
                                        in_specs=_SPEC, out_specs=_SPEC,
                                        check_vma=False)
            else:  # jax < 0.6: experimental API, check_rep not check_vma
                from jax.experimental.shard_map import shard_map

                smapped = shard_map(local_fn, mesh=self.mesh,
                                    in_specs=_SPEC, out_specs=_SPEC,
                                    check_rep=False)
            # cross-session executable store integration (no-op jit
            # when the compile service is off). A plan holding fused
            # spans keys under its own tier with the bucket-ladder
            # parameters folded into the digest: the store never
            # replays a fused executable across a ladder conf change,
            # and prewarm replays fused programs as themselves
            from spark_tpu.compile import build_stage_callable

            fused_nodes: List[D.FusedSpanExec] = []
            _collect_fused(plan, fused_nodes)
            tier = "fused_span" if fused_nodes else "dist"
            extra = tuple(
                ("ladder", f.bucket, f.variants) for f in fused_nodes
            ) or None
            entry = (build_stage_callable(
                tier, plan, smapped,
                tuple(s.sharded.data for s in scans), schema_box,
                mesh_size=self.d, platform=key[2], extra=extra),
                schema_box)
            _DIST_STAGE_CACHE[key] = entry
        jitted, schema_box = entry
        ctx = _trace.current()
        if ctx is not None and ctx.sampled:
            # device time, block_until_ready-bounded, so the span is
            # device execution and not async dispatch; only a SAMPLED
            # trace pays the forced sync (results are identical either
            # way — the host reads the same buffers right after)
            with _trace.span("stage.device", op=type(plan).__name__):
                data = jitted(tuple(s.sharded.data for s in scans))
                data = jax.block_until_ready(data)
        else:
            data = jitted(tuple(s.sharded.data for s in scans))
        sb = ShardedBatch(schema_box["schema"], data, self.mesh)
        if isinstance(plan, D.FusedSpanExec) and plan.speculate:
            # the last slot of every shard is the overflow sentinel —
            # check it BEFORE any compaction could move or drop it
            p = sb.per_device_capacity
            m = np.asarray(sb.data.row_mask).reshape(self.d, p)
            if bool(m[:, -1].any()):
                raise _FusionOverflow()
        n_ex = _count_exchange_nodes(plan)
        if n_ex and not self._adaptive_enabled():
            # fused-mode observability: exchanges ran inside this stage
            # at the static worst-case capacity; report the stage output
            # as the post-exchange shape so padding ratios compare
            # against adaptive mode. One mask readback per
            # exchange-bearing stage.
            from spark_tpu import metrics

            p = sb.per_device_capacity
            metrics.record_exchange(
                op="fused", mode="fused", devices=self.d,
                exchanges=n_ex, rows=sb.num_valid_rows(),
                capacity_before=p, capacity_after=p,
                buffer_bytes=self.d * p * _row_width(sb.schema))
        return self._maybe_compact(sb)

    def _maybe_compact(self, sb: ShardedBatch) -> ShardedBatch:
        p = sb.per_device_capacity
        if p <= 4096:
            return sb
        m = np.asarray(sb.data.row_mask).reshape(self.d, p)
        max_live = int(m.sum(axis=1).max())
        if max_live * 4 > p:
            return sb
        new_p = K.bucket(max_live, 128)
        # slice-safe when no live row sits past new_p on any device —
        # true for front-compacted outputs (exchanges, fused spans),
        # where the stable-argsort gather would be an identity move
        sliced = not bool(m[:, new_p:].any())
        return self._run_stage(_CompactExec(new_p, D.ShardScanExec(sb),
                                            sliced))

    # ---- join lowering ------------------------------------------------------

    def _run_join(self, jb: D.DistJoinBoundary) -> ShardedBatch:
        left_sb = self.run(jb.left)
        right_sb = self.run(jb.right)
        how = jb.how

        if how == "cross":
            return self._run_cross(jb, left_sb, right_sb)

        if self.broadcast_threshold is not None:  # legacy row threshold
            small_build = right_sb.capacity <= self.broadcast_threshold
        elif self._adaptive_enabled():
            # runtime broadcast switching (reference:
            # DynamicJoinSelection.scala:40 over MapOutputStatistics):
            # measure the build side — live rows x row width, one mask
            # readback — instead of trusting the static capacity
            # estimate, which a filtered build side inflates by orders
            # of magnitude
            from spark_tpu import metrics as _metrics

            measured = (right_sb.num_valid_rows()
                        * _row_width(right_sb.schema))
            threshold = int(self.conf.get(
                CF.ADAPTIVE_BROADCAST_THRESHOLD))
            small_build = measured <= threshold
            _metrics.record(
                "aqe",
                decision=("broadcast_join" if small_build
                          else "exchange_join"),
                measured_bytes=int(measured), threshold=threshold,
                static_bytes=_estimated_bytes(right_sb))
            if self._fusion_enabled():
                # the broadcast switch is a measured-bytes host
                # decision by construction — joins always execute at
                # the staged boundary, never inside a fused span
                self._fusion_bailout(
                    "broadcast_switch",
                    "join build side measured on host")
        else:
            from spark_tpu import conf as _conf

            # read per-join so spark.conf.set takes effect immediately
            small_build = (_estimated_bytes(right_sb)
                           <= self.conf.get(_conf.BROADCAST_THRESHOLD))
        broadcast = (how in ("inner", "left", "left_semi", "left_anti")
                     and small_build)

        # Evaluate the key expressions once (a tiny projection stage) —
        # the EXECUTED schema carries the true dictionaries of computed
        # string keys (e.g. substr(col)), which static analysis of the
        # input schema cannot know. Min/max stats don't change under the
        # exchange, so pre-exchange stats are globally valid.
        lproj = self._run_stage(P.ProjectExec(
            tuple(E.Alias(k, f"__k{i}") for i, k in enumerate(jb.left_keys)),
            D.ShardScanExec(left_sb)))
        rproj = self._run_stage(P.ProjectExec(
            tuple(E.Alias(k, f"__k{i}") for i, k in enumerate(jb.right_keys)),
            D.ShardScanExec(right_sb)))
        union_dicts = self._union_dicts(lproj.schema, rproj.schema)
        mins, ranges = self._key_stats(lproj, rproj, union_dicts)

        left0, right0 = left_sb, right_sb  # pre-exchange (balanced rows)
        if not broadcast:
            left_sb = self.run(D.HashPartitionExchangeExec(
                jb.left_keys, D.ShardScanExec(left_sb),
                key_union_dicts=union_dicts))
            right_sb = self.run(D.HashPartitionExchangeExec(
                jb.right_keys, D.ShardScanExec(right_sb),
                key_union_dicts=union_dicts))

        def count_pairs(ls, rs, bcast):
            cnt_plan = D.JoinCountExec(
                D.ShardScanExec(ls), D.ShardScanExec(rs),
                jb.left_keys, jb.right_keys, mins, ranges, bcast)
            cnt_sb = self._run_stage(cnt_plan)
            return np.asarray(cnt_sb.data.columns[0].data)

        need_count = not (how in ("left_semi", "left_anti")
                          and jb.condition is None and mins is not None)
        pair_cap = 0
        if need_count:
            counts = count_pairs(left_sb, right_sb, broadcast)
            # AQE skew handling (reference: OptimizeSkewedJoin.scala:37
            # splits oversized partitions; DynamicJoinSelection demotes
            # to broadcast). Hash exchange sends every row of one hot
            # key to ONE device, so its pair count — and, under SPMD
            # static shapes, EVERY device's capacity — blows up. The
            # pre-exchange distribution is row-sliced and balanced, so
            # re-running as a broadcast join bounds per-device pairs at
            # ~total/d: pairs ride with the evenly-spread probe rows.
            from spark_tpu import conf as _conf

            factor = self.conf.get(_conf.SKEW_FACTOR)
            min_pairs = self.conf.get(_conf.SKEW_MIN_PAIRS)
            med = float(np.median(counts)) if counts.size else 0.0
            skewed = (not broadcast and counts.size
                      and int(counts.max()) >= min_pairs
                      and float(counts.max()) > factor * max(1.0, med))
            if skewed and how in ("inner", "left", "left_semi",
                                  "left_anti"):
                from spark_tpu import metrics

                if _estimated_bytes(right0) <= self.conf.get(
                        _conf.SKEW_MAX_BROADCAST_BYTES):
                    metrics.record(
                        "skew_join_broadcast", max=int(counts.max()),
                        median=med, factor=factor)
                    broadcast = True
                    left_sb, right_sb = left0, right0
                    counts = count_pairs(left_sb, right_sb, True)
                else:
                    # build too big to broadcast whole: SPLIT around the
                    # hot keys (reference: OptimizeSkewedJoin.scala:37
                    # splits oversized partitions; here the hot keys'
                    # probe rows stay row-sliced/balanced and only the
                    # hot keys' FEW build rows replicate)
                    hot = self._detect_hot_keys(jb.left_keys, left0)
                    if hot:
                        metrics.record(
                            "skew_join_split", max=int(counts.max()),
                            median=med, hot_keys=len(hot))
                        return self._run_skew_split(
                            jb, how, left0, right0, hot, union_dicts,
                            mins, ranges, count_pairs)
            pair_cap = K.bucket(int(counts.max()) if counts.size else 0)

        left0 = right0 = None  # release pre-exchange device buffers
        apply_plan = D.JoinApplyExec(
            D.ShardScanExec(left_sb), D.ShardScanExec(right_sb), how,
            jb.left_keys, jb.right_keys, jb.condition, mins, ranges,
            pair_cap, broadcast)
        return self._run_stage(apply_plan)

    def _detect_hot_keys(self, keys, sb: ShardedBatch):
        """Host-side hot-key candidates: each device reports its local
        mode (TopKeyExec); a candidate is hot when its (lower-bound)
        global count exceeds one balanced device share — the row volume
        that would pile onto a single device under a hash exchange."""
        cand = self._run_stage(D.TopKeyExec(tuple(keys),
                                            D.ShardScanExec(sb)))
        nkeys = len(keys)
        fields = cand.schema.fields
        cols = []
        for i in range(nkeys + 1):
            cd = cand.data.columns[i]
            cols.append((np.asarray(cd.data).ravel(),
                         None if cd.validity is None
                         else np.asarray(cd.validity).ravel(),
                         fields[i]))
        counts: dict = {}
        d = len(cols[0][0])
        for j in range(d):
            vals = []
            ok = True
            for i in range(nkeys):
                data, validity, f = cols[i]
                if validity is not None and not bool(validity[j]):
                    ok = False  # null hot key: nulls never join
                    break
                vals.append(_decode_key_value(data[j], f))
            if not ok:
                continue
            cnt = int(cols[nkeys][0][j])
            key = tuple(vals)
            counts[key] = counts.get(key, 0) + cnt
        total = sb.num_valid_rows()
        share = max(1, total // max(1, self.d))
        hot = [k for k, c in sorted(counts.items(),
                                    key=lambda kv: -kv[1]) if c > share]
        return hot[:4]

    def _run_skew_split(self, jb: D.DistJoinBoundary, how: str,
                        left0: ShardedBatch, right0: ShardedBatch,
                        hot, union_dicts, mins, ranges,
                        count_pairs) -> ShardedBatch:
        """AQE skew SPLIT: hot-key probe rows keep their balanced
        row-sliced placement and join against a broadcast of (only) the
        hot keys' build rows; everything else takes the normal hash
        exchange. Union of the two joins is exact for left-preserved
        join types — every probe row lands in exactly one branch and
        sees ALL build rows with its key (the all_to_all analogue of
        OptimizeSkewedJoin.scala:37 partition splitting)."""
        lpred = _hot_key_pred(jb.left_keys, hot)
        rpred = _hot_key_pred(jb.right_keys, hot)
        # null probe keys must survive into the REST branch (preserved
        # rows under outer/anti); NOT(pred) alone is NULL for them
        lkeep_rest = E.Or(E.Not(lpred), _null_any(jb.left_keys))
        rkeep_rest = E.Or(E.Not(rpred), _null_any(jb.right_keys))
        lhot = self._run_stage(P.FilterExec(lpred, D.ShardScanExec(left0)))
        lrest = self._run_stage(P.FilterExec(lkeep_rest,
                                             D.ShardScanExec(left0)))
        rhot = self._run_stage(P.FilterExec(rpred, D.ShardScanExec(right0)))
        rrest = self._run_stage(P.FilterExec(rkeep_rest,
                                             D.ShardScanExec(right0)))
        lrest_ex = self.run(D.HashPartitionExchangeExec(
            jb.left_keys, D.ShardScanExec(lrest),
            key_union_dicts=union_dicts))
        rrest_ex = self.run(D.HashPartitionExchangeExec(
            jb.right_keys, D.ShardScanExec(rrest),
            key_union_dicts=union_dicts))
        c1 = count_pairs(lrest_ex, rrest_ex, False)
        c2 = count_pairs(lhot, rhot, True)
        cap1 = K.bucket(int(c1.max()) if c1.size else 0)
        cap2 = K.bucket(int(c2.max()) if c2.size else 0)
        j1 = self._run_stage(D.JoinApplyExec(
            D.ShardScanExec(lrest_ex), D.ShardScanExec(rrest_ex), how,
            jb.left_keys, jb.right_keys, jb.condition, mins, ranges,
            cap1, broadcast=False))
        j2 = self._run_stage(D.JoinApplyExec(
            D.ShardScanExec(lhot), D.ShardScanExec(rhot), how,
            jb.left_keys, jb.right_keys, jb.condition, mins, ranges,
            cap2, broadcast=True))
        return self._run_stage(P.UnionExec(D.ShardScanExec(j1),
                                           D.ShardScanExec(j2)))

    def _run_cross(self, jb: D.DistJoinBoundary, left_sb: ShardedBatch,
                   right_sb: ShardedBatch) -> ShardedBatch:
        rn = right_sb.num_valid_rows()
        pair_cap = left_sb.per_device_capacity * max(1, rn)
        apply_plan = D.JoinApplyExec(
            D.ShardScanExec(left_sb), D.ShardScanExec(right_sb), "cross",
            (), (), jb.condition, (), (), pair_cap, broadcast=True)
        return self._run_stage(apply_plan)

    @staticmethod
    def _union_dicts(lschema: Schema, rschema: Schema):
        """Per-key unified dictionaries (trace-time constants) so string
        codes hash/pack identically on both sides. Schemas come from the
        EXECUTED key projection, so computed-key dictionaries are exact."""
        from spark_tpu.expr import compiler as C

        out = []
        for lf, rf in zip(lschema.fields, rschema.fields):
            if lf.dictionary is None and rf.dictionary is None:
                out.append(None)
            else:
                union, _ = C.unify_dictionaries(
                    (lf.dictionary or (), rf.dictionary or ()))
                out.append(union)
        return tuple(out)

    def _key_stats(self, lproj: ShardedBatch, rproj: ShardedBatch,
                   union_dicts) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Host-side min/range per join key (the lightweight stats job;
        reference analogue: runtime statistics consumed by AQE)."""
        mins: List[int] = []
        ranges: List[int] = []
        total = 1
        for i, ud in enumerate(union_dicts):
            lf = lproj.schema.fields[i]
            if ud is not None or isinstance(lf.dtype, T.StringType):
                mins.append(0)
                ranges.append(max(1, len(ud or ())))
            else:
                vals = []
                for sb in (lproj, rproj):
                    cd = sb.data.columns[i]
                    m = np.asarray(sb.data.row_mask)
                    if cd.validity is not None:
                        m = m & np.asarray(cd.validity)
                    v = np.asarray(cd.data)[m]
                    if v.size:
                        vals.append((int(v.min()), int(v.max())))
                if not vals:
                    mins.append(0)
                    ranges.append(1)
                else:
                    mn = min(v[0] for v in vals)
                    mx = max(v[1] for v in vals)
                    mins.append(mn)
                    ranges.append(mx - mn + 1)
            total *= ranges[-1]
            if total > (1 << 62):
                # exact packing impossible: switch the whole join to the
                # hash-with-verify fallback (reference:
                # HashedRelation.scala:208 probe-then-confirm)
                return None, None
        return tuple(mins), tuple(ranges)

"""Exchange primitives — shuffle as ICI collectives.

Everything here runs INSIDE a shard_map trace (one device's view, with
the ``data`` axis name in scope). This file is the whole replacement for
the reference's shuffle write/fetch pipeline: sort-based spill files +
Netty chunk fetch (reference: shuffle/sort/SortShuffleManager.scala:73,
UnsafeShuffleWriter.java:173, storage/ShuffleBlockFetcherIterator.scala:86,
common/network-common) becomes: bucket rows into a (D, cap) send tensor
and `lax.all_to_all` it over the interconnect. No files, no serializer,
no fetch scheduler — the collective IS the shuffle.

Static-shape contract: the receive capacity is D * send_capacity (worst
case: everyone routes everything to one device). AQE-style stats can
shrink this between stages (planner._maybe_compact analogue).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.expr import compiler as C
from spark_tpu.expr.compiler import TV
from spark_tpu.parallel.mesh import DATA_AXIS
from spark_tpu.physical import kernels as K
from spark_tpu.physical.operators import Pipe


def axis_index() -> jnp.ndarray:
    return jax.lax.axis_index(DATA_AXIS)


def axis_size() -> int:
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(DATA_AXIS)
    # jax < 0.6: psum of a Python literal is evaluated statically under
    # shard_map, so this is still a concrete int
    return jax.lax.psum(1, DATA_AXIS)


# ---- row routing ------------------------------------------------------------


def hash_rows(tvs: Sequence[TV]) -> jnp.ndarray:
    """Full-width avalanche hash of the key columns, one uint64 per
    row. Dictionary codes hash directly — dictionaries are global
    constants, so codes agree across devices. NULL hashes as a fixed
    sentinel, so null keys collide (and co-locate once routed). Shared
    by hash routing (mod D) and the distinct-key sketch (register
    index + leading-zero rank over the SAME hash chain, so equal keys
    produce equal registers on every device)."""
    cap = int(tvs[0].data.shape[0]) if tvs else 0
    h = jnp.zeros((cap,), dtype=jnp.uint64)
    for tv in tvs:
        data = tv.data
        if jnp.issubdtype(data.dtype, jnp.floating):
            # normalize -0.0 == 0.0 before bitcasting
            data = jax.lax.bitcast_convert_type(
                jnp.where(data == 0, 0.0, data).astype(jnp.float64),
                jnp.uint64)
        code = data.astype(jnp.uint64)
        if tv.validity is not None:
            code = jnp.where(tv.validity, code,
                             jnp.uint64(0xA5A5A5A5A5A5A5A5))
        h = K.hash_combine(h, code)
    return h


def hash_target(tvs: Sequence[TV], mask: jnp.ndarray, d: int) -> jnp.ndarray:
    """Device id per row = avalanche hash of the key columns mod D
    (HashPartitioning analogue, reference:
    exchange/ShuffleExchangeExec.scala:275)."""
    if not tvs:
        return jnp.zeros((int(mask.shape[0]),), dtype=jnp.int32)
    return (hash_rows(tvs) % jnp.uint64(d)).astype(jnp.int32)


def range_target(key: TV, ascending: bool, nulls_first: bool, d: int,
                 mask: jnp.ndarray,
                 samples_per_device: int = 128) -> jnp.ndarray:
    """Device id per row for range partitioning: sample local keys,
    all_gather the samples, cut D-1 splitters — every device derives the
    SAME splitters, so no separate sampling job is needed (reference
    needs one: RangePartitioner sketch job,
    core/.../Partitioner.scala + ShuffleExchangeExec.scala:280)."""
    rank_table = None
    if isinstance(key.dtype, T.StringType):
        rank_table = C.string_rank_table(key.dictionary or ())
    y = K.orderable_int64(key.data, key.validity, ascending, nulls_first,
                          rank_table)
    cap = int(mask.shape[0])
    imax = jnp.iinfo(jnp.int64).max
    ys = jnp.sort(jnp.where(mask, y, imax))
    # spread samples over the live prefix; dead rows sample as +inf and
    # only skew splitters when occupancy is very low (AQE re-split later)
    s = min(samples_per_device, cap)
    idx = (jnp.arange(s) * cap) // s
    samples = ys[idx]
    all_samples = jnp.sort(jax.lax.all_gather(samples, DATA_AXIS,
                                              tiled=True))
    total = int(all_samples.shape[0])
    cut_pos = (jnp.arange(1, d) * total) // d
    splitters = all_samples[cut_pos]
    return jnp.searchsorted(splitters, y, side="right").astype(jnp.int32)


def fan_local(target: jnp.ndarray,
              hot: Sequence[int]) -> jnp.ndarray:
    """Skew fan: rows bound for a hot destination stay on their source
    device instead (the local-shuffle-reader move, reference:
    OptimizeShuffleWithLocalShuffleReader.scala:35 — a skewed partition
    is read where it was produced rather than concentrated). Every
    device holds a slice of the hot keys afterwards; a partial-aggregate
    pre-merge plus a second exchange of the (much smaller) merged groups
    restores the final placement."""
    me = axis_index()
    hot_mask = jnp.zeros(target.shape, dtype=bool)
    for h in hot:
        hot_mask = hot_mask | (target == int(h))
    return jnp.where(hot_mask, me.astype(target.dtype), target)


# ---- the collective exchange ------------------------------------------------


def exchange(pipe: Pipe, target: jnp.ndarray,
             slice_capacity: Optional[int] = None,
             out_capacity: Optional[int] = None) -> Pipe:
    """Route each live row to device ``target[row]``. Local capacity cap
    becomes D*cap after the all_to_all. One fused sequence:
    sort-by-destination -> scatter into (D, cap) send buffer ->
    all_to_all over ICI -> flatten.

    Adaptive execution (executor._run_adaptive_exchange) passes measured
    bounds: ``slice_capacity`` shrinks the per-(src,dest) send slice
    from cap to the measured pmax cell count (the all_to_all then moves
    D*slice instead of D*cap elements over ICI), and ``out_capacity``
    compacts the received rows in-trace to the measured pmax incoming
    count. Both are exact upper bounds from the same target computation,
    so no live row is ever dropped, and both transforms are stable
    (order-preserving), so the live-row sequence — and therefore every
    downstream result — is byte-identical to the unbounded exchange."""
    # fault seam: fires at trace time (a failed trace is never cached,
    # so a stage retry re-traces and re-arrives here)
    from spark_tpu import faults

    faults.inject("exchange.all_to_all")
    d = axis_size()
    cap = pipe.capacity
    scap = cap if slice_capacity is None else max(1, min(int(slice_capacity),
                                                         cap))
    live = pipe.mask
    t = jnp.where(live, jnp.clip(target, 0, d - 1), d)  # dead rows -> sentinel
    order = jnp.argsort(t, stable=True)
    st = t[order]
    starts = jnp.searchsorted(st, jnp.arange(d), side="left")
    pos = jnp.arange(cap) - starts[jnp.clip(st, 0, d - 1)]
    # destination slot in the (D, scap) buffer; sentinel rows -> OOB drop
    # (pos >= scap cannot happen for live rows when slice_capacity is a
    # measured bound, but the guard keeps a stale bound safe: overflow
    # drops rather than corrupting a neighbour slice)
    ok = (st < d) & (pos < scap)
    dest = jnp.where(ok, st * scap + pos, d * scap)

    def route(x: jnp.ndarray, fill) -> jnp.ndarray:
        buf = jnp.full((d * scap,), fill, dtype=x.dtype)
        buf = buf.at[dest].set(x[order], mode="drop")
        return jax.lax.all_to_all(buf.reshape(d, scap), DATA_AXIS, 0, 0,
                                  tiled=True).reshape(-1)

    new_mask = route(live, False)
    cols: Dict[str, TV] = {}
    for name in pipe.order:
        tv = pipe.cols[name]
        data = route(tv.data, jnp.zeros((), tv.data.dtype))
        validity = None if tv.validity is None else route(tv.validity, False)
        cols[name] = TV(data, validity, tv.dtype, tv.dictionary)
    out = Pipe(cols, new_mask, pipe.order)
    if out_capacity is not None and int(out_capacity) < d * scap:
        out = compact(out, int(out_capacity))
    return out


def compact(pipe: Pipe, new_capacity: int) -> Pipe:
    """Stable in-trace compaction: live rows to the front (original
    order preserved), then truncate to ``new_capacity`` slots. The bound
    must cover every live row (adaptive stats guarantee it)."""
    perm = K.compaction_permutation(pipe.mask)[: int(new_capacity)]
    cols = {
        name: TV(tv.data[perm],
                 None if tv.validity is None else tv.validity[perm],
                 tv.dtype, tv.dictionary)
        for name, tv in pipe.cols.items()
    }
    return Pipe(cols, pipe.mask[perm], pipe.order)


def broadcast_gather(pipe: Pipe) -> Pipe:
    """Replicate a (small) pipe onto every device via all_gather — the
    broadcast-exchange data plane (reference: TorrentBroadcast.scala:59 +
    BroadcastExchangeExec.scala:78; one ICI all_gather replaces the
    BitTorrent chunk protocol)."""
    def g(x):
        return jax.lax.all_gather(x, DATA_AXIS, tiled=True)

    cols = {
        name: TV(g(tv.data),
                 None if tv.validity is None else g(tv.validity),
                 tv.dtype, tv.dictionary)
        for name, tv in pipe.cols.items()
    }
    return Pipe(cols, g(pipe.mask), pipe.order)


def to_single_partition(pipe: Pipe) -> Pipe:
    """All rows to device 0 (SinglePartition analogue, reference:
    ShuffleExchangeExec.scala:301): gather + mask off non-zero devices.
    Row order across devices is preserved by the tiled gather."""
    g = broadcast_gather(pipe)
    on_zero = jnp.where(axis_index() == 0, g.mask,
                        jnp.zeros_like(g.mask))
    return Pipe(g.cols, on_zero, g.order)


# ---- merged (cross-device) aggregation primitives ---------------------------


def psum(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.psum(x, DATA_AXIS)


def pmin(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.pmin(x, DATA_AXIS)


def pmax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.pmax(x, DATA_AXIS)

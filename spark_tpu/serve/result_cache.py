"""Plan-keyed Arrow result cache with single-flight execution.

The serving-economics argument (Flare, arxiv 1703.08219): once
per-query compute is native-fast, the dominant serving costs are
dispatch and redundant re-execution of identical dashboard-style
queries. This cache removes the second cost: results are keyed by the
SAME structural plan key that measured-admission and the compile store
use (plan/logical.structural_key), folded with the scan-source
mtime/size fingerprint io/datasource.py already computes — so a
rewritten source file misses naturally and the stale entry ages out
LRU, exactly like the datasource's own batch cache.

Values are the Arrow-IPC-serialized result stream, which is the byte
string the connect server would have produced anyway: a hit returns
the identical bytes an uncached execution serializes, so the on/off
sweep is byte-identical by construction.

Single-flight: a thundering herd of identical queries (8 clients
refreshing the same dashboard) costs ONE device execution — the first
arrival owns the execution, the rest block on its flight and read the
serialized result. Reference shape: CacheManager._materialize's
per-entry lock (api/session.py); the reference system's analogue is
the BlockManager's ``doPutIterator`` single-writer semantics.
"""

from __future__ import annotations

import hashlib
import io
import threading
import time
from typing import Any, Callable, Optional, Tuple

import pyarrow as pa

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import deadline, faults, metrics, trace
from spark_tpu.storage.lru import LruDict

SERVE_FP_CACHE_SECONDS = CF.register(
    "spark.tpu.serve.fingerprintCacheSeconds", 0.0,
    "TTL on a replica's per-source freshness-fingerprint probe (the "
    "stat walk behind the result-cache key). 0 (default) stats on "
    "every request — always fresh, but a stat storm under fleet "
    "traffic. > 0 amortizes the probe and OPENS a stale-serve window "
    "exactly as wide as the TTL; the fleet invalidation log closes it "
    "by dropping entries (and the cached probe) the moment a refresh "
    "or rewrite commits.", float)

#: follower wait bound per round: the owner always sets the flight
#: event in a ``finally``, so this only guards against an owner thread
#: killed by interpreter shutdown or wedged on the device; on expiry a
#: typed FlightWaitTimeout is recorded and the follower falls through
#: to its own execution instead of waiting forever.
_FLIGHT_WAIT_S = 600.0


class FlightWaitTimeout(RuntimeError):
    """A single-flight follower waited the full bound without the
    owner publishing a result or an error. Surfaced in the event log
    (serve_cache phase=wait_timeout) before the follower executes the
    query itself."""

    def __init__(self, key_digest_: str, waited_s: float):
        super().__init__(
            f"single-flight wait for {key_digest_} timed out after "
            f"{waited_s:g}s; executing independently")
        self.key_digest = key_digest_
        self.waited_s = float(waited_s)


def scan_fingerprints(plan) -> Tuple[Any, ...]:
    """Freshness token over every scan source in ``plan``: the
    (path, mtime_ns, size) fingerprint FileSource computes for its own
    cache invalidation — the SAME walk (io/fingerprint.py), so this
    cache, the datasource auto-cache, and the materialized-view delta
    detector can never disagree about staleness. Sources without one
    (in-memory Relations) key by object identity, which structural_key
    already does."""
    from spark_tpu.io.fingerprint import plan_fingerprints

    return plan_fingerprints(plan)


def plan_result_key(plan) -> Tuple[Any, ...]:
    """Cache key: injective structural plan identity + per-source
    freshness. Process-local (structural_key embeds source object
    identity) — each replica process keys its own cache, which is the
    correct scope because fingerprints are local filesystem stats."""
    return (plan.structural_key(), scan_fingerprints(plan))


def key_digest(key: Tuple[Any, ...]) -> str:
    """Short stable digest of a cache key for event-log correlation
    (the full structural key is huge and unreadable in JSON)."""
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


def table_to_ipc(tbl: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue()


def ipc_to_table(blob: bytes) -> pa.Table:
    return pa.ipc.open_stream(io.BytesIO(blob)).read_all()


class _Flight:
    """One in-flight execution: followers wait on the event and read
    either the serialized result or the owner's exception."""

    __slots__ = ("event", "blob", "error")

    def __init__(self):
        self.event = threading.Event()
        self.blob: Optional[bytes] = None
        self.error: Optional[BaseException] = None


class ResultCache:
    """Byte-bounded (``spark.tpu.serve.resultCache.maxBytes``, read
    live) LRU of Arrow-IPC result streams with single-flight execution
    per key. Shared across in-process replicas via the session
    (connect/server.py attaches one per session), so the herd
    guarantee holds even when the router spreads identical queries
    over several replicas."""

    def __init__(self, conf):
        self._conf = conf
        self._lru = LruDict(
            "serve_results",
            cap=4096,
            max_bytes_entry=CF.SERVE_RESULT_CACHE_MAX_BYTES,
            weigher=len,
            conf=conf)
        self._flights: dict = {}
        self._lock = locks.named_lock("serve.result_cache")
        #: TTL'd per-source fingerprint probes: {paths tuple ->
        #: (fingerprint, stamp)} — populated only when
        #: spark.tpu.serve.fingerprintCacheSeconds > 0
        self._fp_cache: dict = {}
        #: last invalidation-log version applied (watermark replay on
        #: reattach)
        self.invalidation_watermark = 0
        self._inval_log = None

    def enabled(self) -> bool:
        try:
            return bool(self._conf.get(CF.SERVE_RESULT_CACHE_ENABLED))
        except Exception:
            return False

    def _max_bytes(self) -> int:
        try:
            return int(self._conf.get(CF.SERVE_RESULT_CACHE_MAX_BYTES))
        except Exception:
            return int(CF.SERVE_RESULT_CACHE_MAX_BYTES.default)

    def _publish_gauges(self) -> None:
        metrics.set_gauge("serve.result_cache.entries", len(self._lru))
        metrics.set_gauge("serve.result_cache.bytes",
                          self._lru.total_bytes)

    def get_or_execute(self, key, execute: Callable[[], pa.Table]
                       ) -> Tuple[bytes, str]:
        """Return ``(arrow_ipc_bytes, status)`` for ``key``; status is
        ``hit`` (served from cache), ``miss`` (this call owned the
        device execution) or ``wait`` (piggybacked on a concurrent
        execution of the same key). ``execute()`` runs AT MOST once
        across all concurrent callers with the same key."""
        kd = key_digest(key)
        while True:
            blob = self._lru.get(key)
            if blob is not None:
                metrics.note_serve("hits")
                metrics.record("serve_cache", phase="hit", key=kd,
                               bytes=len(blob))
                return blob, "hit"
            with self._lock:
                fl = self._flights.get(key)
                owner = fl is None
                if owner:
                    fl = self._flights[key] = _Flight()
            if owner:
                try:
                    t0 = time.perf_counter()
                    tbl = execute()
                    blob = table_to_ipc(tbl)
                    fl.blob = blob
                    self.put(key, blob)
                except BaseException as e:
                    if self._herd_error(e):
                        fl.error = e
                    raise
                finally:
                    fl.event.set()
                    with self._lock:
                        self._flights.pop(key, None)
                metrics.note_serve("misses")
                metrics.record(
                    "serve_cache", phase="execute", key=kd,
                    bytes=len(blob),
                    ms=round((time.perf_counter() - t0) * 1e3, 2))
                metrics.record("serve_cache", phase="miss", key=kd,
                               bytes=len(blob))
                return blob, "miss"
            # follower: block on the owner's flight — never past this
            # caller's own deadline (the owner keeps computing for ITS
            # caller; this follower's window closing is follower-local)
            deadline.check("result_cache.wait")
            metrics.note_serve("waits")
            t0 = time.perf_counter()
            wait_s = _FLIGHT_WAIT_S
            rem = deadline.remaining()
            if rem is not None:
                wait_s = max(0.0, min(wait_s, rem))
            if not fl.event.wait(timeout=wait_s):
                deadline.check("result_cache.wait")
                # the owner exceeded the flight bound without
                # publishing a result or an error: surface the typed
                # timeout and execute independently rather than wait
                # on a wedged owner forever
                tmo = FlightWaitTimeout(kd, time.perf_counter() - t0)
                metrics.note_serve("wait_timeouts")
                metrics.record("serve_cache", phase="wait_timeout",
                               key=kd, error=repr(tmo))
                tbl = execute()
                blob = table_to_ipc(tbl)
                self.put(key, blob)
                return blob, "timeout"
            if fl.error is not None:
                # the owner's failure is this caller's failure too —
                # a SchedulerQueueFull here propagates so the router
                # can shed the whole herd to another replica
                raise fl.error
            if fl.blob is not None:
                metrics.record("serve_cache", phase="wait", key=kd,
                               bytes=len(fl.blob))
                return fl.blob, "wait"
            # owner finished without result or herd-relevant error
            # (owner-local cancellation, interpreter teardown): loop
            # and take ownership

    @staticmethod
    def _herd_error(e: BaseException) -> bool:
        """Owner failures that apply to every follower of the flight.
        Owner-LOCAL outcomes must not fan out: the owner's
        cancellation/deadline belongs to its own caller, not the herd,
        and BaseExceptions (KeyboardInterrupt, SystemExit) are
        interpreter-level. Followers of a non-herd failure find neither
        blob nor error and loop to take ownership themselves."""
        from spark_tpu.scheduler.scheduler import QueryCancelled

        return (isinstance(e, Exception)
                and not isinstance(e, QueryCancelled))

    def put(self, key, blob: bytes) -> None:
        """Insert one serialized result; an oversized single result is
        served but never cached (it would evict the whole cache for
        one entry)."""
        if len(blob) <= self._max_bytes():
            self._lru[key] = blob
        self._publish_gauges()

    def lookup(self, key) -> Optional[bytes]:
        return self._lru.get(key)

    def clear(self) -> None:
        self._lru.clear()
        with self._lock:
            self._fp_cache.clear()
        self._publish_gauges()

    # -- fingerprint probe cache ----------------------------------------------

    def _fp_ttl(self) -> float:
        try:
            return float(self._conf.get(SERVE_FP_CACHE_SECONDS))
        except Exception:
            return float(SERVE_FP_CACHE_SECONDS.default)

    def result_key(self, plan) -> Tuple[Any, ...]:
        """Cache key for ``plan`` through THIS cache's fingerprint
        probe: with fingerprintCacheSeconds <= 0 this is exactly
        ``plan_result_key`` (a fresh stat walk per request); with a
        TTL, per-source probes are reused until they expire or an
        invalidation-log record drops them."""
        ttl = self._fp_ttl()
        if ttl <= 0.0:
            return plan_result_key(plan)
        from spark_tpu.io.fingerprint import source_fingerprint
        from spark_tpu.plan import logical as L

        now = time.time()
        fps = []
        for scan in L.collect_nodes(plan, L.UnresolvedScan):
            src = scan.source
            paths = getattr(src, "paths", None)
            if not paths:
                fp = source_fingerprint(src)
                fps.append(fp if fp is not None
                           else ("src", id(src)))
                continue
            pkey = tuple(str(p) for p in paths)
            with self._lock:
                hit = self._fp_cache.get(pkey)
            if hit is not None and now - hit[1] < ttl:
                fps.append(hit[0])
                continue
            fp = source_fingerprint(src)
            if fp is None:
                fps.append(("src", id(src)))
                continue
            with self._lock:
                self._fp_cache[pkey] = (fp, now)
            fps.append(fp)
        return (plan.structural_key(), tuple(fps))

    # -- fleet-wide invalidation ----------------------------------------------

    def attach_invalidation_log(self, log) -> "ResultCache":
        """Subscribe to a fleet InvalidationLog, first replaying every
        record after this cache's watermark (a reconnecting/revived
        replica catches up); a watermark older than the log's bounded
        ring forces the planned worst case — a full clear (cold,
        never stale)."""
        records, resync = log.since(self.invalidation_watermark)
        if resync:
            self.clear()
            self.invalidation_watermark = log.version
            metrics.record("serve", phase="invalidate_resync",
                           watermark=self.invalidation_watermark)
        else:
            for record in records:
                self.apply_invalidation(record)
        log.subscribe(self.apply_invalidation)
        self._inval_log = log
        return self

    def detach_invalidation_log(self) -> None:
        if self._inval_log is not None:
            self._inval_log.unsubscribe(self.apply_invalidation)
            self._inval_log = None

    def apply_invalidation(self, record: dict) -> None:
        """Drop every cached result (and fingerprint probe) whose key
        touches the record's paths. Any failure — including an
        injected ``serve.invalidate`` fault — degrades to a FULL
        clear: after an invalidation the one state this cache may not
        hold is a stale entry, and empty is always sound."""
        with trace.span("serve.invalidate",
                        version=record.get("v", 0)):
            try:
                faults.inject("serve.invalidate", self._conf)
                dropped = self._drop_paths(record.get("paths", ()))
                metrics.record("serve", phase="invalidate_apply",
                               version=record.get("v", 0),
                               dropped=dropped)
            except Exception as exc:
                self.clear()
                metrics.record(
                    "fault_recovered", point="serve.invalidate",
                    how="full_clear", error=type(exc).__name__)
            self.invalidation_watermark = max(
                self.invalidation_watermark, int(record.get("v", 0)))
        self._publish_gauges()

    @staticmethod
    def _touches(path: str, targets) -> bool:
        """Does file ``path`` equal, live under, or contain one of the
        invalidated ``targets``? (Fingerprints hold walked FILE paths;
        invalidation records may carry the source DIRECTORY.)"""
        import os as _os

        for t in targets:
            if path == t or path.startswith(t.rstrip(_os.sep)
                                            + _os.sep) \
                    or t.startswith(path.rstrip(_os.sep) + _os.sep):
                return True
        return False

    def _drop_paths(self, paths) -> int:
        targets = tuple(str(p) for p in paths)
        if not targets:
            return 0
        dropped = 0
        for key in self._lru.keys():
            fps = key[1] if isinstance(key, tuple) and len(key) == 2 \
                else ()
            hit = False
            for fp in fps if isinstance(fps, tuple) else ():
                if not isinstance(fp, tuple):
                    continue
                for triple in fp:
                    if isinstance(triple, tuple) and triple \
                            and isinstance(triple[0], str) \
                            and self._touches(triple[0], targets):
                        hit = True
                        break
                if hit:
                    break
            if hit and self._lru.pop(key) is not None:
                dropped += 1
        with self._lock:
            for pkey in list(self._fp_cache):
                if any(self._touches(str(p), targets) for p in pkey):
                    del self._fp_cache[pkey]
        return dropped

    def stats(self) -> dict:
        counters = metrics.serve_stats()
        return {
            "entries": len(self._lru),
            "bytes": self._lru.total_bytes,
            "max_bytes": self._max_bytes(),
            "evictions": self._lru.evictions,
            "hits": counters.get("hits", 0),
            "misses": counters.get("misses", 0),
            "waits": counters.get("waits", 0),
        }

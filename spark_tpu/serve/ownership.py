"""Epoch-fenced shard ownership + the fleet-wide invalidation log.

The fleet data plane's control state, promoted from the
coordination-service rendezvous idea in ``parallel/multihost.py`` to a
serving-tier deployment mode (ROADMAP item 3). Two pieces:

**Shard ownership.** A *shard* is the stable identity of one scan's
file set — a digest of its sorted path list, NOT the mtime-bearing
stat fingerprint, so ownership survives appends and only a genuine
re-pointing of a table moves its shard. The ``OwnershipCoordinator``
(router-side) maintains an **epoch-numbered shard→owner map** over the
currently healthy replicas using rendezvous (highest-random-weight)
hashing: ``owner(shard) = argmax_r H(shard | r)``, which is memoryless
— when one replica dies, ONLY its shards move (to their next-highest
survivor), everyone else's assignment is untouched. Every membership
change (breaker trip, death noticed by a probe, revival) **mints a new
epoch**; the router stamps the current epoch on every dispatched
request (``X-SparkTpu-Epoch``) and broadcasts the new map to the
survivors, whose newly-gained shards are rebuilt from source files —
the lineage-recompute analogue. A replica that receives a request
carrying an epoch OLDER than the fleet epoch it has adopted answers a
typed ``EPOCH_RETRY`` (HTTP 409) instead of serving possibly-stale
ownership state; the router (and the connect ``Client``) absorb it
through the unified RetryBudget and re-dispatch with a fresh stamp.

**Invalidation log.** Cache coherence across replica-local
ResultCaches: materialized-view refresh commits and file-rewrite
detections append versioned records here; the log pushes each record
to every subscribed cache (outside its own lock), which drops every
entry whose fingerprint touches the invalidated paths. A reconnecting
subscriber replays ``since(watermark)``; a watermark older than the
bounded ring forces a full resync (clear) — the planned, bounded
worst case: a cold cache, never a stale one.

Reference analogue: the BlockManagerMaster's epoch-stamped executor
re-registration + ``removeExecutor`` re-replication, and the
driver-side ``CacheManager`` invalidation broadcast.
"""

from __future__ import annotations

import collections
import hashlib
import re
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import metrics

SERVE_OWNERSHIP_ENABLED = CF.register(
    "spark.tpu.serve.ownership.enabled", False,
    "Fleet ownership mode: the router plans each query to the replica "
    "owning its scans (rendezvous hashing over healthy replicas), "
    "stamps every dispatch with the ownership epoch, and replicas "
    "fence stale epochs with a typed EPOCH_RETRY. Off (default) the "
    "router routes purely by policy/affinity.", bool)

SERVE_OWNERSHIP_REBUILD = CF.register(
    "spark.tpu.serve.ownership.rebuildOnFailover", True,
    "After an epoch mint re-maps a dead replica's shards, the new "
    "owners eagerly re-discover their gained shards from source files "
    "(dataset + schema warm). Off, rebuild happens lazily on the "
    "first owned query — bytes are identical either way.", bool)

SERVE_OWNERSHIP_REBUILD_TIMEOUT_S = CF.register(
    "spark.tpu.serve.ownership.rebuildTimeoutSeconds", 30.0,
    "Deadline cap on one replica's failover rebuild of newly-gained "
    "shards; on expiry the remaining shards rebuild lazily on first "
    "query (never a hang, never a wrong byte).", float)

SERVE_INVALIDATION_LOG_MAX = CF.register(
    "spark.tpu.serve.invalidationLog.maxRecords", 1024,
    "Bounded ring of invalidation records kept for watermark replay; "
    "a subscriber whose watermark predates the ring resyncs with a "
    "full cache clear (cold, never stale).", int)

#: request/response header carrying the ownership epoch fleet-wide
EPOCH_HEADER = "X-SparkTpu-Epoch"


class EpochRetry(RuntimeError):
    """A request arrived stamped with an epoch OLDER than the fleet
    epoch this replica has adopted: the sender's shard→owner map is
    stale (it may be routing to a dead owner's replacement — or past
    it). Typed and retryable: the unified RetryBudget absorbs it and
    the re-dispatch carries a fresh stamp."""

    def __init__(self, request_epoch: int, fleet_epoch: int):
        super().__init__(
            f"EPOCH_RETRY: request epoch {request_epoch} < fleet "
            f"epoch {fleet_epoch}; re-dispatch with a fresh stamp")
        self.request_epoch = int(request_epoch)
        self.fleet_epoch = int(fleet_epoch)


# --------------------------------------------------------------------------
# shard identity + rendezvous hashing
# --------------------------------------------------------------------------


def shard_key(paths: Sequence[str]) -> str:
    """Stable identity of one scan's file set: a digest of the SORTED
    path list. Deliberately mtime/size-free — appends and rewrites
    change the freshness fingerprint, not the shard, so ownership
    never migrates on a refresh."""
    joined = "\x00".join(sorted({str(p) for p in paths}))
    return hashlib.sha1(joined.encode()).hexdigest()[:16]


def rendezvous_owner(shard: str,
                     members: Sequence[str]) -> Optional[str]:
    """Highest-random-weight owner of ``shard`` among ``members``.
    sha512-based (PYTHONHASHSEED-independent, stable across processes)
    and memoryless: removing one member moves only that member's
    shards."""
    if not members:
        return None
    return max(
        sorted(set(str(m) for m in members)),
        key=lambda rid: hashlib.sha512(
            f"{shard}|{rid}".encode()).digest())


_TABLE_RE = re.compile(
    r"\b(?:from|join)\s+([A-Za-z_][A-Za-z0-9_.]*)", re.IGNORECASE)


def tables_in_sql(sql: str) -> List[str]:
    """Conservative table-identifier extraction from a SQL string
    (FROM/JOIN targets). Subqueries contribute their inner FROMs too —
    over-collection is harmless, the coordinator drops unknown names."""
    return [m.lower() for m in _TABLE_RE.findall(sql or "")]


class OwnershipCoordinator:
    """Router-side epoch-numbered shard→owner map.

    ``observe(healthy_ids)`` mints a new epoch whenever the healthy
    membership changes (including the first observation), returning
    the broadcast payload; unchanged membership returns None. The
    owner function itself is pure rendezvous hashing over the member
    snapshot, so the map never needs repair — only the epoch number
    and the member set are state."""

    def __init__(self, conf=None):
        self._conf = conf
        self._lock = locks.named_lock("serve.ownership")
        self.epoch = 0
        self._members: Tuple[str, ...] = ()
        #: shard -> path list (learned from replicas' GET /shards)
        self._shards: Dict[str, Tuple[str, ...]] = {}
        #: table name (lower) -> shard
        self._tables: Dict[str, str] = {}

    def enabled(self) -> bool:
        try:
            return bool(self._conf.get(SERVE_OWNERSHIP_ENABLED)) \
                if self._conf is not None \
                else bool(SERVE_OWNERSHIP_ENABLED.default)
        except Exception:
            return False

    # -- shard universe -----------------------------------------------------

    def register_shards(self, tables: Dict[str, dict]) -> None:
        """Merge one replica's shard report: ``{table: {"shard": key,
        "paths": [...]}}`` (replicas over one catalog agree; the merge
        is idempotent)."""
        with self._lock:
            for name, info in (tables or {}).items():
                sk = str(info.get("shard", ""))
                if not sk:
                    continue
                self._tables[str(name).lower()] = sk
                self._shards[sk] = tuple(info.get("paths", ()))

    def shards_for_sql(self, sql: str) -> List[str]:
        """Shard keys a SQL query's scans live in (known tables only)."""
        with self._lock:
            tables = dict(self._tables)
        out = []
        for name in tables_in_sql(sql):
            sk = tables.get(name)
            if sk is not None and sk not in out:
                out.append(sk)
        return out

    # -- epoch / membership --------------------------------------------------

    def observe(self, healthy_ids: Iterable[str]) -> Optional[dict]:
        """Note the current healthy membership; mint epoch+1 when it
        changed (or on the first observation) and return the broadcast
        payload {"epoch", "owners", "shards"} — None when nothing
        moved. Metrics are emitted outside the lock."""
        ids = tuple(sorted(set(str(i) for i in healthy_ids)))
        if not ids:
            return None  # a fully-dead fleet has nobody to own shards
        with self._lock:
            if ids == self._members and self.epoch > 0:
                return None
            prev = self._members
            self._members = ids
            self.epoch += 1
            epoch = self.epoch
            owners = {s: rendezvous_owner(s, ids)
                      for s in self._shards}
            shards = {s: list(p) for s, p in self._shards.items()}
        metrics.note_serve("epoch_mints")
        metrics.record("serve", phase="epoch_mint", epoch=epoch,
                       members=list(ids), was=list(prev),
                       shards=len(owners))
        return {"epoch": epoch, "owners": owners, "shards": shards}

    def bump_to(self, epoch: int) -> None:
        """Adopt a newer epoch learned from a replica's EPOCH_RETRY —
        monotonic, never backwards (a second router, or a replica that
        outlived this router's state)."""
        with self._lock:
            if int(epoch) > self.epoch:
                self.epoch = int(epoch)

    def owner_for(self, shards: Sequence[str]) -> Optional[str]:
        """Preferred replica for a query touching ``shards``: the
        rendezvous owner of the first shard (single-table queries are
        the common case; a join's probe side follows its build side)."""
        with self._lock:
            members = self._members
        for s in shards:
            return rendezvous_owner(s, members)
        return None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "epoch": self.epoch,
                "members": list(self._members),
                "shards": {
                    s: rendezvous_owner(s, self._members)
                    for s in self._shards},
                "tables": dict(self._tables),
            }


# --------------------------------------------------------------------------
# catalog -> shard report (replica side of GET /shards)
# --------------------------------------------------------------------------


def catalog_shards(session) -> Dict[str, dict]:
    """``{table: {"shard": key, "paths": [...]}}`` for every catalog
    view backed by exactly one file-fingerprinted scan — the replica's
    shard report. Views over in-memory relations or multiple scans
    have no stable file identity and are routed by policy instead."""
    from spark_tpu.plan import logical as L

    out: Dict[str, dict] = {}
    views = getattr(getattr(session, "catalog", None), "_views", None)
    if not views:
        return out
    for name, plan in list(views.items()):
        try:
            scans = L.collect_nodes(plan, L.UnresolvedScan)
            if len(scans) != 1:
                continue
            src = scans[0].source
            paths = getattr(src, "paths", None)
            if not paths or not callable(
                    getattr(src, "_fingerprint", None)):
                continue
            out[str(name).lower()] = {
                "shard": shard_key(paths),
                "paths": [str(p) for p in paths]}
        except Exception:
            continue  # one odd view must not break the report
    return out


# --------------------------------------------------------------------------
# fleet-wide invalidation log
# --------------------------------------------------------------------------


class InvalidationLog:
    """Versioned, bounded log of cache-invalidation records with live
    push and watermark replay.

    ``append`` assigns the next version and pushes the record to every
    subscriber OUTSIDE the log lock (subscribers take their own cache
    locks). ``since(watermark)`` returns the records a reconnecting
    subscriber missed, or ``resync=True`` when the watermark predates
    the bounded ring — the subscriber must clear instead (cold, never
    stale)."""

    def __init__(self, conf=None):
        self._conf = conf
        self._lock = locks.named_lock("serve.invalidation")
        self._records: collections.deque = collections.deque()
        self._version = 0
        #: version of the OLDEST record still in the ring (0 = nothing
        #: has ever been trimmed)
        self._trimmed_through = 0
        self._subs: List = []

    def _max_records(self) -> int:
        try:
            return max(1, int(
                self._conf.get(SERVE_INVALIDATION_LOG_MAX))) \
                if self._conf is not None \
                else int(SERVE_INVALIDATION_LOG_MAX.default)
        except Exception:
            return int(SERVE_INVALIDATION_LOG_MAX.default)

    def append(self, kind: str, paths: Sequence[str],
               digest: Optional[str] = None) -> int:
        """Record one invalidation (``mview_refresh`` /
        ``source_changed``) over ``paths`` and push it to every
        subscriber; returns the assigned version."""
        with self._lock:
            self._version += 1
            record = {"v": self._version, "kind": str(kind),
                      "paths": tuple(str(p) for p in paths),
                      "digest": digest, "ts": time.time()}
            self._records.append(record)
            cap = self._max_records()
            while len(self._records) > cap:
                dropped = self._records.popleft()
                self._trimmed_through = dropped["v"]
            subs = list(self._subs)
        metrics.note_serve("invalidations")
        metrics.record("serve", phase="invalidate", event=str(kind),
                       version=record["v"], paths=len(record["paths"]))
        for cb in subs:  # outside the log lock: callbacks lock caches
            try:
                cb(record)
            except Exception as exc:
                # a broken subscriber must not lose the record for the
                # others; its own apply path degrades to a full clear
                metrics.record("serve", phase="invalidate_push_error",
                               error=type(exc).__name__)
        return record["v"]

    def subscribe(self, cb) -> None:
        with self._lock:
            if cb not in self._subs:
                self._subs.append(cb)

    def unsubscribe(self, cb) -> None:
        with self._lock:
            if cb in self._subs:
                self._subs.remove(cb)

    def since(self, watermark: int) -> Tuple[List[dict], bool]:
        """(records after ``watermark``, needs_resync). Resync when the
        watermark predates the ring's oldest retained record."""
        with self._lock:
            if int(watermark) < self._trimmed_through:
                return [], True
            return [dict(r) for r in self._records
                    if r["v"] > int(watermark)], False

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def snapshot(self) -> dict:
        with self._lock:
            return {"version": self._version,
                    "records": len(self._records),
                    "trimmed_through": self._trimmed_through,
                    "subscribers": len(self._subs)}


def session_invalidation_log(session) -> InvalidationLog:
    """The one InvalidationLog of a session (created on first use);
    mview refreshes, file-rewrite detections, and every fleet-mode
    ResultCache share it."""
    log = getattr(session, "serve_invalidation_log", None)
    if log is None:
        log = InvalidationLog(session.conf)
        session.serve_invalidation_log = log
    return log

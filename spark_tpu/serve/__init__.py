"""Scale-out serving tier (ROADMAP item 4): replica federation router
+ plan-keyed result cache + cross-replica admission shedding.

Three pieces:

- ``router.FederationRouter`` — an HTTP front end speaking the connect
  protocol over N ConnectServer replicas (``serve_fleet`` spawns an
  in-process fleet over one session; production runs one replica
  process per host and hands the router their URLs).
- ``result_cache.ResultCache`` — Arrow-IPC results keyed by the
  structural plan key + scan-source freshness fingerprints, bounded by
  ``spark.tpu.serve.resultCache.maxBytes``, single-flight per key.
- ``federation.Federation`` — health probing, routing policy
  (``spark.tpu.serve.policy``), 429 shedding to the least-loaded
  replica, and bounded re-dispatch around replica death (fault point
  ``serve.dispatch``).
"""

from spark_tpu.serve.federation import (Federation, NoHealthyReplica,
                                        Replica)
from spark_tpu.serve.result_cache import (ResultCache, ipc_to_table,
                                          plan_result_key,
                                          table_to_ipc)
from spark_tpu.serve.router import (FederationRouter, Fleet,
                                    serve_fleet)

__all__ = [
    "Federation",
    "FederationRouter",
    "Fleet",
    "NoHealthyReplica",
    "Replica",
    "ResultCache",
    "ipc_to_table",
    "plan_result_key",
    "serve_fleet",
    "table_to_ipc",
]

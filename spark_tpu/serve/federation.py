"""Cross-replica dispatch: health probing, policy pick, admission
shedding, and bounded re-dispatch on replica death.

The router's brain. Each replica is a ConnectServer (in-process thread
or separate process — only its URL matters here) whose ``/health``
reports ``replica`` id, live ``queue_depth`` and ``running`` count
(scheduler/scheduler.py snapshots under its own lock). Dispatch:

- **pick** honors session affinity first (the ``X-SparkTpu-Replica``
  header a client echoes back), then the configured policy
  (``spark.tpu.serve.policy``): ``round_robin`` cycles healthy
  replicas, ``least_queued`` takes the one with the fewest
  queued+running queries at the last probe.
- **shed** — a 429 (SchedulerQueueFull) from the chosen replica is NOT
  surfaced: the request re-dispatches to the least-loaded healthy
  replica that has not itself answered 429 for this request. Only when
  every healthy replica is saturated does the client see a 429, with
  ``Retry-After = min`` across the replicas' hints (the soonest any
  capacity frees up anywhere in the fleet).
- **re-dispatch** — a connection failure (or an injected
  ``serve.dispatch`` fault: a replica dying mid-query) marks the
  replica unhealthy and retries a different one, bounded by
  ``spark.tpu.serve.dispatchRetries``. The single-flight result cache
  keys re-dispatched queries to the same structural key, so the query
  still executes at most once even when two replicas see it.

Reference analogue: the driver-side OutputCommitCoordinator +
ExecutorFailuresAllowlist shape (task re-offer on a different executor
after a lost one, bounded by spark.task.maxFailures).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import faults, metrics, trace

#: response headers a replica sets that the router relays verbatim
RELAY_HEADERS = ("X-Query-Id", "X-Queue-Wait-Ms", "X-Cache",
                 "Retry-After", "X-SparkTpu-Replica",
                 "X-SparkTpu-Trace-Id")

#: connection-level failures that mean "this replica is gone" — the
#: re-dispatch trigger (same set the connect Client classifies as
#: retryable)
_CONN_ERRORS = (ConnectionRefusedError, ConnectionResetError,
                ConnectionAbortedError, BrokenPipeError, OSError)


class NoHealthyReplica(RuntimeError):
    """Every replica is down (distinct from all-saturated, which is a
    429 the client can retry after Retry-After)."""


class Replica:
    """One backend ConnectServer as the router sees it: URL, last
    probed load, and health."""

    def __init__(self, rid: str, url: str):
        self.id = str(rid)
        self.url = url.rstrip("/")
        self.healthy = True
        self.queue_depth = 0
        self.running = 0
        self.last_probe = 0.0

    @property
    def load(self) -> int:
        return int(self.queue_depth) + int(self.running)

    def snapshot(self) -> dict:
        return {"id": self.id, "url": self.url,
                "healthy": self.healthy,
                "queue_depth": self.queue_depth,
                "running": self.running}


def _as_replica(i: int, r) -> Replica:
    """Accept a ConnectServer, a URL string, or an (id, url) pair."""
    if isinstance(r, Replica):
        return r
    if isinstance(r, str):
        return Replica(f"r{i}", r)
    if isinstance(r, (tuple, list)) and len(r) == 2:
        return Replica(r[0], r[1])
    rid = getattr(r, "replica_id", None) or f"r{i}"
    return Replica(rid, r.url)


class Federation:
    """The replica set + dispatch engine; owned by a FederationRouter
    but usable headless (bench drives it directly)."""

    def __init__(self, replicas: Sequence, conf=None,
                 timeout: float = 120.0):
        self._conf = conf if conf is not None else CF.RuntimeConf()
        self.replicas: List[Replica] = [
            _as_replica(i, r) for i, r in enumerate(replicas)]
        if not self.replicas:
            raise ValueError("federation needs at least one replica")
        self.timeout = float(timeout)
        self._rr = 0
        self._lock = locks.named_lock("serve.federation")

    # -- health ---------------------------------------------------------------

    def probe(self, force: bool = False) -> None:
        """Refresh each replica's /health snapshot; throttled by
        ``spark.tpu.serve.healthProbeSeconds`` unless forced. A probe
        failure marks the replica unhealthy; a later success revives
        it (a restarted replica rejoins without router restart)."""
        try:
            max_age = float(self._conf.get(CF.SERVE_HEALTH_PROBE_SECONDS))
        except Exception:
            max_age = float(CF.SERVE_HEALTH_PROBE_SECONDS.default)
        now = time.time()
        for r in self.replicas:
            if not force and r.last_probe and \
                    now - r.last_probe < max_age:
                continue
            try:
                with urllib.request.urlopen(r.url + "/health",
                                            timeout=2.0) as resp:
                    h = json.loads(resp.read())
                r.healthy = h.get("status") == "ok"
                r.queue_depth = int(h.get("queue_depth", 0))
                r.running = int(h.get("running", 0))
                rid = h.get("replica")
                if rid:
                    r.id = str(rid)
            except Exception:
                r.healthy = False
            r.last_probe = time.time()

    def healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.healthy]

    def status(self) -> List[dict]:
        return [r.snapshot() for r in self.replicas]

    # -- selection ------------------------------------------------------------

    def pick(self, affinity: Optional[str] = None,
             exclude: Sequence[str] = (),
             least_loaded: bool = False) -> Optional[Replica]:
        """Next replica per policy among healthy, non-excluded ones.
        ``affinity`` (a replica id) wins when that replica is still
        eligible — consistent session routing keeps a client's
        scheduler pool state and compile warmth on one backend.
        ``least_loaded`` forces the load-based choice regardless of
        policy: the shed path always moves work to the emptiest queue."""
        pool = [r for r in self.healthy() if r.id not in set(exclude)]
        if not pool:
            return None
        if affinity:
            for r in pool:
                if r.id == affinity:
                    return r
        try:
            policy = str(self._conf.get(CF.SERVE_POLICY))
        except Exception:
            policy = str(CF.SERVE_POLICY.default)
        if least_loaded or policy == "least_queued":
            return min(pool, key=lambda r: (r.load, r.id))
        with self._lock:
            r = pool[self._rr % len(pool)]
            self._rr += 1
        return r

    # -- dispatch -------------------------------------------------------------

    def forward(self, replica: Replica, method: str, path: str,
                body: Optional[bytes],
                headers: Optional[dict] = None
                ) -> Tuple[int, bytes, Dict[str, str]]:
        """One HTTP round trip to a replica. Returns (code, body,
        relay-headers); 4xx/5xx come back as values (HTTPError bodies
        are real payloads here: 429 carries retry_after_s), connection
        failures raise for the re-dispatch loop."""
        req = urllib.request.Request(
            replica.url + path, data=body, method=method,
            headers=headers or {})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                data = resp.read()
                hdr = {k: resp.headers[k] for k in RELAY_HEADERS
                       if resp.headers.get(k)}
                return resp.status, data, hdr
        except urllib.error.HTTPError as e:
            data = e.read()
            hdr = {k: e.headers[k] for k in RELAY_HEADERS
                   if e.headers.get(k)}
            return e.code, data, hdr
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", None)
            if isinstance(reason, _CONN_ERRORS):
                raise reason
            raise

    def dispatch(self, method: str, path: str, body: Optional[bytes],
                 headers: Optional[dict] = None,
                 affinity: Optional[str] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one request: pick -> forward, shedding 429s to the
        least-loaded remaining replica and re-dispatching around dead
        ones (bounded). The return is what the client sees. One
        ``router.dispatch`` span covers the whole routing decision
        (every shed and re-dispatch attempt stays in the caller's
        trace); each attempt is a ``router.forward`` child whose
        context ships to the replica in ``X-SparkTpu-Trace``."""
        with trace.span("router.dispatch", path=path):
            return self._dispatch_traced(method, path, body,
                                         headers, affinity)

    def _dispatch_traced(self, method: str, path: str,
                         body: Optional[bytes],
                         headers: Optional[dict] = None,
                         affinity: Optional[str] = None
                         ) -> Tuple[int, bytes, Dict[str, str]]:
        try:
            retries = max(0, int(
                self._conf.get(CF.SERVE_DISPATCH_RETRIES)))
        except Exception:
            retries = int(CF.SERVE_DISPATCH_RETRIES.default)
        exhausted: set = set()    # saturated (429) this request
        dead: set = set()         # connection-failed this request
        retry_afters: List[float] = []
        last_err: Optional[BaseException] = None
        shed = False
        for attempt in range(retries + len(self.replicas) + 1):
            self.probe()
            r = self.pick(affinity=affinity,
                          exclude=exhausted | dead,
                          least_loaded=shed)
            affinity = None  # only honored for the first choice
            if r is None:
                break
            metrics.note_serve("dispatches")
            metrics.record("serve", phase="dispatch", replica=r.id,
                           path=path)
            try:
                with trace.span("router.forward", replica=r.id):
                    faults.inject("serve.dispatch", self._conf)
                    # rewrite (not passthrough) the trace header: the
                    # replica's spans must parent under THIS forward
                    # attempt, so shed/re-dispatch attempts stay
                    # distinguishable in the waterfall
                    hdrs = dict(headers or {})
                    hv = trace.header_value()
                    if hv:
                        hdrs[trace.TRACE_HEADER] = hv
                    code, data, hdr = self.forward(
                        r, method, path, body, hdrs)
            except _CONN_ERRORS as e:
                last_err = e
                r.healthy = False
                dead.add(r.id)
                if len(dead) > retries:
                    break
                metrics.note_serve("replica_failures")
                metrics.note_serve("redispatches")
                metrics.record("serve", phase="replica_down",
                               replica=r.id, error=type(e).__name__)
                metrics.record("serve", phase="redispatch",
                               replica=r.id)
                continue
            except faults.InjectedFault as e:
                last_err = e
                if e.kind not in ("transient", "hang"):
                    raise  # corrupt/oom: surface typed, no retry
                # injected replica death mid-query: same recovery as a
                # real connection failure
                r.healthy = False
                dead.add(r.id)
                if len(dead) > retries:
                    break
                metrics.note_serve("replica_failures")
                metrics.note_serve("redispatches")
                metrics.record("serve", phase="replica_down",
                               replica=r.id, error=type(e).__name__)
                metrics.record("serve", phase="redispatch",
                               replica=r.id)
                continue
            if code == 429:
                # admission shedding: this replica's scheduler is
                # full — take the request to the emptiest other queue
                exhausted.add(r.id)
                try:
                    detail = json.loads(data)
                    ra = float(hdr.get("Retry-After")
                               or detail.get("retry_after_s") or 0.0)
                except Exception:
                    ra = 0.0
                retry_afters.append(ra)
                shed = True
                metrics.note_serve("sheds")
                metrics.record("serve", phase="shed", replica=r.id,
                               retry_after_s=ra)
                continue
            return code, data, hdr
        if retry_afters:
            # ALL healthy replicas saturated: now (and only now) the
            # client sees the 429; Retry-After is the soonest any
            # replica expects capacity
            ra = min(retry_afters)
            metrics.note_serve("rejected")
            metrics.record("serve", phase="rejected",
                           retry_after_s=ra)
            body_out = json.dumps(
                {"error": "SchedulerQueueFull",
                 "message": "all replicas saturated",
                 "retry_after_s": ra}).encode()
            return 429, body_out, {"Retry-After": f"{ra:g}"}
        if last_err is not None:
            raise NoHealthyReplica(
                f"dispatch failed after replica failures "
                f"(last: {last_err!r})") from last_err
        raise NoHealthyReplica("no healthy replica available")

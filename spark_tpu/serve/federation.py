"""Cross-replica dispatch: health probing, policy pick, admission
shedding, and bounded re-dispatch on replica death.

The router's brain. Each replica is a ConnectServer (in-process thread
or separate process — only its URL matters here) whose ``/health``
reports ``replica`` id, live ``queue_depth`` and ``running`` count
(scheduler/scheduler.py snapshots under its own lock). Dispatch:

- **pick** honors session affinity first (the ``X-SparkTpu-Replica``
  header a client echoes back), then the configured policy
  (``spark.tpu.serve.policy``): ``round_robin`` cycles healthy
  replicas, ``least_queued`` takes the one with the fewest
  queued+running queries at the last probe.
- **shed** — a 429 (SchedulerQueueFull) from the chosen replica is NOT
  surfaced: the request re-dispatches to the least-loaded healthy
  replica that has not itself answered 429 for this request. Only when
  every healthy replica is saturated does the client see a 429, with
  ``Retry-After = min`` across the replicas' hints (the soonest any
  capacity frees up anywhere in the fleet).
- **re-dispatch** — a connection failure (or an injected
  ``serve.dispatch`` fault: a replica dying mid-query) marks the
  replica unhealthy and retries a different one, bounded by
  ``spark.tpu.serve.dispatchRetries``. The single-flight result cache
  keys re-dispatched queries to the same structural key, so the query
  still executes at most once even when two replicas see it.

Reference analogue: the driver-side OutputCommitCoordinator +
ExecutorFailuresAllowlist shape (task re-offer on a different executor
after a lost one, bounded by spark.task.maxFailures).
"""

from __future__ import annotations

import collections
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from spark_tpu import locks
from spark_tpu import conf as CF
from spark_tpu import deadline, faults, metrics, recovery, trace
from spark_tpu.serve.ownership import (EPOCH_HEADER,
                                       OwnershipCoordinator)

SERVE_BREAKER_ENABLED = CF.register(
    "spark.tpu.serve.breaker.enabled", True,
    "Per-replica circuit breaker: a replica whose recent dispatch "
    "failure rate crosses breaker.failureRate stops receiving traffic "
    "(open) until a probe trickle (half-open) proves it healthy again.",
    bool)
SERVE_BREAKER_WINDOW_S = CF.register(
    "spark.tpu.serve.breaker.windowSeconds", 30.0,
    "Sliding window over which a replica's dispatch failure rate is "
    "measured for the circuit breaker.", float)
SERVE_BREAKER_MIN_REQUESTS = CF.register(
    "spark.tpu.serve.breaker.minRequests", 5,
    "Minimum dispatch outcomes inside the window before the breaker "
    "will open (a single failure on a cold replica is not a rate).",
    int)
SERVE_BREAKER_FAILURE_RATE = CF.register(
    "spark.tpu.serve.breaker.failureRate", 0.5,
    "Windowed failure-rate threshold at which a replica's breaker "
    "opens.", float)
SERVE_BREAKER_OPEN_S = CF.register(
    "spark.tpu.serve.breaker.openSeconds", 2.0,
    "How long an open breaker blocks all traffic before admitting a "
    "single half-open probe request.", float)

SERVE_BROWNOUT_ENABLED = CF.register(
    "spark.tpu.serve.brownout.enabled", True,
    "Fleet-wide brownout: under sustained dispatch pressure the fleet "
    "sheds analysis-heavy OPTIONAL work (trace sampling, compile "
    "pre-warm, scan auto-cache promotion) before it sheds queries.",
    bool)
SERVE_BROWNOUT_WINDOW_S = CF.register(
    "spark.tpu.serve.brownout.windowSeconds", 30.0,
    "Sliding window over which fleet dispatch pressure (sheds + "
    "failures as a fraction of outcomes) is measured.", float)
SERVE_BROWNOUT_ENTER_RATE = CF.register(
    "spark.tpu.serve.brownout.enterRate", 0.5,
    "Windowed pressure at or above which the fleet enters brownout "
    "level 1.", float)
SERVE_BROWNOUT_EXIT_RATE = CF.register(
    "spark.tpu.serve.brownout.exitRate", 0.1,
    "Windowed pressure at or below which the fleet exits brownout "
    "(hysteresis: between exitRate and enterRate the level holds).",
    float)
SERVE_BROWNOUT_MIN_EVENTS = CF.register(
    "spark.tpu.serve.brownout.minEvents", 8,
    "Minimum dispatch outcomes inside the window before the brownout "
    "level may change.", int)

#: response headers a replica sets that the router relays verbatim
RELAY_HEADERS = ("X-Query-Id", "X-Queue-Wait-Ms", "X-Cache",
                 "Retry-After", "X-SparkTpu-Replica",
                 "X-SparkTpu-Trace-Id", "X-SparkTpu-Epoch",
                 "X-SparkTpu-Predicted-Ms", "X-SparkTpu-Sched-Policy",
                 "X-SparkTpu-Brownout")

#: connection-level failures that mean "this replica is gone" — the
#: re-dispatch trigger (same set the connect Client classifies as
#: retryable)
_CONN_ERRORS = (ConnectionRefusedError, ConnectionResetError,
                ConnectionAbortedError, BrokenPipeError, OSError)


class NoHealthyReplica(RuntimeError):
    """Every replica is down (distinct from all-saturated, which is a
    429 the client can retry after Retry-After)."""


class CircuitBreaker:
    """Per-replica closed/open/half-open breaker over a sliding window
    of dispatch outcomes.

    closed: outcomes accumulate in the window; when there are at least
    ``breaker.minRequests`` of them and the failure fraction reaches
    ``breaker.failureRate``, the breaker OPENS. open: all traffic is
    refused for ``breaker.openSeconds``, then the next ``admits()``
    moves to half-open. half-open: exactly ONE probe request is
    admitted at a time (``begin()`` claims the slot); its success
    CLOSES the breaker and clears the window, its failure re-OPENS it.
    The router's health probe is orthogonal: the breaker measures real
    dispatch outcomes, not /health reachability, so a replica that
    answers /health but fails queries still trips."""

    _MAX_TRANSITIONS = 32

    def __init__(self, conf=None):
        self._conf = conf
        self._lock = locks.named_lock("serve.breaker")
        #: replica id, for the breaker_transition metrics events
        self.owner = ""
        self.state = "closed"
        self._window: collections.deque = collections.deque()
        self._opened_at = 0.0
        self._probe_inflight = False
        self._last_change: Optional[Tuple[str, str]] = None
        #: bounded (ts, from, to) history — the chaos campaign asserts
        #: open -> half_open -> closed recovery through this
        self.state_changes: List[Tuple[float, str, str]] = []

    def _param(self, entry, cast):
        try:
            return cast(self._conf.get(entry)) if self._conf is not None \
                else cast(entry.default)
        except Exception:
            return cast(entry.default)

    def _enabled(self) -> bool:
        return self._param(SERVE_BREAKER_ENABLED, bool)

    def _set_state(self, to: str) -> None:
        if to == self.state:
            return
        self.state_changes.append((time.time(), self.state, to))
        del self.state_changes[:-self._MAX_TRANSITIONS]
        self._last_change = (self.state, to)
        self.state = to

    def _publish(self) -> None:
        """Emit the latest transition as a metrics event — called by
        the public methods AFTER releasing the breaker lock (metrics
        takes its own registry lock; same outside-the-lock discipline
        as the brownout controller)."""
        with self._lock:
            change, self._last_change = self._last_change, None
        if change is None:
            return
        metrics.note_serve("breaker_transitions")
        metrics.record("serve", phase="breaker_transition",
                       replica=self.owner, from_state=change[0],
                       to_state=change[1])

    def _prune(self, now: float) -> None:
        horizon = now - self._param(SERVE_BREAKER_WINDOW_S, float)
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    def admits(self) -> bool:
        """May this replica receive a request right now? (Transitions
        open -> half_open once openSeconds have elapsed.)"""
        if not self._enabled():
            return True
        with self._lock:
            if self.state == "open":
                open_s = self._param(SERVE_BREAKER_OPEN_S, float)
                if time.time() - self._opened_at >= open_s:
                    self._set_state("half_open")
                    self._probe_inflight = False
                else:
                    return False
            if self.state == "half_open":
                result = not self._probe_inflight
            else:
                result = True
        self._publish()
        return result

    def reset(self) -> None:
        """Forget all window history and transitions and return to
        closed — used between directed chaos scenarios so one
        scenario's outcome mix does not skew the next one's rate."""
        with self._lock:
            self._window.clear()
            self._probe_inflight = False
            self.state = "closed"
            self._last_change = None
            del self.state_changes[:]

    def begin(self) -> None:
        """A request is about to be forwarded: in half-open this claims
        the single probe slot."""
        if not self._enabled():
            return
        with self._lock:
            if self.state == "half_open":
                self._probe_inflight = True

    def success(self) -> None:
        if not self._enabled():
            return
        with self._lock:
            if self.state == "half_open":
                # the probe proved the replica: full traffic resumes
                # with a clean slate
                self._set_state("closed")
                self._window.clear()
                self._probe_inflight = False
            elif self.state == "closed":
                now = time.time()
                self._window.append((now, True))
                self._prune(now)
        self._publish()

    def trip(self) -> None:
        """OPEN immediately on a connection-level dispatch failure —
        replica death is not a *rate*, it is a fact. ``failure()``
        waits for ``minRequests`` outcomes before it will open, which
        is right for flaky-but-alive replicas and wrong for dead ones:
        inside the healthProbeSeconds throttle window a dead replica
        with a closed breaker kept absorbing one doomed forward per
        dispatch (the probe-vs-dispatch race the PR-14 chaos run
        caught). The outcome still lands in the window so snapshots
        account for it."""
        if not self._enabled():
            return
        with self._lock:
            now = time.time()
            self._window.append((now, False))
            self._prune(now)
            if self.state in ("closed", "half_open"):
                self._set_state("open")
                self._opened_at = now
                self._probe_inflight = False
                self._window.clear()
        self._publish()

    def failure(self) -> None:
        if not self._enabled():
            return
        with self._lock:
            now = time.time()
            if self.state == "half_open":
                self._set_state("open")
                self._opened_at = now
                self._probe_inflight = False
            elif self.state == "closed":
                self._window.append((now, False))
                self._prune(now)
                total = len(self._window)
                fails = sum(1 for _, ok in self._window if not ok)
                if (total >= self._param(SERVE_BREAKER_MIN_REQUESTS,
                                         int)
                        and fails / total
                        >= self._param(SERVE_BREAKER_FAILURE_RATE,
                                       float)):
                    self._set_state("open")
                    self._opened_at = now
                    self._window.clear()
        self._publish()

    def snapshot(self) -> dict:
        with self._lock:
            total = len(self._window)
            fails = sum(1 for _, ok in self._window if not ok)
            return {
                "state": self.state,
                "window_requests": total,
                "window_failures": fails,
                "state_changes": [
                    {"at": ts, "from": a, "to": b}
                    for ts, a, b in self.state_changes],
            }


class BrownoutController:
    """Fleet-wide load-shedding level derived from dispatch outcomes.

    Every dispatch outcome is noted as ``ok`` / ``shed`` (a 429 from a
    saturated replica) / ``failure`` (replica death). When the windowed
    pressure — (shed + failure) / total — reaches ``brownout.enterRate``
    with at least ``brownout.minEvents`` outcomes, the fleet enters
    level 1: OPTIONAL analysis-heavy work is shed before any query is
    (trace/_sample_root stops sampling new traces, compile/service
    skips pre-warm, io/datasource stops auto-cache promotion). Pressure
    at or below ``brownout.exitRate`` exits; between the two rates the
    level holds (hysteresis). The level is published through
    ``metrics.set_brownout`` so those consumers need no reference to
    the federation."""

    def __init__(self, conf=None):
        self._conf = conf
        self._lock = locks.named_lock("serve.brownout")
        self._window: collections.deque = collections.deque()
        self.level = 0

    def _param(self, entry, cast):
        try:
            return cast(self._conf.get(entry)) if self._conf is not None \
                else cast(entry.default)
        except Exception:
            return cast(entry.default)

    def note(self, kind: str) -> None:
        """Record one dispatch outcome (``ok``/``shed``/``failure``)
        and re-evaluate the level."""
        if not self._param(SERVE_BROWNOUT_ENABLED, bool):
            return
        level = None
        with self._lock:
            now = time.time()
            self._window.append((now, kind))
            horizon = now - self._param(SERVE_BROWNOUT_WINDOW_S, float)
            w = self._window
            while w and w[0][0] < horizon:
                w.popleft()
            total = len(w)
            if total >= self._param(SERVE_BROWNOUT_MIN_EVENTS, int):
                pressure = sum(
                    1 for _, k in w if k != "ok") / total
                if self.level == 0 and pressure >= self._param(
                        SERVE_BROWNOUT_ENTER_RATE, float):
                    self.level = 1
                    level = 1
                elif self.level > 0 and pressure <= self._param(
                        SERVE_BROWNOUT_EXIT_RATE, float):
                    self.level = 0
                    level = 0
        if level is not None:
            metrics.set_brownout(level)
            metrics.record("serve", phase="brownout",
                           level=level)

    def snapshot(self) -> dict:
        with self._lock:
            total = len(self._window)
            bad = sum(1 for _, k in self._window if k != "ok")
            return {"level": self.level, "window_events": total,
                    "window_pressure": (bad / total) if total else 0.0}


class Replica:
    """One backend ConnectServer as the router sees it: URL, last
    probed load, and health."""

    def __init__(self, rid: str, url: str):
        self.id = str(rid)
        self.url = url.rstrip("/")
        self.healthy = True
        self.queue_depth = 0
        self.running = 0
        self.last_probe = 0.0
        self.breaker = CircuitBreaker()

    @property
    def load(self) -> int:
        return int(self.queue_depth) + int(self.running)

    def snapshot(self) -> dict:
        return {"id": self.id, "url": self.url,
                "healthy": self.healthy,
                "queue_depth": self.queue_depth,
                "running": self.running,
                "breaker": self.breaker.snapshot()}


def _as_replica(i: int, r) -> Replica:
    """Accept a ConnectServer, a URL string, or an (id, url) pair."""
    if isinstance(r, Replica):
        return r
    if isinstance(r, str):
        return Replica(f"r{i}", r)
    if isinstance(r, (tuple, list)) and len(r) == 2:
        return Replica(r[0], r[1])
    rid = getattr(r, "replica_id", None) or f"r{i}"
    return Replica(rid, r.url)


class Federation:
    """The replica set + dispatch engine; owned by a FederationRouter
    but usable headless (bench drives it directly)."""

    def __init__(self, replicas: Sequence, conf=None,
                 timeout: float = 120.0):
        self._conf = conf if conf is not None else CF.RuntimeConf()
        self.replicas: List[Replica] = [
            _as_replica(i, r) for i, r in enumerate(replicas)]
        if not self.replicas:
            raise ValueError("federation needs at least one replica")
        self.timeout = float(timeout)
        self._rr = 0
        self._lock = locks.named_lock("serve.federation")
        for r in self.replicas:
            r.breaker._conf = self._conf
            r.breaker.owner = r.id
        self.brownout = BrownoutController(self._conf)
        self.ownership = OwnershipCoordinator(self._conf)

    # -- health ---------------------------------------------------------------

    def probe(self, force: bool = False) -> None:
        """Refresh each replica's /health snapshot; throttled by
        ``spark.tpu.serve.healthProbeSeconds`` unless forced. A probe
        failure marks the replica unhealthy; a later success revives
        it (a restarted replica rejoins without router restart)."""
        try:
            max_age = float(self._conf.get(CF.SERVE_HEALTH_PROBE_SECONDS))
        except Exception:
            max_age = float(CF.SERVE_HEALTH_PROBE_SECONDS.default)
        now = time.time()
        for r in self.replicas:
            if not force and r.last_probe and \
                    now - r.last_probe < max_age:
                continue
            try:
                with urllib.request.urlopen(r.url + "/health",
                                            timeout=2.0) as resp:
                    h = json.loads(resp.read())
                r.healthy = h.get("status") == "ok"
                r.queue_depth = int(h.get("queue_depth", 0))
                r.running = int(h.get("running", 0))
                rid = h.get("replica")
                if rid:
                    r.id = str(rid)
                    r.breaker.owner = r.id
                if r.healthy and self.ownership.enabled():
                    self._fetch_shards(r)
            except Exception:
                r.healthy = False
            r.last_probe = time.time()
        if self.ownership.enabled():
            self._sync_ownership()

    def _fetch_shards(self, r: Replica) -> None:
        """Learn the shard map (table -> scan-fingerprint shard) a
        replica's catalog exposes; best-effort — an older replica
        without /shards just contributes no shards."""
        try:
            with urllib.request.urlopen(r.url + "/shards",
                                        timeout=2.0) as resp:
                payload = json.loads(resp.read())
            self.ownership.register_shards(payload.get("tables", {}))
        except Exception:
            pass

    def _sync_ownership(self) -> None:
        """Re-derive the shard->owner map from current membership; a
        membership change mints a new epoch which is then broadcast so
        replicas can fence stale routers and rebuild gained shards."""
        minted = self.ownership.observe(
            [r.id for r in self.replicas if r.healthy])
        if minted is not None:
            self._broadcast_epoch(minted)

    def _broadcast_epoch(self, payload: dict) -> None:
        """Push a freshly minted epoch + owner map to every healthy
        replica. Strictly best-effort and called OUTSIDE all locks: a
        replica that misses the broadcast (network blip, injected
        ``serve.ownership`` fault) adopts the epoch lazily from the
        next stamped request and rebuilds on first touch — bytes never
        depend on this push landing."""
        body = json.dumps(payload).encode()
        with trace.span("serve.epoch", epoch=payload.get("epoch")):
            for r in self.replicas:
                if not r.healthy:
                    continue
                try:
                    faults.inject("serve.ownership", self._conf)
                    req = urllib.request.Request(
                        r.url + "/epoch", data=body, method="POST",
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=5.0):
                        pass
                except Exception as e:
                    metrics.record(
                        "fault_recovered", point="serve.ownership",
                        how="lazy_adopt", replica=r.id,
                        error=type(e).__name__)

    def _on_replica_death(self, r: Replica) -> None:
        """A dispatch just proved ``r`` dead: mint a new epoch NOW
        (not at the next throttled probe) so the dead replica's shards
        re-map to survivors and their rebuilds start before the next
        query for those shards arrives."""
        if self.ownership.enabled():
            self._sync_ownership()

    def healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.healthy]

    def status(self) -> List[dict]:
        return [r.snapshot() for r in self.replicas]

    # -- selection ------------------------------------------------------------

    def pick(self, affinity: Optional[str] = None,
             exclude: Sequence[str] = (),
             least_loaded: bool = False,
             prefer: Optional[str] = None) -> Optional[Replica]:
        """Next replica per policy among healthy, non-excluded ones.
        ``prefer`` (the shard OWNER under the ownership map) wins over
        everything when eligible — owner routing is what makes each
        replica's cache authoritative for its shards. ``affinity``
        (the ``X-SparkTpu-Replica`` header a client echoes back) wins
        next — consistent session routing keeps a client's scheduler
        pool state and compile warmth on one backend. ``least_loaded``
        forces the load-based choice regardless of policy: the shed
        path always moves work to the emptiest queue."""
        pool = [r for r in self.healthy() if r.id not in set(exclude)]
        if not pool:
            return None
        # breaker filtering is advisory: when every candidate's breaker
        # refuses (e.g. the whole fleet just flapped), fall back to the
        # unfiltered pool — an attempt against a maybe-bad replica
        # beats refusing a request the fleet could still serve
        admitted = [r for r in pool if r.breaker.admits()]
        if admitted:
            pool = admitted
        if prefer:
            for r in pool:
                if r.id == prefer:
                    return r
        if affinity:
            for r in pool:
                if r.id == affinity:
                    return r
        try:
            policy = str(self._conf.get(CF.SERVE_POLICY))
        except Exception:
            policy = str(CF.SERVE_POLICY.default)
        if least_loaded or policy == "least_queued":
            return min(pool, key=lambda r: (r.load, r.id))
        with self._lock:
            r = pool[self._rr % len(pool)]
            self._rr += 1
        return r

    # -- dispatch -------------------------------------------------------------

    def forward(self, replica: Replica, method: str, path: str,
                body: Optional[bytes],
                headers: Optional[dict] = None
                ) -> Tuple[int, bytes, Dict[str, str]]:
        """One HTTP round trip to a replica. Returns (code, body,
        relay-headers); 4xx/5xx come back as values (HTTPError bodies
        are real payloads here: 429 carries retry_after_s), connection
        failures raise for the re-dispatch loop."""
        req = urllib.request.Request(
            replica.url + path, data=body, method=method,
            headers=headers or {})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                data = resp.read()
                hdr = {k: resp.headers[k] for k in RELAY_HEADERS
                       if resp.headers.get(k)}
                return resp.status, data, hdr
        except urllib.error.HTTPError as e:
            data = e.read()
            hdr = {k: e.headers[k] for k in RELAY_HEADERS
                   if e.headers.get(k)}
            return e.code, data, hdr
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", None)
            if isinstance(reason, _CONN_ERRORS):
                raise reason
            raise

    def dispatch(self, method: str, path: str, body: Optional[bytes],
                 headers: Optional[dict] = None,
                 affinity: Optional[str] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one request: pick -> forward, shedding 429s to the
        least-loaded remaining replica and re-dispatching around dead
        ones (bounded). The return is what the client sees. One
        ``router.dispatch`` span covers the whole routing decision
        (every shed and re-dispatch attempt stays in the caller's
        trace); each attempt is a ``router.forward`` child whose
        context ships to the replica in ``X-SparkTpu-Trace``."""
        with trace.span("router.dispatch", path=path):
            return self._dispatch_traced(method, path, body,
                                         headers, affinity)

    def _dispatch_traced(self, method: str, path: str,
                         body: Optional[bytes],
                         headers: Optional[dict] = None,
                         affinity: Optional[str] = None
                         ) -> Tuple[int, bytes, Dict[str, str]]:
        try:
            retries = max(0, int(
                self._conf.get(CF.SERVE_DISPATCH_RETRIES)))
        except Exception:
            retries = int(CF.SERVE_DISPATCH_RETRIES.default)
        exhausted: set = set()    # saturated (429) this request
        dead: set = set()         # connection-failed this request
        retry_afters: List[float] = []
        last_err: Optional[BaseException] = None
        shed = False
        slo_reject = None  # last typed 503 (InfeasibleDeadline) seen
        # ownership routing: plan the query to the replica OWNING its
        # scans (rendezvous hash over healthy members) so the fleet
        # behaves as one coherent cache instead of N cold ones
        shards: Tuple[str, ...] = ()
        if self.ownership.enabled() and path in ("/sql", "/plan") \
                and body:
            try:
                q = json.loads(body).get("query", "")
                shards = self.ownership.shards_for_sql(q)
            except Exception:
                shards = ()
        for attempt in range(retries + len(self.replicas) + 1):
            deadline.check("serve.dispatch")
            self.probe()
            # owner is re-derived per attempt: a failover two lines
            # down re-maps the shard, and the retry must follow it
            prefer = self.ownership.owner_for(shards) if shards \
                else None
            r = self.pick(affinity=affinity,
                          exclude=exhausted | dead,
                          least_loaded=shed,
                          prefer=prefer if prefer not in
                          (exhausted | dead) else None)
            affinity = None  # only honored for the first choice
            if r is None:
                break
            r.breaker.begin()
            metrics.note_serve("dispatches")
            metrics.record("serve", phase="dispatch", replica=r.id,
                           path=path)
            try:
                with trace.span("router.forward", replica=r.id):
                    faults.inject("serve.dispatch", self._conf)
                    # rewrite (not passthrough) the trace header: the
                    # replica's spans must parent under THIS forward
                    # attempt, so shed/re-dispatch attempts stay
                    # distinguishable in the waterfall
                    hdrs = dict(headers or {})
                    hv = trace.header_value()
                    if hv:
                        hdrs[trace.TRACE_HEADER] = hv
                    if self.ownership.enabled():
                        # per-ATTEMPT stamp: a failover between
                        # attempts must fence the retry at the new
                        # epoch, not the one the request started with
                        hdrs[EPOCH_HEADER] = str(self.ownership.epoch)
                    code, data, hdr = self.forward(
                        r, method, path, body, hdrs)
            except _CONN_ERRORS as e:
                last_err = e
                # a connection-level failure is a fact, not a rate:
                # trip the breaker open IMMEDIATELY, even inside the
                # healthProbeSeconds throttle window
                r.breaker.trip()
                self.brownout.note("failure")
                r.healthy = False
                dead.add(r.id)
                self._on_replica_death(r)
                if len(dead) > retries:
                    break
                metrics.note_serve("replica_failures")
                metrics.record("serve", phase="replica_down",
                               replica=r.id, error=type(e).__name__)
                if not recovery.retry_allowed("serve.dispatch"):
                    break
                metrics.note_serve("redispatches")
                metrics.record("serve", phase="redispatch",
                               replica=r.id)
                continue
            except faults.InjectedFault as e:
                last_err = e
                if e.kind not in ("transient", "hang"):
                    raise  # corrupt/oom: surface typed, no retry
                # injected replica death mid-query: same recovery as a
                # real connection failure
                r.breaker.trip()
                self.brownout.note("failure")
                r.healthy = False
                dead.add(r.id)
                self._on_replica_death(r)
                if len(dead) > retries:
                    break
                metrics.note_serve("replica_failures")
                metrics.record("serve", phase="replica_down",
                               replica=r.id, error=type(e).__name__)
                if not recovery.retry_allowed("serve.dispatch"):
                    break
                metrics.note_serve("redispatches")
                metrics.record("serve", phase="redispatch",
                               replica=r.id)
                continue
            if code == 409 and self.ownership.enabled():
                # typed EPOCH_RETRY: the replica fenced a stale stamp
                # (it learned of a newer epoch than this router holds,
                # e.g. from a concurrent router). The replica ANSWERED
                # — its breaker records the success — and the request
                # re-dispatches with a fresh stamp under the unified
                # retry budget.
                r.breaker.success()
                new_epoch = 0
                try:
                    detail = json.loads(data)
                    new_epoch = int(hdr.get(EPOCH_HEADER)
                                    or detail.get("epoch") or 0)
                except Exception:
                    pass
                self.ownership.bump_to(new_epoch)
                metrics.note_serve("epoch_retries")
                metrics.record("serve", phase="epoch_retry",
                               replica=r.id, epoch=new_epoch)
                if recovery.retry_allowed("serve.dispatch"):
                    continue
                return code, data, hdr  # budget spent: surface typed
            if code == 503:
                # typed SLO reject (InfeasibleDeadline): the replica's
                # latency model predicts the query cannot finish inside
                # its deadline given THAT replica's backlog. The
                # replica ANSWERED (breaker success), the fleet
                # brownout records a shed, and the request is ABSORBED
                # into a re-dispatch toward the least-loaded other
                # replica while the unified retry budget allows — a
                # different queue is a different prediction. Budget
                # spent (or fleet exhausted), the typed 503 SURFACES
                # with the prediction that condemned it.
                r.breaker.success()
                self.brownout.note("shed")
                exhausted.add(r.id)
                shed = True
                slo_reject = (code, data, hdr)
                metrics.note_serve("slo_rejects")
                metrics.record(
                    "serve", phase="slo_reject", replica=r.id,
                    predicted_ms=hdr.get("X-SparkTpu-Predicted-Ms"))
                if recovery.retry_allowed("serve.dispatch"):
                    continue
                return code, data, hdr  # budget spent: surface typed
            if code == 429:
                # admission shedding: this replica's scheduler is
                # full — take the request to the emptiest other queue.
                # the replica ANSWERED, so its breaker records a
                # success; the fleet-wide brownout records the shed
                r.breaker.success()
                self.brownout.note("shed")
                exhausted.add(r.id)
                try:
                    detail = json.loads(data)
                    ra = float(hdr.get("Retry-After")
                               or detail.get("retry_after_s") or 0.0)
                except Exception:
                    ra = 0.0
                retry_afters.append(ra)
                shed = True
                metrics.note_serve("sheds")
                metrics.record("serve", phase="shed", replica=r.id,
                               retry_after_s=ra)
                continue
            r.breaker.success()
            self.brownout.note("ok")
            return code, data, hdr
        if slo_reject is not None:
            # every candidate replica predicted the deadline
            # infeasible (or the budget ran dry re-dispatching): the
            # typed 503 surfaces with its prediction — more
            # actionable than a synthesized 429, and never retried
            # by the client on the same deadline
            metrics.note_serve("rejected")
            metrics.record("serve", phase="slo_reject_surfaced")
            return slo_reject
        if retry_afters:
            # ALL healthy replicas saturated: now (and only now) the
            # client sees the 429; Retry-After is the soonest any
            # replica expects capacity
            ra = min(retry_afters)
            metrics.note_serve("rejected")
            metrics.record("serve", phase="rejected",
                           retry_after_s=ra)
            body_out = json.dumps(
                {"error": "SchedulerQueueFull",
                 "message": "all replicas saturated",
                 "retry_after_s": ra}).encode()
            return 429, body_out, {"Retry-After": f"{ra:g}"}
        if last_err is not None:
            raise NoHealthyReplica(
                f"dispatch failed after replica failures "
                f"(last: {last_err!r})") from last_err
        raise NoHealthyReplica("no healthy replica available")

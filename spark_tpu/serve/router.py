"""Federation router: one HTTP front end over N ConnectServer replicas.

A stdlib ThreadingHTTPServer (the same machinery as the connect server
and the status UI — no new dependency) that speaks the EXACT connect
protocol, so the existing ``connect.server.Client`` talks to a fleet
without changes: POST /sql, /plan, /lint, /cancel/<id>; GET /health,
/tables, /queries. Query traffic routes through
``Federation.dispatch`` (policy pick, 429 shedding, bounded
re-dispatch around dead replicas); the chosen replica's id is echoed
back as ``X-SparkTpu-Replica`` and honored as session affinity when
the client sends it on its next request.

Deployment shapes:

- **in-process fleet** (tests, single-host bench): ``serve_fleet``
  spawns N ConnectServers as threads over ONE session — they share
  the device mesh, the HBM store, and one ResultCache (so the
  single-flight herd guarantee spans replicas).
- **multi-process fleet** (production): start one
  ``connect.serve(session)`` per host/mesh-slice, then
  ``FederationRouter(["http://host1:15002", ...])`` anywhere — the
  router only ever speaks HTTP to replica URLs.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from spark_tpu import conf as CF
from spark_tpu import deadline, metrics, recovery, trace
from spark_tpu.serve.federation import Federation, NoHealthyReplica

#: request headers the router forwards to the chosen replica
#: (X-SparkTpu-Trace is a passthrough fallback — Federation.dispatch
#: rewrites it per forward attempt so replica spans parent correctly;
#: X-SparkTpu-Deadline is an ABSOLUTE timestamp, forwarded verbatim so
#: the replica's scheduler/retry seams observe the client's window)
_FORWARD_HEADERS = ("Content-Type", "X-Spark-Pool", trace.TRACE_HEADER,
                    deadline.DEADLINE_HEADER)


class FederationRouter:
    """HTTP front end; ``replicas`` is any mix of ConnectServer
    objects, URLs, or (id, url) pairs."""

    def __init__(self, replicas: Sequence, conf=None,
                 host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 120.0):
        self.conf = conf if conf is not None else CF.RuntimeConf()
        self.federation = Federation(replicas, self.conf,
                                     timeout=timeout)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str,
                      headers=None) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _dispatch(self, method: str) -> None:
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n) if n else None
                fwd = {k: self.headers[k] for k in _FORWARD_HEADERS
                       if self.headers.get(k)}
                affinity = self.headers.get("X-SparkTpu-Replica")
                # adopt the client's trace so router.dispatch /
                # router.forward spans join it (a fresh root otherwise);
                # bind the client's deadline so the dispatch loop's own
                # re-dispatch attempts stop when the window closes, and
                # a per-request retry budget so re-dispatches draw from
                # the same unified pool as every other layer
                rctx = trace.from_header(
                    self.headers.get(trace.TRACE_HEADER))
                rdl = deadline.from_header(
                    self.headers.get(deadline.DEADLINE_HEADER))
                with trace.attach(rctx), deadline.bind(rdl), \
                        recovery.bind_default_budget(outer.conf):
                    self._dispatch_traced(method, body, fwd, affinity)

            def _dispatch_traced(self, method: str, body, fwd,
                                 affinity) -> None:
                try:
                    code, data, hdr = outer.federation.dispatch(
                        method, self.path, body, headers=fwd,
                        affinity=affinity)
                except deadline.DeadlineExceeded as e:
                    self._send(504, json.dumps(
                        {"error": "DeadlineExceeded",
                         "message": str(e)}).encode(),
                        "application/json")
                    return
                except NoHealthyReplica as e:
                    self._send(503, json.dumps(
                        {"error": "NoHealthyReplica",
                         "message": str(e)}).encode(),
                        "application/json")
                    return
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": type(e).__name__,
                         "message": str(e)}).encode(),
                        "application/json")
                    return
                ctype = "application/vnd.apache.arrow.stream" \
                    if code == 200 and self.path in ("/sql", "/plan") \
                    else "application/json"
                self._send(code, data, ctype, headers=hdr)

            def do_GET(self):
                if self.path == "/health":
                    outer.federation.probe(force=True)
                    reps = outer.federation.status()
                    ok = any(r["healthy"] for r in reps)
                    body = json.dumps({
                        "status": "ok" if ok else "degraded",
                        "router": True,
                        "policy": str(outer.conf.get(CF.SERVE_POLICY)),
                        "replicas": reps,
                        "brownout":
                            outer.federation.brownout.snapshot(),
                        "ownership":
                            outer.federation.ownership.snapshot(),
                        "retry_budget":
                            metrics.retry_budget_stats()}).encode()
                    self._send(200, body, "application/json")
                    return
                if self.path == "/tables" \
                        or self.path.startswith("/queries") \
                        or self.path.startswith("/trace/"):
                    self._dispatch("GET")
                    return
                self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path.startswith("/cancel/"):
                    # query ids are replica-local: broadcast, report
                    # success if any replica owned the id
                    n = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(n) if n else b"{}"
                    cancelled = False
                    for r in outer.federation.healthy():
                        try:
                            code, data, _ = outer.federation.forward(
                                r, "POST", self.path, body,
                                {"Content-Type": "application/json"})
                            if code == 200 and json.loads(data).get(
                                    "cancelled"):
                                cancelled = True
                        except Exception:
                            continue
                    self._send(
                        200 if cancelled else 404,
                        json.dumps({"cancelled": cancelled}).encode(),
                        "application/json")
                    return
                if self.path not in ("/sql", "/plan", "/lint"):
                    self._send(404, b"not found", "text/plain")
                    return
                self._dispatch("POST")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FederationRouter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="spark-tpu-router", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


class Fleet:
    """An in-process serving fleet: N replica ConnectServers (threads
    over one session) plus the router in front. ``stop()`` tears the
    whole thing down in reverse order."""

    def __init__(self, router: FederationRouter, replicas: List):
        self.router = router
        self.replicas = replicas

    @property
    def url(self) -> str:
        return self.router.url

    def stop(self) -> None:
        self.router.stop()
        for r in self.replicas:
            try:
                r.stop()
            except Exception:
                pass


def serve_fleet(session, replicas: Optional[int] = None,
                host: str = "127.0.0.1", port: int = 0,
                timeout: float = 120.0) -> Fleet:
    """Spawn ``replicas`` in-process ConnectServers over ``session``
    (default ``spark.tpu.serve.replicas``) and a FederationRouter in
    front; returns the started Fleet."""
    from spark_tpu.connect.server import ConnectServer
    from spark_tpu.serve.ownership import (SERVE_OWNERSHIP_ENABLED,
                                           session_invalidation_log)
    from spark_tpu.serve.result_cache import ResultCache

    n = int(replicas if replicas is not None
            else session.conf.get(CF.SERVE_REPLICAS))
    n = max(1, n)
    try:
        owned = bool(session.conf.get(SERVE_OWNERSHIP_ENABLED))
    except Exception:
        owned = False
    caches = None
    if owned:
        # ownership mode: each replica keys and owns its OWN result
        # cache (the fleet-coherence contract is the invalidation log
        # + owner routing, not shared memory) — this is the in-process
        # stand-in for the multi-process fleet, where separate caches
        # are physically forced
        log = session_invalidation_log(session)
        caches = [
            ResultCache(session.conf).attach_invalidation_log(log)
            for _ in range(n)]
    servers = [
        ConnectServer(session, host=host, port=0,
                      replica_id=f"r{i}",
                      result_cache=caches[i] if caches else None
                      ).start()
        for i in range(n)]
    router = FederationRouter(servers, conf=session.conf,
                              host=host, port=port,
                              timeout=timeout).start()
    return Fleet(router, servers)

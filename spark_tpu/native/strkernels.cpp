// Native string kernels for dictionary-table evaluation.
//
// Role: the reference's string hot path is native-tier JVM code
// (common/unsafe/.../types/UTF8String.java byte-level contains/match,
// plus Janino-codegen'd LIKE, catalyst
// expressions/regexpExpressions.scala). In this engine every string
// predicate evaluates host-side over a column's *dictionary* (strings
// never materialize on device), so the hot loop is "run one predicate
// over millions of distinct UTF-8 strings". CPython regex/str calls pay
// object overhead per entry; these kernels stream over the Arrow
// buffer (int64 offsets + contiguous UTF-8 bytes) directly.
//
// Semantics mirror expr/compiler.py exactly:
//   LIKE: '%' = any byte sequence, '_' = exactly one CODEPOINT
//         (the Python path uses re '.' with DOTALL), all other
//         pattern chars are literal (no escape syntax).
//
// Built by spark_tpu/native/__init__.py with g++ -O3; loaded via
// ctypes. Pure-Python fallback remains when no compiler is present.

#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// Advance one UTF-8 codepoint starting at s[i]; returns new index.
static inline int64_t utf8_next(const char* s, int64_t i, int64_t len) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    int64_t step = 1;
    if (c >= 0xF0) step = 4;
    else if (c >= 0xE0) step = 3;
    else if (c >= 0xC0) step = 2;
    i += step;
    return i > len ? len : i;
}

// Iterative greedy wildcard match with backtracking on the last '%'.
static bool like_one(const char* s, int64_t slen,
                     const char* p, int64_t plen) {
    int64_t si = 0, pi = 0;
    int64_t star_pi = -1, star_si = 0;
    while (si < slen) {
        if (pi < plen && p[pi] == '%') {
            star_pi = ++pi;
            star_si = si;
        } else if (pi < plen && p[pi] == '_') {
            si = utf8_next(s, si, slen);
            ++pi;
        } else if (pi < plen && p[pi] == s[si]) {
            ++si;
            ++pi;
        } else if (star_pi >= 0) {
            star_si = utf8_next(s, star_si, slen);
            si = star_si;
            pi = star_pi;
        } else {
            return false;
        }
    }
    while (pi < plen && p[pi] == '%') ++pi;
    return pi == plen;
}

// data/offsets: Arrow large_string layout; out: one byte per entry.
void like_table(const char* data, const int64_t* offsets, int64_t n,
                const char* pattern, int64_t plen, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const char* s = data + offsets[i];
        int64_t slen = offsets[i + 1] - offsets[i];
        out[i] = like_one(s, slen, pattern, plen) ? 1 : 0;
    }
}

// op: 0 = contains, 1 = startswith, 2 = endswith
void predicate_table(const char* data, const int64_t* offsets, int64_t n,
                     const char* needle, int64_t nlen, int32_t op,
                     uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const char* s = data + offsets[i];
        int64_t slen = offsets[i + 1] - offsets[i];
        bool r;
        if (nlen > slen) {
            r = false;
        } else if (op == 1) {
            r = std::memcmp(s, needle, nlen) == 0;
        } else if (op == 2) {
            r = std::memcmp(s + slen - nlen, needle, nlen) == 0;
        } else {
            r = nlen == 0 ||
                std::search(s, s + slen, needle, needle + nlen) != s + slen;
        }
        out[i] = r ? 1 : 0;
    }
}

// 64-bit avalanche hash per entry (splitmix64 finalizer over bytes,
// chunked) — partition-routing for host-side string keys; must agree
// with itself across hosts, not with the device hash.
void hash_table64(const char* data, const int64_t* offsets, int64_t n,
                  uint64_t seed, uint64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const unsigned char* s = reinterpret_cast<const unsigned char*>(
            data + offsets[i]);
        int64_t slen = offsets[i + 1] - offsets[i];
        uint64_t h = seed ^ (0x9E3779B97F4A7C15ULL * (uint64_t)slen);
        int64_t j = 0;
        for (; j + 8 <= slen; j += 8) {
            uint64_t k;
            std::memcpy(&k, s + j, 8);
            h ^= k;
            h = (h ^ (h >> 33)) * 0xFF51AFD7ED558CCDULL;
        }
        uint64_t tail = 0;
        for (int64_t t = 0; j + t < slen; ++t)
            tail |= (uint64_t)s[j + t] << (8 * t);
        h ^= tail;
        h = (h ^ (h >> 33)) * 0xC4CEB9FE1A85EC53ULL;
        h ^= h >> 33;
        out[i] = h;
    }
}

}  // extern "C"

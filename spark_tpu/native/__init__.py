"""Native (C++) runtime kernels, loaded via ctypes.

The reference's equivalent tier is JVM-native code: UTF8String.java
byte-twiddling, Janino-compiled predicates, JNI codecs (SURVEY.md §2
[NATIVE-EQ] rows). Here the device compute path is XLA/Pallas; the
*host* runtime tier — dictionary-table string predicates feeding the
trace — is C++ compiled on first use with the toolchain g++ and bound
with ctypes (no pybind11 in this image).

Degradation contract: if no compiler is present or the build fails,
``available()`` is False and every caller keeps its pure-Python path.
The build is cached next to the source and rebuilt when the source
changes (mtime check).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from spark_tpu import locks

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "strkernels.cpp")
_SO = os.path.join(_DIR, "_strkernels.so")

_lock = locks.named_lock("native.registry")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    os.replace(_SO + ".tmp", _SO)
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SPARK_TPU_NATIVE", "1") == "0":
            return None
        fresh = os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        if not fresh and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.like_table.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8)]
        lib.predicate_table.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)]
        lib.hash_table64.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _arrow_buffers(strings: Sequence[str]):
    """Dictionary -> (data bytes, int64 offsets) in Arrow large_string
    layout. pyarrow does the UTF-8 encode in C, so the only Python-level
    loop anywhere on this path is pyarrow's sequence ingestion."""
    import pyarrow as pa

    arr = pa.array(strings, type=pa.large_string())
    bufs = arr.buffers()  # [validity, offsets, data]
    offsets = np.frombuffer(bufs[1], dtype=np.int64,
                            count=len(strings) + 1)
    data = bufs[2]
    return (bytes(data) if data is not None else b""), offsets


def like_table(dictionary: Sequence[str], pattern: str) -> np.ndarray:
    """bool[n]: SQL LIKE over every dictionary entry (semantics match
    expr/compiler._like_to_regex: % any run, _ one codepoint)."""
    lib = _load()
    assert lib is not None
    data, offsets = _arrow_buffers(dictionary)
    n = len(dictionary)
    out = np.zeros(n, dtype=np.uint8)
    pat = pattern.encode("utf-8")
    lib.like_table(
        data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, pat, len(pat),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.astype(bool)


_PRED_OPS = {"contains": 0, "startswith": 1, "endswith": 2}


def predicate_table(dictionary: Sequence[str], op: str,
                    needle: str) -> np.ndarray:
    lib = _load()
    assert lib is not None
    data, offsets = _arrow_buffers(dictionary)
    n = len(dictionary)
    out = np.zeros(n, dtype=np.uint8)
    nd = needle.encode("utf-8")
    lib.predicate_table(
        data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, nd, len(nd), _PRED_OPS[op],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.astype(bool)


def hash_table64(dictionary: Sequence[str], seed: int = 42) -> np.ndarray:
    lib = _load()
    assert lib is not None
    data, offsets = _arrow_buffers(dictionary)
    n = len(dictionary)
    out = np.zeros(n, dtype=np.uint64)
    lib.hash_table64(
        data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, ctypes.c_uint64(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return out

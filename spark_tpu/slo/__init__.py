"""SLO-driven serving: predict -> schedule -> shed (ROADMAP item 5).

Per-plan latency prediction (:mod:`spark_tpu.slo.model`), earliest-
feasible-deadline-first ordering with typed reject-at-admission
(:mod:`spark_tpu.slo.edf`), and the predictive brownout / auto-
concurrency controller (:mod:`spark_tpu.slo.controller`). The whole
subsystem is gated on ``spark.tpu.slo.enabled``; off, the scheduler's
FIFO/FAIR paths are byte-identical to the pre-SLO engine.
"""

from spark_tpu.slo.edf import InfeasibleDeadline, edf_key  # noqa: F401
from spark_tpu.slo.model import (LatencyModel,  # noqa: F401
                                 fingerprint_plan, fingerprint_sql,
                                 model_path_from_conf, plan_input_rows)
from spark_tpu.slo.controller import SloController  # noqa: F401

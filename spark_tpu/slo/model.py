"""Online per-plan latency model for SLO-driven serving.

Predicts how long a query will run BEFORE it runs, from what identical
plans cost in the past. State is one EWMA record per plan fingerprint
(the same ``sql:``-sha1 scheme the plan-history journal uses, so a
replica's prediction state and replay state describe the same keys):

    host_ms / device_ms / queue_ms / transfer_ms / run_ms / rows / n
    (+ cold_ms / cold_n — see below)

``run_ms`` is the directly-measured wall time of the scheduler's run
phase (always available); the component EWMAs come from trace span
events when sampling is on (best-effort — they refine the row-count
scaling but the prediction never depends on them existing).

Cold-compile runs are quarantined: a query whose trace shows the
compile store MISSED (an ``aot_compile``/``aot_failed`` compile event
inside the trace) folds its wall time into a separate ``cold_ms``/
``cold_n`` component and leaves every warm EWMA untouched — one cold
outlier used to multiply the run-time estimate by the compile time
and poison admission for the next N queries. ``predict_run_ms`` stays
warm-only (a replayed/prewarmed plan never pays the compile again);
``cold_ms`` is observability for the snapshot and bench. Journals
written before this field existed load with cold_ms = cold_n = 0.

Prediction scales the device+transfer share by the ratio of the
query's input-row count to the EWMA'd historical row count (scan-stat
driven, clamped to [0.1, 10] so one wild cardinality estimate cannot
produce an absurd prediction), leaving the host share fixed — host
overhead (parse/analyze/dispatch) is roughly size-independent.

Persistence mirrors ``compile.service.PlanHistory``: a JSONL journal
beside the plan-history file where EACH LINE IS A FULL PER-FINGERPRINT
STATE SNAPSHOT, so load is last-line-wins per fingerprint and a
restarted replica predicts from its first query (ISSUE 18 tentpole a).
Compaction past 2x maxEntries rewrites one line per live fingerprint
via tmp + os.replace, same as the history journal.

Locking: everything mutable sits under the registered ``slo.model``
lock (rank 320 — legal to take while holding ``scheduler.cond`` at
300, which is exactly what the submit-path feasibility check does).
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from typing import Any, Dict, Optional

from spark_tpu import locks


# -- fingerprints ------------------------------------------------------------

def fingerprint_sql(sql: str) -> str:
    """Whitespace-normalized SQL fingerprint — IDENTICAL to the scheme
    ``CompileService.note_served`` journals, so the latency model and
    the plan-history/prewarm journal key the same queries the same
    way."""
    return "sql:" + hashlib.sha1(
        " ".join(sql.split()).encode()).hexdigest()[:24]


def fingerprint_plan(plan) -> Optional[str]:
    """Structural plan fingerprint for non-SQL submissions; stable
    across restarts (node_string, not id()). Type-name as last resort;
    None when even that fails — no fingerprint means no prediction,
    which means FIFO-equivalent behaviour for that query."""
    try:
        return "plan:" + hashlib.sha1(
            plan.node_string().encode()).hexdigest()[:24]
    except Exception:
        try:
            return "plan:" + hashlib.sha1(
                type(plan).__name__.encode()).hexdigest()[:24]
        except Exception:
            return None


def plan_input_rows(plan) -> Optional[float]:
    """Total input cardinality: sum of scan-stat row estimates over the
    plan's leaves (exact for Parquet metadata / in-memory batches).
    None when the plan exposes no usable estimates."""
    try:
        from spark_tpu.plan.join_reorder import estimate_rows

        total, found = 0.0, False
        stack = [plan]
        while stack:
            node = stack.pop()
            kids = list(node.children())
            if not kids:
                total += float(estimate_rows(node))
                found = True
            else:
                stack.extend(kids)
        return total if found else None
    except Exception:
        return None


# -- the model ---------------------------------------------------------------

class LatencyModel:
    """EWMA-per-fingerprint latency estimator with JSONL persistence.

    All public methods are safe to call from any thread and never
    raise out (prediction is advisory: a broken journal or a full disk
    must degrade to in-memory / cold-start, never fail a query).
    """

    def __init__(self, path: str = "", *, alpha: float = 0.3,
                 max_entries: int = 512):
        self.path = str(path or "")
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.max_entries = max(8, int(max_entries))
        self._lock = locks.named_lock("slo.model")
        #: fp -> {host_ms, device_ms, queue_ms, transfer_ms, run_ms,
        #:        rows, n, cold_ms, cold_n} — OrderedDict as LRU
        #: (move_to_end on touch)
        self._state: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
        self._appends = 0
        self._load()

    # -- persistence ---------------------------------------------------------

    def _load(self) -> None:
        if not self.path:
            return
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except FileNotFoundError:
            return
        except Exception:
            return
        loaded: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                fp = rec.pop("fp")
                loaded.pop(fp, None)  # last-line-wins, refresh LRU slot
                cur = {k: float(rec[k]) for k in
                       ("host_ms", "device_ms", "queue_ms",
                        "transfer_ms", "run_ms", "rows", "n")}
                # cold component post-dates the journal format: old
                # lines load as never-cold rather than being dropped
                cur["cold_ms"] = float(rec.get("cold_ms", 0.0))
                cur["cold_n"] = float(rec.get("cold_n", 0.0))
                loaded[fp] = cur
            except Exception:
                continue  # tolerate torn/garbage lines
        while len(loaded) > self.max_entries:
            loaded.popitem(last=False)
        with self._lock:
            self._state = loaded
            self._appends = 0
        if loaded:
            try:
                from spark_tpu import metrics

                metrics.note_slo("loads", len(loaded))
            except Exception:
                pass

    def _persist_locked(self, fp: str) -> None:
        """Append one full state snapshot for ``fp``; compact the
        journal once it holds 2x maxEntries lines. Runs under the
        model lock so the journal and the in-memory state cannot
        diverge (same trade as PlanHistory.note)."""
        if not self.path:
            return
        rec = dict(self._state[fp])
        rec["fp"] = fp
        line = json.dumps(rec, sort_keys=True) + "\n"
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line)
        self._appends += 1
        if self._appends >= 2 * self.max_entries:
            tmp = f"{self.path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                for k, v in self._state.items():
                    out = dict(v)
                    out["fp"] = k
                    f.write(json.dumps(out, sort_keys=True) + "\n")
            os.replace(tmp, self.path)
            self._appends = 0

    # -- observe / predict ---------------------------------------------------

    def observe(self, fp: str, *, run_ms: float, queue_ms: float = 0.0,
                rows: Optional[float] = None, device_ms: float = 0.0,
                transfer_ms: float = 0.0, cold: bool = False) -> None:
        """Fold one completed query into the fingerprint's EWMAs and
        journal the updated snapshot. ``cold=True`` (the trace showed a
        compile-store miss) updates ONLY the quarantined cold
        component — the warm run-time estimate never sees the compile
        outlier. Never raises."""
        if not fp or run_ms is None or run_ms < 0:
            return
        host_ms = max(0.0, float(run_ms) - float(device_ms)
                      - float(transfer_ms))
        try:
            with self._lock:
                cur = self._state.pop(fp, None)
                a = self.alpha
                if cold:
                    if cur is None:
                        cur = {"host_ms": 0.0, "device_ms": 0.0,
                               "queue_ms": 0.0, "transfer_ms": 0.0,
                               "run_ms": 0.0,
                               "rows": float(rows) if rows else 0.0,
                               "n": 0.0, "cold_ms": float(run_ms),
                               "cold_n": 1.0}
                    elif cur.get("cold_n", 0.0) <= 0:
                        cur["cold_ms"] = float(run_ms)
                        cur["cold_n"] = 1.0
                    else:
                        cur["cold_ms"] = ((1 - a) * cur["cold_ms"]
                                          + a * float(run_ms))
                        cur["cold_n"] = cur.get("cold_n", 0.0) + 1.0
                elif cur is None:
                    cur = {"host_ms": host_ms,
                           "device_ms": float(device_ms),
                           "queue_ms": float(queue_ms),
                           "transfer_ms": float(transfer_ms),
                           "run_ms": float(run_ms),
                           "rows": float(rows) if rows else 0.0,
                           "n": 1.0, "cold_ms": 0.0, "cold_n": 0.0}
                elif cur.get("n", 0.0) <= 0:
                    # first WARM observation of an entry a cold run
                    # created: seed directly — folding against the
                    # zeroed placeholders would bias the estimate low
                    cur.update({"host_ms": host_ms,
                                "device_ms": float(device_ms),
                                "queue_ms": float(queue_ms),
                                "transfer_ms": float(transfer_ms),
                                "run_ms": float(run_ms)})
                    if rows:
                        cur["rows"] = float(rows)
                    cur["n"] = 1.0
                else:
                    for key, obs in (("host_ms", host_ms),
                                     ("device_ms", float(device_ms)),
                                     ("queue_ms", float(queue_ms)),
                                     ("transfer_ms", float(transfer_ms)),
                                     ("run_ms", float(run_ms))):
                        cur[key] = (1 - a) * cur[key] + a * obs
                    if rows:
                        prev = cur.get("rows", 0.0)
                        cur["rows"] = (float(rows) if prev <= 0
                                       else (1 - a) * prev + a * float(rows))
                    cur["n"] = cur.get("n", 0.0) + 1.0
                self._state[fp] = cur  # re-insert at LRU tail
                while len(self._state) > self.max_entries:
                    self._state.popitem(last=False)
                self._persist_locked(fp)
            try:
                from spark_tpu import metrics

                metrics.note_slo("cold_observations" if cold
                                 else "observations")
            except Exception:
                pass
        except Exception:
            pass  # advisory: journal/disk failure must not fail queries

    def predict_run_ms(self, fp: Optional[str],
                       rows: Optional[float] = None) -> Optional[float]:
        """Predicted run time for one execution of ``fp``; None when
        the model has never seen the fingerprint (callers treat
        unpredictable as always-feasible / FIFO-equivalent)."""
        if not fp:
            return None
        with self._lock:
            cur = self._state.get(fp)
            if cur is None or cur.get("n", 0.0) < 1.0:
                # cold-only entries predict nothing: the only signal is
                # compile time, which a warm run never pays again
                return None
            self._state.move_to_end(fp)
            hist_rows = cur.get("rows", 0.0)
            scaled = cur["device_ms"] + cur["transfer_ms"]
            # size-independent host share + row-scaled device share;
            # when components were never traced, scale run_ms whole
            if scaled <= 0.0:
                base, fixed = cur["run_ms"], 0.0
            else:
                base, fixed = scaled, cur["host_ms"]
            ratio = 1.0
            if rows and hist_rows > 0:
                ratio = min(10.0, max(0.1, float(rows) / hist_rows))
            return fixed + base * ratio

    def predict_queue_ms(self, fp: Optional[str]) -> Optional[float]:
        """Historical queue-wait EWMA (controller fallback when it has
        no live backlog estimate)."""
        if not fp:
            return None
        with self._lock:
            cur = self._state.get(fp)
            return None if cur is None else cur["queue_ms"]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._state),
                    "path": self.path,
                    "alpha": self.alpha,
                    "observations": sum(v.get("n", 0.0)
                                        for v in self._state.values()),
                    "cold_observations": sum(
                        v.get("cold_n", 0.0)
                        for v in self._state.values())}


def model_path_from_conf(conf) -> str:
    """Journal location: explicit ``spark.tpu.slo.model.path``, else
    beside the plan-history journal under the compile store root, else
    "" (in-memory only — cold-start every restart)."""
    from spark_tpu import conf as CF

    try:
        explicit = str(conf.get(CF.SLO_MODEL_PATH) or "")
        if explicit:
            return explicit
        root = str(conf.get(CF.COMPILE_STORE_DIR) or "")
        return os.path.join(root, "slo_model.jsonl") if root else ""
    except Exception:
        return ""

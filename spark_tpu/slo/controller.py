"""SLO controller: predictive brownout + auto-sized concurrency.

One instance per :class:`~spark_tpu.scheduler.scheduler.QueryScheduler`
(constructed only when ``spark.tpu.slo.enabled`` is true — when it is
None the scheduler's FIFO/FAIR paths are byte-identical to before).
Three responsibilities:

1. **Prediction seam** — wraps the :class:`LatencyModel` behind the
   ``slo.predict`` fault point; a failed/injected prediction degrades
   to "no prediction" (FIFO-equivalent for that query), never an error.

2. **Reject-at-admission** — :meth:`admission_check_locked` (called by
   ``submit`` under ``scheduler.cond`` BEFORE the ticket exists)
   compares predicted completion against the caller's deadline and
   raises the typed :class:`InfeasibleDeadline` when the query is
   doomed. The decision gate itself sits behind the ``slo.reject``
   fault point and FAILS OPEN: an injected fault disables rejection
   for that submit, it never rejects spuriously.

3. **Predictive brownout + auto-concurrency** — a sliding window of
   predicted completion times drives brownout entry/exit against the
   configured p99 target *before* queries are observably late (vs the
   serve-layer BrownoutController, which reacts to observed
   failures), and EWMA'd queue/run ratios shrink or grow the
   scheduler's effective concurrency between the configured floor and
   ``spark.tpu.scheduler.maxConcurrency``.

Lock order: ``slo.controller`` (rank 325) and ``slo.model`` (320) are
both legal under ``scheduler.cond`` (300); the controller NEVER calls
into the model while holding its own lock, so 325->320 never nests.
Fault injection happens OUTSIDE ``scheduler.cond`` (in the predict /
reject-gate phases) so a hang-kind injection can never stall the
scheduler with the condition lock held.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_tpu import conf as CF
from spark_tpu import faults, locks, metrics, trace
from spark_tpu.slo import edf
from spark_tpu.slo.model import LatencyModel


def _p99(values: List[float]) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999999))
    return s[idx]


class SloController:
    """Predict -> schedule -> shed loop state for one scheduler."""

    def __init__(self, conf, model: LatencyModel, max_concurrency: int):
        self._conf = conf
        self.model = model
        self._lock = locks.named_lock("slo.controller")
        self._max = max(1, int(max_concurrency))
        self._effective = self._max
        self._target_ms = float(conf.get(CF.SLO_TARGET_P99_MS))
        self._margin = float(conf.get(CF.SLO_REJECT_MARGIN))
        self._reject = bool(conf.get(CF.SLO_REJECT_ENABLED))
        self._window_s = max(1.0, float(conf.get(CF.SLO_WINDOW_SECONDS)))
        self._min_preds = max(1, int(conf.get(CF.SLO_MIN_PREDICTIONS)))
        self._exit_ratio = min(1.0, max(0.1,
                               float(conf.get(CF.SLO_EXIT_RATIO))))
        self._autosize = bool(conf.get(CF.SLO_AUTOSIZE_ENABLED))
        self._auto_min = max(1, int(conf.get(CF.SLO_AUTOSIZE_MIN)))
        #: (wall-time, predicted completion ms) per admitted submit
        self._window: "deque[tuple]" = deque(maxlen=4096)
        self._level = 0
        self._queue_ewma: Optional[float] = None
        self._run_ewma: Optional[float] = None
        self._finished = 0
        self._last_resize = time.time()

    # -- prediction seam (outside scheduler.cond) ----------------------------

    def predict_run_ms(self, fp: Optional[str],
                       rows: Optional[float] = None) -> Optional[float]:
        """Predicted run time, or None (unknown fingerprint, model
        failure, or an injected ``slo.predict`` fault — all absorbed:
        no prediction just means FIFO-equivalent treatment)."""
        try:
            faults.inject("slo.predict", self._conf)
            pred = self.model.predict_run_ms(fp, rows)
            if pred is not None:
                metrics.note_slo("predictions")
            return pred
        except faults.InjectedFault:
            return None
        except Exception:
            return None

    def reject_gate(self) -> bool:
        """Whether reject-at-admission applies to this submit. The
        ``slo.reject`` fault point fails OPEN (gate off) so injection
        can only admit more, never shed spuriously."""
        if not self._reject:
            return False
        try:
            faults.inject("slo.reject", self._conf)
            return True
        except faults.InjectedFault:
            return False
        except Exception:
            return False

    # -- admission (under scheduler.cond; pure computation) ------------------

    def admission_check_locked(self, *, deadline: Optional[float],
                               pred_run_ms: Optional[float],
                               pending_ms: List[float],
                               inflight_ms: List[float],
                               reject: bool) -> Optional[float]:
        """Feasibility check for one submit. Returns the predicted
        completion (queue + run, margin applied) or None when the
        model has nothing to say; raises
        :class:`~spark_tpu.slo.edf.InfeasibleDeadline` when ``reject``
        is on, a deadline is set, and the prediction says it will be
        missed. Pure computation — safe under ``scheduler.cond``."""
        if pred_run_ms is None:
            return None
        with trace.span("slo.admit", deadline=bool(deadline)):
            default_ms = self._run_ewma or pred_run_ms
            queue_ms = edf.backlog_ms(pending_ms, inflight_ms,
                                      self.effective_concurrency(),
                                      default_ms)
            ok, predicted_ms = edf.feasible(
                deadline if reject else None,
                queue_ms, pred_run_ms, self._margin)
            self._note_prediction(predicted_ms)
            if not ok:
                metrics.note_slo("rejects")
                metrics.record("slo", phase="reject",
                               predicted_ms=round(predicted_ms, 3))
                raise edf.InfeasibleDeadline(
                    predicted_ms, deadline,
                    queue_ms=queue_ms, run_ms=pred_run_ms)
            return predicted_ms

    def _note_prediction(self, predicted_ms: float) -> None:
        now = time.time()
        with self._lock:
            self._window.append((now, predicted_ms))
            self._update_brownout_locked(now)

    def _update_brownout_locked(self, now: float) -> None:
        """Predictive brownout: enter when the p99 of recent PREDICTED
        completions exceeds the target, exit (with hysteresis) when it
        falls back under exitRatio x target."""
        if self._target_ms <= 0:
            return
        while self._window and self._window[0][0] < now - self._window_s:
            self._window.popleft()
        # min_preds is noise protection for ENTERING only: a handful
        # of slow predictions must not flap the ladder. The exit check
        # runs on whatever recent evidence exists — requiring a full
        # window to exit would wedge a browned-out replica at level 1
        # forever once the overload (and thus the prediction stream)
        # that caused it dries up to a trickle.
        if not self._window \
                or (self._level == 0
                    and len(self._window) < self._min_preds):
            return
        p99 = _p99([ms for _, ms in self._window])
        if self._level == 0 and p99 > self._target_ms:
            self._level = 1
            metrics.set_brownout(1)
            metrics.note_slo("brownout_enters")
            metrics.record("slo", phase="brownout",
                           level=1, predicted_p99_ms=round(p99, 3))
        elif self._level > 0 and p99 <= self._exit_ratio * self._target_ms:
            self._level = 0
            metrics.set_brownout(0)
            metrics.note_slo("brownout_exits")
            metrics.record("slo", phase="brownout",
                           level=0, predicted_p99_ms=round(p99, 3))

    # -- observation (scheduler worker thread, no scheduler lock held) -------

    def note_finished(self, ticket) -> None:
        """Fold a FINISHED ticket back into the model and the
        auto-sizing EWMAs. Best-effort observability — never raises."""
        try:
            fp = getattr(ticket, "slo_fp", None)
            if not fp or ticket.started_t is None \
                    or ticket.finished_t is None:
                return
            run_ms = (ticket.finished_t - ticket.started_t) * 1e3
            queue_ms = ticket.queue_wait_ms() or 0.0
            device_ms, transfer_ms, cold = self._span_components(ticket)
            with trace.span("slo.observe", fp=fp, cold=cold):
                self.model.observe(
                    fp, run_ms=run_ms, queue_ms=queue_ms,
                    rows=getattr(ticket, "slo_rows", None),
                    device_ms=device_ms, transfer_ms=transfer_ms,
                    cold=cold)
            self._note_ratios(queue_ms, run_ms)
        except Exception:
            pass

    @staticmethod
    def _span_components(ticket):
        """(device_ms, transfer_ms, cold) from the query's trace
        events — components present only when trace sampling recorded
        them, (0, 0) otherwise. Span events carry their wall time as
        ``ms`` (trace.span); ``duration_ms`` is kept as a legacy
        fallback for externally-fed event logs. ``cold`` flags a
        compile-store miss inside this trace (an ``aot_compile`` ran,
        or failed trying): the run's wall time is dominated by
        compilation, and the model quarantines it in the cold
        component instead of folding it into the warm run-time
        EWMA."""
        device_ms = transfer_ms = 0.0
        cold = False
        try:
            ctx = getattr(ticket, "_trace_ctx", None)
            if ctx and getattr(ctx, "trace_id", None):
                for ev in metrics.query_events(ctx.trace_id):
                    if (ev.get("kind") == "compile"
                            and ev.get("phase") in ("aot_compile",
                                                    "aot_failed")):
                        cold = True
                    name = ev.get("span") or ev.get("name") or ""
                    dur = float(ev.get("duration_ms")
                                or ev.get("ms") or 0.0)
                    if name == "stage.device":
                        device_ms += dur
                    elif name == "pipeline.transfer":
                        transfer_ms += dur
        except Exception:
            pass
        return device_ms, transfer_ms, cold

    def _note_ratios(self, queue_ms: float, run_ms: float) -> None:
        """Auto-size effective concurrency from the queue/run ratio:
        queueing dominating run time means too many queries contend
        for the devices (shrink); near-empty queues mean headroom
        (grow back toward the configured maximum)."""
        a = 0.3
        with self._lock:
            self._queue_ewma = queue_ms if self._queue_ewma is None \
                else (1 - a) * self._queue_ewma + a * queue_ms
            self._run_ewma = run_ms if self._run_ewma is None \
                else (1 - a) * self._run_ewma + a * run_ms
            self._finished += 1
            if not self._autosize or self._run_ewma <= 1e-6 \
                    or self._finished < self._min_preds:
                return
            now = time.time()
            if now - self._last_resize < max(1.0, self._window_s / 10.0):
                return
            ratio = self._queue_ewma / self._run_ewma
            new = self._effective
            if ratio > 2.0:
                new = max(self._auto_min, self._effective - 1)
            elif ratio < 0.5:
                new = min(self._max, self._effective + 1)
            if new != self._effective:
                self._effective = new
                self._last_resize = now
                metrics.note_slo("resizes")
                metrics.set_gauge("slo.effective_concurrency", new)

    # -- introspection -------------------------------------------------------

    def effective_concurrency(self) -> int:
        with self._lock:
            return self._effective

    def brownout_level(self) -> int:
        with self._lock:
            return self._level

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            preds = [ms for _, ms in self._window]
            snap = {
                "target_p99_ms": self._target_ms,
                "reject_enabled": self._reject,
                "margin": self._margin,
                "effective_concurrency": self._effective,
                "max_concurrency": self._max,
                "brownout_level": self._level,
                "window_predictions": len(preds),
                "predicted_p99_ms": round(_p99(preds), 3),
                "queue_ewma_ms": round(self._queue_ewma or 0.0, 3),
                "run_ewma_ms": round(self._run_ewma or 0.0, 3),
            }
        snap["model"] = self.model.snapshot()
        return snap

"""Earliest-feasible-deadline-first ordering and reject-at-admission.

Pure policy helpers for the SLO scheduler path (no locks, no IO, no
engine imports — scheduler/scheduler.py calls these under its own
condition lock). Two ideas:

- **EDF ordering** (:func:`edf_key`): among queued tickets, the one
  whose absolute deadline is earliest runs first; deadline-less tickets
  order FIFO *after* every deadlined one (a query that told us when it
  must finish outranks one that did not). Ties break on submit order,
  so the ordering is a total order and A/B-deterministic.

- **Feasibility** (:func:`feasible`): a submit whose predicted
  completion — queue backlog estimate plus its own predicted run time,
  scaled by ``spark.tpu.slo.rejectMargin`` — already exceeds its
  deadline is REJECTED at admission with the typed
  :class:`InfeasibleDeadline` instead of enqueued. Burning queue slots
  and device time on a query that is doomed to miss only makes every
  other query later; shedding it immediately is the whole point of the
  predict->schedule->shed loop (ROADMAP item 5).

Classification contract: like ``deadline.DeadlineExceeded``,
:class:`InfeasibleDeadline` is typed and terminal — never retried by
any layer on the same deadline (the prediction does not improve by
asking again), though the federation router may re-dispatch it to a
LESS LOADED replica under the unified retry budget (a different queue
is a different prediction).
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

#: sorts after every real deadline, before nothing
_NO_DEADLINE = float("inf")


class InfeasibleDeadline(RuntimeError):
    """Typed reject-at-admission: the predicted completion time already
    exceeds the caller's deadline, so the query is shed BEFORE it costs
    a queue slot or any device time. Carries the prediction so clients
    (and the 503 payload) can say how infeasible, not just that."""

    def __init__(self, predicted_ms: float, deadline: float,
                 now: Optional[float] = None,
                 queue_ms: float = 0.0, run_ms: float = 0.0):
        now = time.time() if now is None else now
        self.predicted_ms = float(predicted_ms)
        self.deadline = float(deadline)
        self.queue_ms = float(queue_ms)
        self.run_ms = float(run_ms)
        self.slack_ms = (self.deadline - now) * 1e3
        super().__init__(
            f"INFEASIBLE_DEADLINE: predicted completion in "
            f"{self.predicted_ms:.1f}ms (queue {self.queue_ms:.1f}ms + "
            f"run {self.run_ms:.1f}ms) exceeds the deadline "
            f"{self.slack_ms:.1f}ms away — rejected at admission")


def edf_key(ticket) -> Tuple[float, int]:
    """Total order for EDF: (absolute deadline, submit id); tickets
    without a deadline sort last, FIFO among themselves."""
    dl = getattr(ticket, "deadline", None)
    return (dl if dl is not None else _NO_DEADLINE, ticket.id)


def pick_edf(tickets) -> Optional[object]:
    """Earliest-feasible-deadline-first choice among ``tickets``
    (queued or gate-waiting). Returns None on an empty collection."""
    best = None
    best_key = None
    for t in tickets:
        k = edf_key(t)
        if best_key is None or k < best_key:
            best, best_key = t, k
    return best


def backlog_ms(pending_ms: List[float], inflight_ms: List[float],
               workers: int, default_ms: float) -> float:
    """Queue-wait estimate for a NEW submit: predicted run time of
    everything already queued plus in flight, divided by the effective
    worker count (the M/M/c shortcut — crude, but it only has to be
    right about ORDER of magnitude to shed doomed queries early).
    ``default_ms`` substitutes for tickets the model cannot predict."""
    w = max(1, int(workers))
    total = 0.0
    for ms in pending_ms:
        total += ms if ms and ms > 0 else default_ms
    for ms in inflight_ms:
        # in-flight queries are partway done; count half on average
        total += (ms if ms and ms > 0 else default_ms) / 2.0
    return total / w


def feasible(deadline: Optional[float], queue_ms: float, run_ms: float,
             margin: float = 1.0,
             now: Optional[float] = None) -> Tuple[bool, float]:
    """(is_feasible, predicted_total_ms) for a submit with ``deadline``
    (absolute epoch seconds, None = always feasible) given the queue
    backlog estimate and the query's own predicted run time."""
    predicted_ms = (max(0.0, queue_ms) + max(0.0, run_ms)) \
        * max(0.0, float(margin))
    if deadline is None:
        return True, predicted_ms
    now = time.time() if now is None else now
    slack_ms = (float(deadline) - now) * 1e3
    return predicted_ms <= slack_ms, predicted_ms

"""Arrow <-> device-batch interchange.

The TPU analogue of the reference's Arrow surface
(reference: sql/core/.../execution/arrow/ArrowConverters.scala:188,313 and
ArrowColumnVector.java): Arrow record batches are the ingestion format
from Parquet/CSV readers and external clients, and the hand-off point to
device memory.

Strings are dictionary-encoded with pyarrow on the host (the analogue of
the reference leaning on UTF8String everywhere is *not* wanted on TPU:
all device-side string ops happen on int32 codes, and per-dictionary
lookup tables are built host-side at trace time).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from spark_tpu import types as T
from spark_tpu.columnar.batch import Batch, from_numpy
from spark_tpu.types import Field, Schema


def arrow_type_to_dtype(at: pa.DataType) -> T.DataType:
    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_int8(at):
        return T.INT8
    if pa.types.is_int16(at):
        return T.INT16
    if pa.types.is_int32(at):
        return T.INT32
    if pa.types.is_int64(at):
        return T.INT64
    if pa.types.is_float32(at):
        return T.FLOAT32
    if pa.types.is_float64(at):
        return T.FLOAT64
    if pa.types.is_decimal(at):
        if at.precision > T.DecimalType.MAX_PRECISION:
            # the device representation is scaled int64 (18 digits); a
            # wider column's high limb carries real data the ingest
            # would silently drop — refuse loudly instead
            raise NotImplementedError(
                f"decimal({at.precision},{at.scale}) exceeds the "
                f"engine's {T.DecimalType.MAX_PRECISION}-digit "
                f"(int64) cap")
        return T.DecimalType(at.precision, at.scale)
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.STRING
    if pa.types.is_date32(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_dictionary(at):
        return arrow_type_to_dtype(at.value_type)
    raise TypeError(f"unsupported arrow type: {at}")


def dtype_to_arrow_type(dt: T.DataType) -> pa.DataType:
    if isinstance(dt, T.BooleanType):
        return pa.bool_()
    if isinstance(dt, T.Int8Type):
        return pa.int8()
    if isinstance(dt, T.Int16Type):
        return pa.int16()
    if isinstance(dt, T.Int32Type):
        return pa.int32()
    if isinstance(dt, T.Int64Type):
        return pa.int64()
    if isinstance(dt, T.Float32Type):
        return pa.float32()
    if isinstance(dt, T.Float64Type):
        return pa.float64()
    if isinstance(dt, T.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, T.StringType):
        return pa.string()
    if isinstance(dt, T.DateType):
        return pa.date32()
    if isinstance(dt, T.TimestampType):
        return pa.timestamp("us")
    if isinstance(dt, T.ArrayType):
        return pa.list_(dtype_to_arrow_type(dt.element))
    raise TypeError(f"unsupported dtype: {dt}")


def decimal_from_unscaled(unscaled: np.ndarray,
                          typ: pa.DataType,
                          validity: Optional[np.ndarray] = None) -> pa.Array:
    """Exact decimal128 column from unscaled int64 values via the raw
    16-byte little-endian buffer — a per-value python-Decimal loop is
    ~100x slower at lineitem scale. Values must fit int64 (the engine's
    p<=18 cap guarantees it)."""
    unscaled = unscaled.astype(np.int64)
    buf = np.empty((len(unscaled), 2), dtype=np.int64)
    buf[:, 0] = unscaled
    buf[:, 1] = np.where(unscaled < 0, -1, 0)  # sign extension limb
    vbuf = None
    if validity is not None and not validity.all():
        vbuf = pa.py_buffer(np.packbits(
            validity.astype(np.uint8), bitorder="little").tobytes())
    return pa.Array.from_buffers(
        typ, len(unscaled), [vbuf, pa.py_buffer(buf.tobytes())],
        null_count=-1 if vbuf is not None else 0)


def _column_to_numpy(
    arr: pa.ChunkedArray, dtype: T.DataType
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[Tuple[str, ...]]]:
    """Convert one Arrow column to (values, validity, dictionary)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()

    validity: Optional[np.ndarray] = None
    if arr.null_count > 0:
        validity = pc.is_valid(arr).to_numpy(zero_copy_only=False)

    dictionary: Optional[Tuple[str, ...]] = None
    if isinstance(dtype, T.StringType):
        if not pa.types.is_dictionary(arr.type):
            arr = pc.dictionary_encode(arr)
        # pre-encoded dictionaries may contain a null entry; rows mapping
        # to it are nulls (validity already covers them) — use "" so the
        # sort below stays total
        raw_dict = [s if s is not None else ""
                    for s in arr.dictionary.to_pylist()]
        codes = pc.fill_null(arr.indices, 0).to_numpy(zero_copy_only=False)
        values = np.ascontiguousarray(codes, dtype=np.int32)
        # Normalize to a SORTED, DEDUPLICATED dictionary so code order ==
        # lexicographic order AND code equality == value equality (the
        # engine's GROUP BY/DISTINCT/join invariant): string min/max/
        # compare/sort become plain int32 ops on device. Pre-encoded
        # inputs (dictionary parquet) may legally carry duplicate values
        # — equal strings must collapse to ONE code.
        uniq = sorted(set(raw_dict))
        pos = {s: i for i, s in enumerate(uniq)}
        remap = np.array([pos[s] for s in raw_dict], dtype=np.int32)
        dictionary = tuple(uniq)
        if len(remap):
            values = remap[values]
        if validity is not None:
            values = np.where(validity, values, 0).astype(np.int32)
        return values.astype(np.int32, copy=False), validity, dictionary

    if isinstance(dtype, T.DecimalType):
        if pa.types.is_decimal(arr.type):
            # exact unscaled int64 straight from the decimal128 buffer:
            # low limb of each 16-byte little-endian value (values fit
            # int64 at the engine's p<=18 cap, so the high limb is pure
            # sign extension)
            raw = np.frombuffer(arr.buffers()[1], dtype=np.int64)
            lo = arr.offset * 2
            values = raw[lo:lo + 2 * len(arr):2].copy()
            delta = dtype.scale - arr.type.scale
            if delta > 0:
                limit = (10 ** 18 - 1) // (10 ** delta)
                if len(values) and np.abs(values).max() > limit:
                    raise NotImplementedError(
                        f"rescaling decimal({arr.type.precision},"
                        f"{arr.type.scale}) storage to scale "
                        f"{dtype.scale} overflows the engine's 18-digit "
                        "int64 cap — narrow the schema scale or cast to "
                        "double")
                values = values * (10 ** delta)
            elif delta < 0:
                # HALF_UP, matching every other ->decimal path
                factor = 10 ** (-delta)
                values = (np.sign(values)
                          * ((np.abs(values) + factor // 2) // factor))
            if validity is not None:
                values = np.where(validity, values, 0)
            return values, validity, None
        # non-decimal storage (e.g. float parquet read with a decimal
        # schema): scale + round through float64, HALF_UP like every
        # other float->decimal path (np.rint would be HALF_EVEN)
        f = np.nan_to_num(
            arr.cast(pa.float64()).to_numpy(zero_copy_only=False))
        scaled = f * (10 ** dtype.scale)
        values = (np.sign(scaled)
                  * np.floor(np.abs(scaled) + 0.5)).astype(np.int64)
        return values, validity, None
    if isinstance(dtype, T.DateType):
        arr = arr.cast(pa.int32())
    if isinstance(dtype, T.TimestampType):
        arr = arr.cast(pa.timestamp("us")).cast(pa.int64())
    if isinstance(dtype, T.BooleanType):
        values = arr.to_numpy(zero_copy_only=False).astype(np.bool_)
    else:
        values = arr.to_numpy(zero_copy_only=False)
    values = np.asarray(values)
    if validity is not None:
        # Arrow may hand us an object/NaN-filled array for nullable cols.
        fill = np.zeros((), dtype=dtype.np_dtype)
        values = np.where(validity, values, fill)
    return values.astype(dtype.np_dtype, copy=False), validity, dictionary


def _list_to_padded(col: pa.ChunkedArray):
    """Arrow list column -> (values 2D padded, lengths, validity,
    element dictionary, element dtype). The PADDED layout is the
    ArrayType contract (types.ArrayType)."""
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    validity = None
    if col.null_count > 0:
        validity = pc.is_valid(col).to_numpy(zero_copy_only=False)
    # ABSOLUTE offsets into col.values: flatten() would DROP null rows'
    # value ranges (legal Arrow) and silently misalign every later row
    offsets = col.offsets.to_numpy(zero_copy_only=False).astype(np.int64)
    lengths = np.diff(offsets).astype(np.int32)
    if validity is not None:
        lengths = np.where(validity, lengths, 0).astype(np.int32)
    el_dtype = arrow_type_to_dtype(col.type.value_type)
    fvals, _, dictionary = _column_to_numpy(
        pa.chunked_array([col.values]), el_dtype)
    n = len(col)
    max_len = max(1, int(lengths.max()) if n else 1)
    vals = np.zeros((n, max_len), dtype=fvals.dtype)
    if len(fvals):
        # row-major gather of each row's slice (vectorized by mask)
        jj = np.arange(max_len)[None, :]
        take = offsets[:-1, None] + jj
        alive = jj < lengths[:, None]
        vals[alive] = fvals[np.clip(take, 0, len(fvals) - 1)][alive]
    return vals, lengths, validity, dictionary, el_dtype


def arrow_to_numpy(table: pa.Table):
    """Arrow table -> (Schema, host arrays, validities): the host half
    of ``from_arrow``, exposed separately so the out-of-HBM pipeline
    producer can stage arrow decode and device upload as independently
    timed stages (physical/pipeline.py). List columns become padded-2D
    ArrayType columns plus a hidden '#len' companion; struct columns
    FLATTEN into dotted children (reference peers: UnsafeArrayData /
    nested schema pruning)."""
    fields = []
    arrays = []
    validities = []

    def add(name, col, parent_valid=None):
        if pa.types.is_struct(col.type):
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            pv = parent_valid
            if col.null_count > 0:
                sv = pc.is_valid(col).to_numpy(zero_copy_only=False)
                pv = sv if pv is None else (pv & sv)
            for i, f in enumerate(col.type):
                add(f"{name}.{f.name}", col.field(i), pv)
            return
        if pa.types.is_map(col.type):
            # map<k,v> DECOMPOSES into parallel '#keys'/'#vals' array
            # columns sharing lengths (types.MapType); the map's own
            # nulls ride as parent validity on both components
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            pv = parent_valid
            if col.null_count > 0:
                mv = pc.is_valid(col).to_numpy(zero_copy_only=False)
                pv = mv if pv is None else (pv & mv)
            offsets = col.offsets
            add(T.map_keys_col(name),
                pa.ListArray.from_arrays(offsets, col.keys), pv)
            add(T.map_vals_col(name),
                pa.ListArray.from_arrays(offsets, col.items), pv)
            return
        if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
            vals, lengths, validity, dictionary, el_dtype = \
                _list_to_padded(col)
            if parent_valid is not None:
                validity = (parent_valid if validity is None
                            else (validity & parent_valid))
                lengths = np.where(validity, lengths, 0).astype(np.int32)
            fields.append(Field(name, T.ArrayType(el_dtype),
                                nullable=validity is not None,
                                dictionary=dictionary))
            arrays.append(vals)
            validities.append(validity)
            fields.append(Field(T.array_len_col(name), T.INT32,
                                nullable=False))
            arrays.append(lengths)
            validities.append(None)
            return
        dtype = arrow_type_to_dtype(col.type)
        values, validity, dictionary = _column_to_numpy(col, dtype)
        if parent_valid is not None:
            # a NULL struct row means every child field is NULL
            validity = (parent_valid if validity is None
                        else (validity & parent_valid))
        fields.append(Field(name, dtype, nullable=validity is not None,
                            dictionary=dictionary))
        arrays.append(values)
        validities.append(validity)

    for name, col in zip(table.column_names, table.columns):
        add(name, col)
    return Schema(tuple(fields)), arrays, validities


def from_arrow(table: pa.Table, capacity: Optional[int] = None,
               narrow_transfer: bool = False) -> Batch:
    """Arrow table -> device Batch (pads to bucketed capacity); see
    ``arrow_to_numpy`` for the host-side column conversion rules."""
    schema, arrays, validities = arrow_to_numpy(table)
    return from_numpy(schema, arrays, validities, capacity=capacity,
                      narrow_transfer=narrow_transfer)


def schema_from_arrow(pa_schema: "pa.Schema") -> Schema:
    """Engine Schema for an arrow schema (via an empty conversion so the
    type mapping stays in one place)."""
    empty = pa.table({f.name: pa.array([], type=f.type)
                      for f in pa_schema})
    return from_arrow(empty).schema


def to_arrow(batch: Batch) -> pa.Table:
    """Device Batch -> Arrow table with only live rows (whole batch
    fetched in ONE device->host transfer, see Batch.fetch_host). Array
    columns rebuild arrow lists from the padded 2D layout + '#len'
    companion (which is dropped from the output)."""
    mask, host_cols = batch.fetch_host()
    columns = []
    names = []
    by_name = {f.name: hc for f, hc in zip(batch.schema.fields,
                                           host_cols)}
    hidden = {T.array_len_col(f.name) for f in batch.schema.fields
              if isinstance(f.dtype, T.ArrayType)}
    def rebuild_list(f, cdata, cvalid):
        """Padded 2D + '#len' companion -> (offsets int32 np, flat
        values pa.Array, valid np bool|None)."""
        data = cdata[mask]
        valid = None if cvalid is None else cvalid[mask]
        comp = by_name.get(T.array_len_col(f.name))
        lens = (comp[0][mask].astype(np.int64) if comp is not None
                else np.full(len(data), data.shape[1], np.int64))
        if valid is not None:
            lens = np.where(valid, lens, 0)
        offsets = np.zeros(len(data) + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        jj = np.arange(data.shape[1])[None, :]
        alive = jj < lens[:, None]
        flat = data[alive]
        if isinstance(f.dtype.element, T.StringType):
            d = list(f.dictionary or ())
            values = pa.DictionaryArray.from_arrays(
                pa.array(flat.astype(np.int32), pa.int32()),
                pa.array(d, pa.string())).cast(pa.string())
        elif isinstance(f.dtype.element, T.DecimalType):
            # flat holds UNSCALED scaled-int64 values — route through
            # the raw-buffer rebuild like the scalar decimal branch
            values = decimal_from_unscaled(
                flat, dtype_to_arrow_type(f.dtype.element))
        else:
            values = pa.array(
                flat, type=dtype_to_arrow_type(f.dtype.element))
        return offsets, values, valid

    field_by_name = {f.name: f for f in batch.schema.fields}
    for f, (cdata, cvalid) in zip(batch.schema.fields, host_cols):
        if f.name in hidden:
            continue
        if isinstance(f.dtype, T.ArrayType):
            base = T.map_base_name(f.name)
            sibling = (T.map_vals_col(base) if base is not None
                       and f.name.endswith(T.MAP_KEYS_SUFFIX) else None)
            if base is not None and sibling in field_by_name:
                # '#keys'/'#vals' pair -> one arrow map column
                offsets, keys, valid = rebuild_list(f, cdata, cvalid)
                vf = field_by_name[sibling]
                _, items, _ = rebuild_list(vf, *by_name[sibling])
                off = pa.array(
                    offsets, pa.int32(),
                    mask=(np.concatenate([~valid, [False]])
                          if valid is not None and not valid.all()
                          else None))
                columns.append(pa.MapArray.from_arrays(off, keys, items))
                names.append(base)
                continue
            if base is not None \
                    and f.name.endswith(T.MAP_VALS_SUFFIX) \
                    and T.map_keys_col(base) in field_by_name:
                continue  # emitted with its '#keys' sibling
            offsets, values, valid = rebuild_list(f, cdata, cvalid)
            if valid is not None and not valid.all():
                arr = pa.ListArray.from_arrays(
                    pa.array(offsets, pa.int32()), values,
                    mask=pa.array(~valid))
            else:
                arr = pa.ListArray.from_arrays(
                    pa.array(offsets, pa.int32()), values)
            columns.append(arr)
            names.append(f.name)
            continue
        data = cdata[mask]
        valid = None if cvalid is None else cvalid[mask]
        if isinstance(f.dtype, T.StringType):
            dictionary = list(f.dictionary or ())
            codes = pa.array(data, type=pa.int32(),
                             mask=None if valid is None else ~valid)
            arr = pa.DictionaryArray.from_arrays(
                codes, pa.array(dictionary, type=pa.string())
            ).cast(pa.string())
        elif isinstance(f.dtype, T.DateType):
            arr = pa.array(data, type=pa.int32(),
                           mask=None if valid is None else ~valid).cast(pa.date32())
        elif isinstance(f.dtype, T.TimestampType):
            arr = pa.array(data, type=pa.int64(),
                           mask=None if valid is None else ~valid).cast(
                pa.timestamp("us"))
        elif isinstance(f.dtype, T.DecimalType):
            arr = decimal_from_unscaled(
                data, pa.decimal128(f.dtype.precision, f.dtype.scale),
                valid)
        else:
            arr = pa.array(data, type=dtype_to_arrow_type(f.dtype),
                           mask=None if valid is None else ~valid)
        columns.append(arr)
        names.append(f.name)
    return pa.table(columns, names=names)

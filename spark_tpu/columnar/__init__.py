from spark_tpu.columnar.batch import (
    Batch,
    BatchData,
    ColumnData,
    empty_batch,
    from_numpy,
    round_capacity,
)
from spark_tpu.columnar.arrow import from_arrow, to_arrow

__all__ = [
    "Batch",
    "BatchData",
    "ColumnData",
    "empty_batch",
    "from_numpy",
    "from_arrow",
    "to_arrow",
    "round_capacity",
]

"""Device-side columnar batch.

The TPU-native analogue of the reference's ColumnarBatch / ColumnVector
surface (reference: sql/catalyst/src/main/java/org/apache/spark/sql/
vectorized/ColumnarBatch.java:30, ColumnVector.java) and of the Tungsten
row format it replaces (UnsafeRow.java:57).

Design (TPU-first, not a port):

- A batch has a *static* row capacity. Live rows are tracked with a
  boolean ``row_mask`` instead of a dynamic length, so every operator is
  shape-stable under ``jax.jit`` — filters flip mask bits, they never
  compact. This is the static-shape discipline XLA needs; the reference
  has no peer (JVM rows are fully dynamic).
- Per-column nulls are separate boolean validity arrays (Arrow-style),
  `None` meaning "all valid".
- Strings are int32 dictionary codes; the dictionary itself lives on the
  host in the Schema, never on device.

``BatchData`` is a pytree (NamedTuples of arrays) so whole query
pipelines jit end-to-end; ``Schema`` travels on the host beside it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_tpu.types import Field, Schema


class ColumnData(NamedTuple):
    """Device arrays for one column: dense values + optional validity."""

    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # bool[capacity]; None = all valid

    def valid_mask(self, capacity: int) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((capacity,), dtype=jnp.bool_)
        return self.validity


class BatchData(NamedTuple):
    """Device half of a batch: column arrays + live-row mask.

    All arrays share the same leading (and only) dimension: the static
    row capacity. ``row_mask[i]`` False means row i does not exist
    (filtered out or padding) — distinct from SQL NULL.
    """

    columns: Tuple[ColumnData, ...]
    row_mask: jnp.ndarray  # bool[capacity]

    @property
    def capacity(self) -> int:
        return int(self.row_mask.shape[0])


class Batch:
    """Host-level pairing of a Schema with BatchData, the unit the
    executor passes between stages. Thin — all compute goes through the
    physical operators, which consume (schema, data) and are jitted."""

    __slots__ = ("schema", "data", "__weakref__")

    def __init__(self, schema: Schema, data: BatchData):
        assert len(schema) == len(data.columns), (
            f"schema arity {len(schema)} != data arity {len(data.columns)}"
        )
        self.schema = schema
        self.data = data

    @property
    def capacity(self) -> int:
        return self.data.capacity

    def num_valid_rows(self) -> int:
        return int(np.asarray(self.data.row_mask).sum())

    def column(self, name: str) -> ColumnData:
        return self.data.columns[self.schema.index(name)]

    def __repr__(self) -> str:
        return f"Batch({self.schema}, capacity={self.capacity})"

    # ---- host materialization -------------------------------------------

    def to_pylist(self) -> list:
        """Materialize live rows as a list of dicts (decoding string
        dictionaries and dates). For tests and `.collect()`."""
        import datetime

        from spark_tpu.types import DateType, StringType, TimestampType

        import jax

        # ONE bulk device->host fetch for the whole batch: per-array
        # np.asarray() pays a full blocking round trip each (87 ms over a
        # tunneled TPU), which dominated collect() latency
        host = jax.device_get(
            (self.data.row_mask,
             tuple((cd.data, cd.validity) for cd in self.data.columns)))
        mask = np.asarray(host[0])
        out_rows: list = []
        cols = []
        for f, (cdata, cvalid) in zip(self.schema.fields, host[1]):
            data = np.asarray(cdata)[mask]
            valid = (
                np.ones(len(data), dtype=bool)
                if cvalid is None
                else np.asarray(cvalid)[mask]
            )
            if isinstance(f.dtype, StringType):
                dictionary = f.dictionary or ()
                vals = [
                    dictionary[c] if (v and 0 <= c < len(dictionary)) else None
                    for c, v in zip(data, valid)
                ]
            elif isinstance(f.dtype, DateType):
                epoch = datetime.date(1970, 1, 1)
                vals = [
                    epoch + datetime.timedelta(days=int(d)) if v else None
                    for d, v in zip(data, valid)
                ]
            elif isinstance(f.dtype, TimestampType):
                epoch = datetime.datetime(1970, 1, 1)
                vals = [
                    epoch + datetime.timedelta(microseconds=int(d)) if v else None
                    for d, v in zip(data, valid)
                ]
            else:
                vals = [d.item() if v else None for d, v in zip(data, valid)]
            cols.append(vals)
        for i in range(len(cols[0]) if cols else 0):
            out_rows.append(
                {f.name: cols[j][i] for j, f in enumerate(self.schema.fields)}
            )
        return out_rows

    def to_pandas(self):
        import pandas as pd

        rows = self.to_pylist()
        return pd.DataFrame(rows, columns=list(self.schema.names))


def round_capacity(n: int, multiple: int = 1024) -> int:
    """Round row count up to a bucketed capacity so jit caches hit across
    similar-sized inputs (analogue of recompile avoidance; the reference
    has no static-shape constraint)."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def from_numpy(
    schema: Schema,
    arrays: Sequence[np.ndarray],
    validities: Optional[Sequence[Optional[np.ndarray]]] = None,
    capacity: Optional[int] = None,
) -> Batch:
    """Build a device batch from host numpy columns, padding to capacity."""
    n = int(arrays[0].shape[0]) if arrays else 0
    for a in arrays:
        assert a.shape[0] == n, "all columns must have equal length"
    cap = capacity if capacity is not None else round_capacity(n)
    assert cap >= n
    if validities is None:
        validities = [None] * len(arrays)

    cols = []
    for f, arr, val in zip(schema.fields, arrays, validities):
        np_dt = f.dtype.np_dtype
        padded = np.zeros((cap,), dtype=np_dt)
        padded[:n] = arr.astype(np_dt, copy=False)
        v = None
        if val is not None:
            pv = np.zeros((cap,), dtype=bool)
            pv[:n] = val
            v = jnp.asarray(pv)
        cols.append(ColumnData(jnp.asarray(padded), v))
    row_mask = np.zeros((cap,), dtype=bool)
    row_mask[:n] = True
    return Batch(schema, BatchData(tuple(cols), jnp.asarray(row_mask)))


def empty_batch(schema: Schema, capacity: int = 1024) -> Batch:
    return from_numpy(
        schema, [np.zeros((0,), dtype=f.dtype.np_dtype) for f in schema.fields],
        capacity=capacity,
    )

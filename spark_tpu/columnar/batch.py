"""Device-side columnar batch.

The TPU-native analogue of the reference's ColumnarBatch / ColumnVector
surface (reference: sql/catalyst/src/main/java/org/apache/spark/sql/
vectorized/ColumnarBatch.java:30, ColumnVector.java) and of the Tungsten
row format it replaces (UnsafeRow.java:57).

Design (TPU-first, not a port):

- A batch has a *static* row capacity. Live rows are tracked with a
  boolean ``row_mask`` instead of a dynamic length, so every operator is
  shape-stable under ``jax.jit`` — filters flip mask bits, they never
  compact. This is the static-shape discipline XLA needs; the reference
  has no peer (JVM rows are fully dynamic).
- Per-column nulls are separate boolean validity arrays (Arrow-style),
  `None` meaning "all valid".
- Strings are int32 dictionary codes; the dictionary itself lives on the
  host in the Schema, never on device.

``BatchData`` is a pytree (NamedTuples of arrays) so whole query
pipelines jit end-to-end; ``Schema`` travels on the host beside it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_tpu.types import Field, Schema

# jitted column-packers for single-transfer host fetches, keyed on
# (capacity, per-array kind/dtype signature)
_PACKER_CACHE: dict = {}

# one spare thread for overlapping the float-plane fetch with the
# int-plane fetch in Batch.fetch_host (tunnel latency hiding)
import concurrent.futures as _cf

_FETCH_POOL = _cf.ThreadPoolExecutor(max_workers=1)


class ColumnData(NamedTuple):
    """Device arrays for one column: dense values + optional validity."""

    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # bool[capacity]; None = all valid

    def valid_mask(self, capacity: int) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones((capacity,), dtype=jnp.bool_)
        return self.validity


class BatchData(NamedTuple):
    """Device half of a batch: column arrays + live-row mask.

    All arrays share the same leading (and only) dimension: the static
    row capacity. ``row_mask[i]`` False means row i does not exist
    (filtered out or padding) — distinct from SQL NULL.
    """

    columns: Tuple[ColumnData, ...]
    row_mask: jnp.ndarray  # bool[capacity]

    @property
    def capacity(self) -> int:
        return int(self.row_mask.shape[0])


class Batch:
    """Host-level pairing of a Schema with BatchData, the unit the
    executor passes between stages. Thin — all compute goes through the
    physical operators, which consume (schema, data) and are jitted."""

    __slots__ = ("schema", "data", "__weakref__")

    def __init__(self, schema: Schema, data: BatchData):
        assert len(schema) == len(data.columns), (
            f"schema arity {len(schema)} != data arity {len(data.columns)}"
        )
        self.schema = schema
        self.data = data

    @property
    def capacity(self) -> int:
        return self.data.capacity

    def num_valid_rows(self) -> int:
        return int(np.asarray(self.data.row_mask).sum())

    def column(self, name: str) -> ColumnData:
        return self.data.columns[self.schema.index(name)]

    def __repr__(self) -> str:
        return f"Batch({self.schema}, capacity={self.capacity})"

    def device_nbytes(self) -> int:
        """Device bytes held by this batch (values + validity + mask) —
        the unit of the out-of-HBM prefetch byte budget."""
        total = self.data.row_mask.size * self.data.row_mask.dtype.itemsize
        for cd in self.data.columns:
            total += cd.data.size * cd.data.dtype.itemsize
            if cd.validity is not None:
                total += cd.validity.size * cd.validity.dtype.itemsize
        return int(total)

    def block_until_ready(self) -> "Batch":
        """Wait for all pending host->device transfers of this batch's
        arrays. The pipeline producer calls this so a chunk's transfer
        completes on the PRODUCER thread (overlapped with the consumer's
        device compute) instead of lazily serializing into the
        consumer's next dispatch."""
        try:
            self.data.row_mask.block_until_ready()
            for cd in self.data.columns:
                cd.data.block_until_ready()
                if cd.validity is not None:
                    cd.validity.block_until_ready()
        except AttributeError:
            pass  # non-jax arrays (tests) have no block_until_ready
        except RuntimeError as e:
            # a deleted buffer is benign (chunk already consumed); any
            # other RuntimeError is a real transfer/allocation failure
            # and must surface here, on the producer thread
            if "deleted" not in str(e).lower():
                raise
        return self

    # ---- host materialization -------------------------------------------

    def fetch_host(self):
        """Move the WHOLE batch to host in one device->host transfer.

        Returns (mask: np.bool_[cap], [(data, validity|None)] per column,
        numpy). Per-array fetches pay a full ~25 ms round trip EACH over
        a tunneled TPU and jax.device_get's copy_to_host_async overlap
        is a no-op there, so an 8-column result cost 8 round trips. Here
        a tiny jitted packer bitcasts every column (+mask/validity) into
        one (k, capacity) uint64 matrix fetched with a single transfer,
        then host-side views restore the dtypes."""
        import jax

        cols = self.data.columns
        # two planes (value-preserving casts only — the axon AOT x64
        # rewrite cannot lower 64-bit bitcasts): ints/bools stack as
        # int64, floats stack as float64
        plan = [("i", 0, jnp.bool_)]  # (plane, slot, dtype) for mask
        int_arrays = [self.data.row_mask]
        flt_arrays = []
        extra_arrays = []  # 2D array columns: fetched individually
        for cd in cols:
            if cd.data.ndim > 1:
                plan.append(("x", len(extra_arrays), cd.data.dtype))
                extra_arrays.append(cd.data)
            elif jnp.issubdtype(cd.data.dtype, jnp.floating):
                plan.append(("f", len(flt_arrays), cd.data.dtype))
                flt_arrays.append(cd.data)
            else:
                plan.append(("i", len(int_arrays), cd.data.dtype))
                int_arrays.append(cd.data)
            if cd.validity is not None:
                plan.append(("i", len(int_arrays), jnp.bool_))
                int_arrays.append(cd.validity)
        sig = (self.capacity, tuple((p, str(d)) for p, _, d in plan))
        packer = _PACKER_CACHE.get(sig)
        if packer is None:
            def pack(ints, flts):
                iplane = jnp.stack([x.astype(jnp.int64) for x in ints])
                fplane = (jnp.stack([x.astype(jnp.float64) for x in flts])
                          if flts else jnp.zeros((0, 0), jnp.float64))
                return iplane, fplane

            packer = jax.jit(pack)
            _PACKER_CACHE[sig] = packer
        iplane, fplane = packer(tuple(int_arrays), tuple(flt_arrays))
        if fplane.size:
            # fetch the two planes CONCURRENTLY: device_get walks the
            # tree serially and each blocking transfer pays the full
            # tunnel round trip (~120 ms measured), so two overlapped
            # fetches cost ~one
            fut = _FETCH_POOL.submit(np.asarray, fplane)
            ih = np.asarray(iplane)
            fh = fut.result()
        else:
            # all-integer batch (e.g. decimal money results): do NOT
            # fetch the empty float plane — even a zero-size device_get
            # pays the full tunnel round trip
            ih = np.asarray(iplane)
            fh = np.zeros((0, 0), dtype=np.float64)

        xh = [np.asarray(a) for a in extra_arrays]  # one RTT each

        def restore(plane, slot, dt):
            if plane == "x":
                return xh[slot]
            row = ih[slot] if plane == "i" else fh[slot]
            if dt == jnp.bool_:
                return row.astype(bool)
            return row

        mask = restore(*plan[0])
        out = []
        i = 1
        for cd in cols:
            data = restore(*plan[i])
            i += 1
            valid = None
            if cd.validity is not None:
                valid = restore(*plan[i])
                i += 1
            out.append((data, valid))
        return mask, out

    def to_pylist(self) -> list:
        """Materialize live rows as a list of dicts (decoding string
        dictionaries and dates). For tests and `.collect()`."""
        import datetime

        from spark_tpu.types import (ArrayType, DateType, DecimalType,
                                     StringType, TimestampType,
                                     array_len_col)

        mask, host_cols = self.fetch_host()
        out_rows: list = []
        cols = []
        by_name = {f.name: hc for f, hc in zip(self.schema.fields,
                                               host_cols)}
        hidden = {array_len_col(f.name) for f in self.schema.fields
                  if isinstance(f.dtype, ArrayType)}
        out_fields = [f for f in self.schema.fields
                      if f.name not in hidden]
        for f in out_fields:
            cdata, cvalid = by_name[f.name]
            data = cdata[mask]
            valid = (
                np.ones(len(data), dtype=bool)
                if cvalid is None
                else cvalid[mask]
            )
            if isinstance(f.dtype, ArrayType):
                comp = by_name.get(array_len_col(f.name))
                lens = (comp[0][mask] if comp is not None
                        else np.full(len(data), data.shape[1]))

                def el(x):
                    if isinstance(f.dtype.element, StringType):
                        d = f.dictionary or ()
                        return d[x] if 0 <= x < len(d) else None
                    if isinstance(f.dtype.element, DecimalType):
                        import decimal as _d

                        return _d.Decimal(int(x)).scaleb(
                            -f.dtype.element.scale)
                    return x.item() if hasattr(x, "item") else x

                vals = [
                    [el(x) for x in row[:int(ln)]] if v else None
                    for row, ln, v in zip(data, lens, valid)
                ]
            elif isinstance(f.dtype, StringType):
                dictionary = f.dictionary or ()
                vals = [
                    dictionary[c] if (v and 0 <= c < len(dictionary)) else None
                    for c, v in zip(data, valid)
                ]
            elif isinstance(f.dtype, DateType):
                epoch = datetime.date(1970, 1, 1)
                vals = [
                    epoch + datetime.timedelta(days=int(d)) if v else None
                    for d, v in zip(data, valid)
                ]
            elif isinstance(f.dtype, TimestampType):
                epoch = datetime.datetime(1970, 1, 1)
                vals = [
                    epoch + datetime.timedelta(microseconds=int(d)) if v else None
                    for d, v in zip(data, valid)
                ]
            elif isinstance(f.dtype, DecimalType):
                import decimal as _decimal

                s = f.dtype.scale
                vals = [
                    _decimal.Decimal(int(d)).scaleb(-s) if v else None
                    for d, v in zip(data, valid)
                ]
            else:
                vals = [d.item() if v else None for d, v in zip(data, valid)]
            cols.append(vals)
        # pair '#keys'/'#vals' components back into map dicts
        # (types.MapType decomposition)
        from spark_tpu.types import map_base_name, map_keys_col, \
            map_vals_col

        idx = {f.name: j for j, f in enumerate(out_fields)}
        emit: list = []  # (output name, column index | (kj, vj))
        for j, f in enumerate(out_fields):
            base = map_base_name(f.name)
            if base is not None and map_keys_col(base) in idx \
                    and map_vals_col(base) in idx:
                if f.name.endswith("#keys"):
                    emit.append((base, (j, idx[map_vals_col(base)])))
                continue  # '#vals' rides with its '#keys' sibling
            emit.append((f.name, j))
        n_rows = len(cols[0]) if cols else 0
        for i in range(n_rows):
            row = {}
            for name, j in emit:
                if isinstance(j, tuple):
                    kj, vj = j
                    ks, vs = cols[kj][i], cols[vj][i]
                    row[name] = None if ks is None else dict(zip(ks, vs))
                else:
                    row[name] = cols[j][i]
            out_rows.append(row)
        return out_rows

    def to_pandas(self):
        import pandas as pd

        rows = self.to_pylist()
        return pd.DataFrame(rows, columns=list(self.schema.names))


def round_capacity(n: int, multiple: int = 1024) -> int:
    """Round row count up to a bucketed capacity so jit caches hit across
    similar-sized inputs (analogue of recompile avoidance; the reference
    has no static-shape constraint)."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def from_numpy(
    schema: Schema,
    arrays: Sequence[np.ndarray],
    validities: Optional[Sequence[Optional[np.ndarray]]] = None,
    capacity: Optional[int] = None,
    narrow_transfer: bool = False,
) -> Batch:
    """Build a device batch from host numpy columns, padding to capacity.

    ``narrow_transfer`` ships int64 columns whose values fit int32 as
    int32 — the stage runner widens them back at trace entry
    (Pipe.from_batch_data), so the cast runs ON DEVICE and the
    host->device link moves half the bytes. Built for tunneled TPUs
    (~34 MB/s measured): the out-of-HBM tiers stream tens of GB
    through this path."""
    n = int(arrays[0].shape[0]) if arrays else 0
    for a in arrays:
        assert a.shape[0] == n, "all columns must have equal length"
    cap = capacity if capacity is not None else round_capacity(n)
    assert cap >= n
    if validities is None:
        validities = [None] * len(arrays)

    cols = []
    for f, arr, val in zip(schema.fields, arrays, validities):
        np_dt = arr.dtype if arr.ndim > 1 else f.dtype.np_dtype
        if narrow_transfer and arr.ndim == 1 \
                and np.dtype(np_dt) == np.int64 and n > 0:
            lo = int(arr.min()) if n else 0
            hi = int(arr.max()) if n else 0
            if -(1 << 31) <= lo and hi < (1 << 31):
                np_dt = np.int32
        shape = (cap,) + tuple(arr.shape[1:])
        padded = np.zeros(shape, dtype=np_dt)
        padded[:n] = arr.astype(np_dt, copy=False)
        v = None
        if val is not None:
            pv = np.zeros((cap,), dtype=bool)
            pv[:n] = val
            v = jnp.asarray(pv)
        cols.append(ColumnData(jnp.asarray(padded), v))
    row_mask = np.zeros((cap,), dtype=bool)
    row_mask[:n] = True
    return Batch(schema, BatchData(tuple(cols), jnp.asarray(row_mask)))


def empty_batch(schema: Schema, capacity: int = 1024) -> Batch:
    return from_numpy(
        schema, [np.zeros((0,), dtype=f.dtype.np_dtype) for f in schema.fields],
        capacity=capacity,
    )

"""Data type system for the TPU-native SQL engine.

Maps the reference's Catalyst type system (reference:
sql/catalyst/src/main/scala/org/apache/spark/sql/types/) onto JAX-friendly
device representations:

- integers / floats map directly to jnp dtypes,
- StringType is dictionary-encoded: int32 codes on device + a host-side
  tuple of strings (the dictionary) carried in the schema,
- DateType is int32 days since the Unix epoch (Arrow date32 layout),
- TimestampType is int64 microseconds since the epoch,
- DecimalType(p, s) is represented as float64 on device for round-1
  (parity tests use tolerances; an exact scaled-int64 path is planned).

Unlike Catalyst there is no UnsafeRow binary format: columns are plain
dense arrays, nulls live in a separate validity bitmask (Arrow-style),
which is the natural TPU layout (vectorizable, MXU/VPU friendly).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import numpy as np


class DataType:
    """Base class for SQL data types."""

    #: numpy dtype used for the device representation of values.
    np_dtype: Any = None

    def __repr__(self) -> str:
        return self.__class__.__name__.replace("Type", "").lower()

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, (IntegralType, FractionalType, DecimalType))

    @property
    def is_integral(self) -> bool:
        return isinstance(self, IntegralType)

    @property
    def is_string(self) -> bool:
        return isinstance(self, StringType)


class IntegralType(DataType):
    pass


class FractionalType(DataType):
    pass


class BooleanType(DataType):
    np_dtype = np.bool_


class Int8Type(IntegralType):
    np_dtype = np.int8


class Int16Type(IntegralType):
    np_dtype = np.int16


class Int32Type(IntegralType):
    np_dtype = np.int32


class Int64Type(IntegralType):
    np_dtype = np.int64


class Float32Type(FractionalType):
    np_dtype = np.float32


class Float64Type(FractionalType):
    np_dtype = np.float64


class StringType(DataType):
    """Dictionary-encoded on device: values are int32 codes into a
    host-side dictionary (tuple of python strings) stored in the schema."""

    np_dtype = np.int32


class DateType(DataType):
    """Days since 1970-01-01, int32 (Arrow date32)."""

    np_dtype = np.int32


class TimestampType(DataType):
    """Microseconds since epoch, int64 (Arrow timestamp[us])."""

    np_dtype = np.int64


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """Decimal(precision, scale). Device representation: SCALED int64
    (unscaled value = decimal * 10^scale), so money math is EXACT
    (reference: sql/catalyst/.../types/Decimal.scala — a JVM BigDecimal/
    long hybrid). Deviation from the reference: max precision is 18
    digits (int64) rather than 38 (int128); results whose Spark-rule
    precision would exceed 18 get their scale reduced to fit, like
    Spark's own DecimalPrecision.adjustPrecisionScale does past 38.
    Division and avg route through float64 then round back to the
    result scale (exact for quotients up to 2^53)."""

    precision: int = 18
    scale: int = 6
    np_dtype: Any = field(default=np.int64, compare=False, repr=False)

    MAX_PRECISION = 18

    def __repr__(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __hash__(self) -> int:
        return hash((DecimalType, self.precision, self.scale))


@dataclass(frozen=True)
class ArrayType(DataType):
    """array<element>. Device layout: PADDED 2D values (capacity,
    max_len) plus a hidden '<col>#len' int32 companion column — the
    TPU-first answer to the reference's offsets-based UnsafeArrayData
    (UnsafeArrayData.java): static shapes, and every row-level kernel
    (gather joins, compaction, exchanges, sort permutations) handles the
    pair as two ordinary columns with zero special cases. Cost: memory
    is rows x max_len (document per-batch); elements are non-null
    (element_at of a missing position is NULL, null ELEMENTS inside an
    array are not represented yet)."""

    element: DataType
    np_dtype: Any = field(default=np.int64, compare=False, repr=False)

    def __repr__(self) -> str:
        return f"array<{self.element!r}>"

    def __hash__(self) -> int:
        return hash((ArrayType, self.element))


LEN_SUFFIX = "#len"


def array_len_col(name: str) -> str:
    """Hidden companion column carrying per-row array lengths."""
    return name + LEN_SUFFIX


@dataclass(frozen=True)
class MapType(DataType):
    """map<key, value>. Device layout: a map column DECOMPOSES at the
    batch boundary into two parallel padded array columns,
    '<col>#keys' (array<key>) and '<col>#vals' (array<value>), sharing
    equal per-row lengths — the TPU-first answer to the reference's
    ArrayBasedMapData (two ArrayData siblings inside one value,
    reference: types/MapType.scala, ArrayBasedMapData.scala): static
    shapes, and every row-level kernel handles the pair as ordinary
    array columns with zero special cases. Lookups (element_at /
    m[k]) are a vectorized key-match + take_along_axis over the pair.
    Like the reference, maps are not orderable/groupable."""

    key: DataType
    value: DataType
    np_dtype: Any = field(default=np.int64, compare=False, repr=False)

    def __repr__(self) -> str:
        return f"map<{self.key!r},{self.value!r}>"

    def __hash__(self) -> int:
        return hash((MapType, self.key, self.value))


MAP_KEYS_SUFFIX = "#keys"
MAP_VALS_SUFFIX = "#vals"


def map_keys_col(name: str) -> str:
    return name + MAP_KEYS_SUFFIX


def map_vals_col(name: str) -> str:
    return name + MAP_VALS_SUFFIX


def map_base_name(name: str) -> Optional[str]:
    """'m#keys'/'m#vals' -> 'm'; None for non-map-component names."""
    for suffix in (MAP_KEYS_SUFFIX, MAP_VALS_SUFFIX):
        if name.endswith(suffix):
            return name[:-len(suffix)]
    return None


@dataclass(frozen=True)
class StructType(DataType):
    """struct<...>. Structs FLATTEN at ingest into dotted columns
    ('s.f1', 's.f2' — reference peer: UnsafeRow nested struct access);
    this marker type survives only in error messages and casts."""

    names: Tuple[str, ...] = ()
    np_dtype: Any = field(default=np.int64, compare=False, repr=False)

    def __hash__(self) -> int:
        return hash((StructType, self.names))


# Singleton instances for convenience.
BOOLEAN = BooleanType()
INT8 = Int8Type()
INT16 = Int16Type()
INT32 = Int32Type()
INT64 = Int64Type()
FLOAT32 = Float32Type()
FLOAT64 = Float64Type()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()


_NUMERIC_WIDENING = [
    Int8Type(),
    Int16Type(),
    Int32Type(),
    Int64Type(),
    Float32Type(),
    Float64Type(),
]


def common_type(a: DataType, b: DataType) -> DataType:
    """Numeric/temporal type coercion, modelled after Catalyst's
    TypeCoercion (reference: sql/catalyst/.../analysis/TypeCoercion.scala).
    """
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        if isinstance(a, (Float32Type, Float64Type)) or isinstance(
            b, (Float32Type, Float64Type)
        ):
            return FLOAT64
        # decimal vs decimal/integral: widest integral part + widest
        # scale (reference: DecimalPrecision.widerDecimalType)
        pa = a.precision if isinstance(a, DecimalType) else 19
        sa = a.scale if isinstance(a, DecimalType) else 0
        pb = b.precision if isinstance(b, DecimalType) else 19
        sb = b.scale if isinstance(b, DecimalType) else 0
        return bounded_decimal(max(pa - sa, pb - sb) + max(sa, sb),
                               max(sa, sb))
    if a.is_numeric and b.is_numeric:
        ia = _NUMERIC_WIDENING.index(a)
        ib = _NUMERIC_WIDENING.index(b)
        return _NUMERIC_WIDENING[max(ia, ib)]
    if isinstance(a, DateType) and isinstance(b, StringType):
        return a
    if isinstance(a, StringType) and isinstance(b, DateType):
        return b
    raise TypeError(f"cannot find common type for {a} and {b}")


def bounded_decimal(precision: int, scale: int) -> DecimalType:
    """Cap a derived decimal type at the int64-representable 18 digits,
    sacrificing scale first (the reference's adjustPrecisionScale
    discipline at ITS 38-digit cap, DecimalType.scala) while keeping at
    least 6 fractional digits when the integral part allows."""
    cap = DecimalType.MAX_PRECISION
    if precision <= cap:
        return DecimalType(precision, scale)
    intpart = precision - scale
    min_scale = min(scale, 6)
    new_scale = max(min_scale, cap - intpart)
    return DecimalType(cap, new_scale)


def infer_type(value: Any) -> DataType:
    """Infer the SQL type of a python literal."""
    if isinstance(value, bool):
        return BOOLEAN
    if isinstance(value, (int, np.integer)):
        return INT64
    if isinstance(value, (float, np.floating)):
        return FLOAT64
    if isinstance(value, str):
        return STRING
    if isinstance(value, datetime.datetime):
        return TIMESTAMP
    if isinstance(value, datetime.date):
        return DATE
    raise TypeError(f"cannot infer SQL type for literal {value!r}")


def date_to_days(d: datetime.date) -> int:
    return (d - datetime.date(1970, 1, 1)).days


def days_to_date(days: int) -> datetime.date:
    return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(days))


@dataclass(frozen=True)
class Field:
    """A named, typed column in a schema. ``dictionary`` is the host-side
    string dictionary for StringType columns (None until bound to data)."""

    name: str
    dtype: DataType
    nullable: bool = True
    dictionary: Optional[Tuple[str, ...]] = None

    def with_name(self, name: str) -> "Field":
        return Field(name, self.dtype, self.nullable, self.dictionary)


@dataclass(frozen=True)
class Schema:
    """Ordered collection of fields; the host-side half of a batch
    (device half is columnar.batch.BatchData). Plays the role of
    Catalyst's StructType (reference: sql/catalyst/.../types/StructType.scala)."""

    fields: Tuple[Field, ...]

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"column {name!r} not found in schema {self.names}")

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"column {name!r} not found in schema {self.names}")

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}:{f.dtype}" for f in self.fields)
        return f"schema<{inner}>"


def parse_ddl_schema(ddl: str) -> Schema:
    """Parse a simple DDL schema string: ``"name type, name type"``
    (reference: StructType.fromDDL; the subset pyspark users pass to
    applyInPandas* — no nested types)."""
    mapping = {
        "boolean": BOOLEAN, "bool": BOOLEAN,
        "tinyint": INT8, "byte": INT8,
        "smallint": INT16, "short": INT16,
        "int": INT32, "integer": INT32,
        "bigint": INT64, "long": INT64,
        "float": FLOAT32, "real": FLOAT32,
        "double": FLOAT64,
        "string": STRING, "varchar": STRING,
        "date": DATE, "timestamp": TIMESTAMP,
    }
    fields = []
    for part in ddl.split(","):
        toks = part.strip().split()
        if len(toks) < 2:
            raise ValueError(f"bad DDL field: {part!r}")
        name, type_name = toks[0], toks[1].lower()
        base = type_name.split("(")[0]
        if base == "decimal":
            inner = type_name[type_name.index("(") + 1:
                              type_name.index(")")].split(",") \
                if "(" in type_name else ["10", "0"]
            dt: DataType = DecimalType(int(inner[0]), int(inner[1]))
        elif base in mapping:
            dt = mapping[base]
        else:
            raise ValueError(f"unsupported DDL type: {type_name!r}")
        fields.append(Field(name, dt, nullable=True))
    return Schema(tuple(fields))

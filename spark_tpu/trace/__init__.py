"""End-to-end hierarchical query tracing (spark_tpu/trace/).

The span analogue of the reference's TaskMetrics/SQLMetrics + event-log
replay: every query gets a ``trace_id``, and every unit of work —
connect request, router dispatch, scheduler queue/admit/run, plan
analysis, compile-store probe, per-stage device execution, exchange
stats fetch, pipeline chunk decode/transfer, fault retry, result-cache/
mview/storage probe — opens a child span under a contextvar-carried
parent. Spans land in the existing metrics ring/JSONL as ``span``
events, and the active (trace_id, span_id, parent_id) triple is stamped
onto EVERY event ``metrics.record()`` emits, so flat events (stage,
exchange, fault_injected, ...) attribute to the query that caused them
even under the concurrent scheduler — positional slicing survives only
as a fallback for id-less events.

Context crosses threads explicitly (scheduler tickets and the chunk
pipeline producer capture ``current()`` and re-enter it) and crosses
processes via the ``X-SparkTpu-Trace`` header (``header_value()`` /
``from_header()``), so one trace spans client -> federation router ->
replica -> scheduler -> stages.

Cost discipline: id stamping is always on (one contextvar read per
event). Span *events* obey ``spark.tpu.trace.enabled`` and the
``spark.tpu.trace.sampleRatio`` knob — the sampling decision is made
once at root creation and inherited, so a trace is either complete or
absent, never partial. Tracing never touches data: results are
byte-identical with tracing on or off.

Every span name must be declared in ``SPAN_NAMES`` below —
tools/lint_invariants.py rule 6 enforces the same discipline conf keys
and fault points get.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Iterator, NamedTuple, Optional

from spark_tpu import conf as CF
from spark_tpu import metrics

TRACE_ENABLED = CF.register(
    "spark.tpu.trace.enabled", True,
    "Record hierarchical span events for every unit of query work "
    "(connect request, dispatch, queue, stage, chunk, ...). Ids are "
    "stamped on events regardless; this only gates span events.", bool)

TRACE_SAMPLE_RATIO = CF.register(
    "spark.tpu.trace.sampleRatio", 1.0,
    "Fraction of traces that record span events (decided once at root "
    "creation, inherited fleet-wide via X-SparkTpu-Trace). Lower it "
    "when span-heavy paths (per-chunk pipeline spans) matter.", float)

TRACE_HEADER = "X-SparkTpu-Trace"

#: central registry of legal span names (lint_invariants rule 6:
#: every ``trace.span("<name>", ...)`` literal must appear here)
SPAN_NAMES = frozenset({
    "connect.client",       # client side of one HTTP request
    "connect.request",      # replica/server handling of one request
    "router.dispatch",      # federation routing of one request
    "router.forward",       # one forward attempt to one replica
    "scheduler.queue",      # submit -> admitted (queue + admission gate)
    "scheduler.run",        # prepare + execute on a scheduler worker
    "query.execute",        # DataFrame._execute (root when standalone)
    "query.analysis",       # static plan analysis + submit gate
    "compile.probe",        # AOT executable-store lookup
    "stage.run",            # one physical stage (host glue + device)
    "stage.fused",          # whole-query fused span: multi-exchange
                            # plan as ONE XLA program, zero host sync
    "stage.device",         # device execution, block_until_ready-bounded
    "exchange.stats",       # AQE host round-trip fetching device stats
    "agg.decide",           # adaptive-agg sketch fetch + strategy pick
    "agg.sort",             # sort rung: range exchange + sorted merge
    "agg.presplit",         # hot-key pre-split: salted exchange + merge
    "pipeline.decode",      # chunk pipeline: one chunk decode+filter
    "pipeline.transfer",    # chunk pipeline: one chunk host->device
    "fault.retry",          # one recovery re-attempt after a fault
    "result_cache.probe",   # serve-tier plan-keyed result cache probe
    "serve.epoch",          # ownership epoch mint + fleet broadcast
    "serve.invalidate",     # one invalidation-log record applied
    "mview.probe",          # materialized-view / cache-manager probe
    "storage.pin",          # HBM pin-scope around query execution
    "join.partition",       # hybrid hash join: grant + partition pass
    "join.spill",           # hybrid hash join: one spill write/read
    "slo.admit",            # SLO feasibility check at submit time
    "slo.observe",          # fold a finished query into the SLO model
})


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    sampled: bool

    def header(self) -> str:
        """Wire form for ``X-SparkTpu-Trace`` (traceparent-shaped:
        trace-span-flags)."""
        return f"{self.trace_id}-{self.span_id}-{int(self.sampled)}"


def _new_id(n: int = 16) -> str:
    return uuid.uuid4().hex[:n]


def current() -> Optional[SpanContext]:
    """The active span context on this thread (None outside any trace)."""
    return metrics.trace_context()


def current_trace_id() -> Optional[str]:
    ctx = metrics.trace_context()
    return ctx.trace_id if ctx is not None else None


def _conf():
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    return None if sess is None else sess.conf


def _sample_root() -> bool:
    """Sampling decision for a NEW trace root."""
    conf = _conf()
    try:
        enabled = bool(conf.get(TRACE_ENABLED)) if conf is not None \
            else bool(TRACE_ENABLED.default)
        ratio = float(conf.get(TRACE_SAMPLE_RATIO)) if conf is not None \
            else float(TRACE_SAMPLE_RATIO.default)
    except Exception:
        enabled, ratio = True, 1.0
    if not enabled or ratio <= 0.0:
        return False
    if metrics.brownout_level() > 0:
        # fleet brownout sheds NEW trace sampling before any query:
        # in-flight traces finish, fresh roots go unsampled
        return False
    return ratio >= 1.0 or random.random() < ratio


@contextmanager
def span(name: str, **attrs: Any) -> Iterator[SpanContext]:
    """Open one unit of work as a child of the ambient span (or as a
    new trace root when none is active). On exit a ``span`` event is
    recorded into the metrics ring/JSONL with trace_id/span_id/
    parent_id, start time ``t0`` (epoch s), ``ms`` and the attrs; root
    exit also flushes the buffered JSONL writer so a finished query is
    always on disk."""
    parent = metrics.trace_context()
    if parent is None:
        ctx = SpanContext(_new_id(16), _new_id(8), None, _sample_root())
    else:
        ctx = SpanContext(parent.trace_id, _new_id(8),
                          parent.span_id, parent.sampled)
    token = metrics.set_trace_context(ctx)
    t0 = time.time()
    p0 = time.perf_counter()
    err: Optional[str] = None
    try:
        yield ctx
    except BaseException as e:
        err = repr(e)
        raise
    finally:
        metrics.reset_trace_context(token)
        if ctx.sampled:
            ms = (time.perf_counter() - p0) * 1e3
            fields = dict(name=name, ms=round(ms, 3), t0=round(t0, 6),
                          tid=threading.get_ident() % 10_000_000,
                          trace_id=ctx.trace_id, span_id=ctx.span_id,
                          parent_id=ctx.parent_id)
            if err is not None:
                fields["error"] = err
            fields.update(attrs)
            metrics.record("span", **fields)
        if parent is None:
            # trace root closed: a query just finished end-to-end
            metrics.flush_log()


@contextmanager
def attach(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Re-enter a captured span context on another thread (scheduler
    worker, pipeline producer) or adopt a remote parent decoded from
    ``X-SparkTpu-Trace``. No span event is recorded — children opened
    inside do that."""
    if ctx is None:
        yield
        return
    token = metrics.set_trace_context(ctx)
    try:
        yield
    finally:
        metrics.reset_trace_context(token)


def from_header(value: Optional[str]) -> Optional[SpanContext]:
    """Decode ``X-SparkTpu-Trace``; malformed values are dropped (a bad
    peer must not break serving)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 3 or not parts[0] or not parts[1]:
        return None
    if not all(c in "0123456789abcdef" for c in parts[0] + parts[1]):
        return None
    return SpanContext(parts[0], parts[1], None, parts[2] == "1")


def header_value() -> Optional[str]:
    """Wire form of the current context (None outside any trace)."""
    ctx = metrics.trace_context()
    return ctx.header() if ctx is not None else None

"""RDD tier: SparkContext + lineage-tracked partitioned collections.

Analogue of the reference's core RDD API (reference: core/.../rdd/
RDD.scala — 2,156 ln; checkpoint:1627; Dependency.scala; Partitioner.scala)
and the task-retry half of the scheduler (reference:
scheduler/DAGScheduler.scala:1762 handleTaskCompletion resubmits lost
tasks by recomputing their lineage; TaskSetManager maxTaskFailures).

TPU-first stance: the RDD is the *arbitrary-Python-object escape hatch*,
exactly as it is in modern PySpark — closures cannot run on the MXU, so
this tier executes host-side over partitioned lists, while ``toDF()`` /
``DataFrame.rdd`` bridge to the columnar engine where the real compute
belongs. What is kept from the reference is the semantics users rely on:
lazy lineage (a partition is recomputed from its parents on failure —
recompute IS the fault-tolerance story, there is no replication),
narrow vs shuffle dependencies, hash partitioning for *ByKey ops,
``cache()`` as materialized partitions, and ``checkpoint()`` as lineage
truncation to durable storage.

Failure handling: every partition computation runs as a *task* with
``spark.task.maxFailures`` attempts (reference: TaskSetManager.scala) —
a flaky closure (e.g. transient IO) is retried from lineage, a
deterministic error surfaces after the attempt budget.
"""

from __future__ import annotations

import builtins
import itertools
import os
import pickle
import random
from collections import defaultdict
from typing import Any, Callable, Iterable, List, Optional, Tuple

from spark_tpu import conf as CF

TASK_MAX_FAILURES = CF.register(
    "spark.task.maxFailures", 4,
    "Attempts per partition-compute task before the job fails "
    "(reference: config/package.scala TASK_MAX_FAILURES).", int)


class Broadcast:
    """Read-only value shared with every task (reference:
    broadcast/TorrentBroadcast.scala:59 — in a single driver process the
    torrent protocol collapses to a handle; on the mesh tier large
    columnar broadcasts ride all_gather in parallel/exchange.py)."""

    def __init__(self, value: Any):
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    def unpersist(self) -> None:
        self._value = None

    def destroy(self) -> None:
        self._value = None


class Accumulator:
    """Add-only shared counter (reference: util/AccumulatorV2.scala)."""

    def __init__(self, value: Any):
        self.value = value

    def add(self, term: Any) -> None:
        self.value = self.value + term

    def __iadd__(self, term: Any) -> "Accumulator":
        self.add(term)
        return self


class RDD:
    """A lazily-evaluated, partitioned collection with lineage."""

    _next_id = itertools.count()

    def __init__(self, sc: "SparkContext", num_partitions: int,
                 compute: Callable[[int], List[Any]],
                 parents: Tuple["RDD", ...] = (),
                 name: str = "rdd"):
        self._sc = sc
        self._num_partitions = num_partitions
        self._compute = compute
        self._parents = parents
        self._name = name
        self.id = next(RDD._next_id)
        self._cached: Optional[List[List[Any]]] = None
        self._cache_requested = False
        self._checkpoint_requested = False
        self._checkpoint_dir: Optional[str] = None

    # -- partitions & tasks --------------------------------------------------

    def getNumPartitions(self) -> int:
        return self._num_partitions

    def _partition(self, i: int) -> List[Any]:
        """Materialize partition i, honoring cache/checkpoint tiers and
        running the compute as a retried task."""
        if self._cached is not None:
            return self._cached[i]
        if self._checkpoint_dir is not None:
            with open(self._ckpt_path(i), "rb") as f:
                return pickle.load(f)
        part = self._run_task(i)
        if self._cache_requested:
            # materialize ALL partitions on first touch so cache state
            # is consistent (reference: BlockManager.getOrElseUpdate)
            self._cached = [part if j == i else self._run_task(j)
                            for j in range(self._num_partitions)]
        if self._checkpoint_requested:
            self._do_checkpoint()
        return part

    def _run_task(self, i: int) -> List[Any]:
        from spark_tpu import recovery

        attempts = int(self._sc._conf_get(TASK_MAX_FAILURES))
        last: Optional[BaseException] = None
        for attempt in range(max(1, attempts)):
            try:
                return list(self._compute(i))
            except Exception as e:  # lineage recompute on next attempt
                last = e
                if attempt + 1 < max(1, attempts) \
                        and not recovery.retry_allowed("rdd.task"):
                    break
        raise RuntimeError(
            f"task failed {attempts} times: {self._name} partition {i}"
        ) from last

    def _all_partitions(self) -> List[List[Any]]:
        return [self._partition(i) for i in range(self._num_partitions)]

    # -- persistence ---------------------------------------------------------

    def cache(self) -> "RDD":
        self._cache_requested = True
        return self

    persist = cache

    def unpersist(self) -> "RDD":
        self._cache_requested = False
        self._cached = None
        return self

    def checkpoint(self) -> None:
        """Mark for truncation to durable storage on next materialization
        (reference: RDD.scala:1627 — checkpointed data replaces lineage,
        bounding recompute chains)."""
        if self._sc._checkpoint_dir is None:
            raise RuntimeError("call sc.setCheckpointDir(path) first")
        self._checkpoint_requested = True

    def localCheckpoint(self) -> None:
        self.cache()

    def isCheckpointed(self) -> bool:
        return self._checkpoint_dir is not None

    def _ckpt_path(self, i: int) -> str:
        assert self._checkpoint_dir is not None
        return os.path.join(self._checkpoint_dir, f"part-{i:05d}.pkl")

    def _do_checkpoint(self) -> None:
        d = os.path.join(self._sc._checkpoint_dir, f"rdd-{self.id}")
        os.makedirs(d, exist_ok=True)
        parts = [self._run_task(i) for i in range(self._num_partitions)]
        self._checkpoint_dir = d
        for i, p in enumerate(parts):
            with open(self._ckpt_path(i), "wb") as f:
                pickle.dump(p, f)
        self._parents = ()  # lineage truncated

    # -- narrow transformations ----------------------------------------------

    def _derive(self, fn: Callable[[int, List[Any]], List[Any]],
                name: str) -> "RDD":
        parent = self

        def compute(i: int) -> List[Any]:
            return fn(i, parent._partition(i))

        return RDD(self._sc, self._num_partitions, compute,
                   (parent,), name)

    def map(self, f: Callable) -> "RDD":
        return self._derive(lambda i, p: [f(x) for x in p], "map")

    def filter(self, f: Callable) -> "RDD":
        return self._derive(lambda i, p: [x for x in p if f(x)], "filter")

    def flatMap(self, f: Callable) -> "RDD":
        return self._derive(
            lambda i, p: [y for x in p for y in f(x)], "flatMap")

    def mapPartitions(self, f: Callable[[Iterable], Iterable]) -> "RDD":
        return self._derive(lambda i, p: list(f(iter(p))), "mapPartitions")

    def mapPartitionsWithIndex(self, f) -> "RDD":
        return self._derive(lambda i, p: list(f(i, iter(p))),
                            "mapPartitionsWithIndex")

    def mapValues(self, f: Callable) -> "RDD":
        return self.map(lambda kv: (kv[0], f(kv[1])))

    def flatMapValues(self, f: Callable) -> "RDD":
        return self.flatMap(lambda kv: [(kv[0], v) for v in f(kv[1])])

    def keyBy(self, f: Callable) -> "RDD":
        return self.map(lambda x: (f(x), x))

    def keys(self) -> "RDD":
        return self.map(lambda kv: kv[0])

    def values(self) -> "RDD":
        return self.map(lambda kv: kv[1])

    def zipWithIndex(self) -> "RDD":
        parent = self
        sizes = [len(p) for p in self._all_partitions()]
        offsets = [0]
        for s in sizes[:-1]:
            offsets.append(offsets[-1] + s)

        def compute(i: int) -> List[Any]:
            return [(x, offsets[i] + j)
                    for j, x in enumerate(parent._partition(i))]

        return RDD(self._sc, self._num_partitions, compute, (parent,),
                   "zipWithIndex")

    def sample(self, withReplacement: bool, fraction: float,
               seed: Optional[int] = None) -> "RDD":
        base = seed if seed is not None else 17

        def fn(i: int, p: List[Any]) -> List[Any]:
            rng = random.Random(base * 1000003 + i)
            if withReplacement:
                n = int(len(p) * fraction + 0.5)
                return [rng.choice(p) for _ in range(n)] if p else []
            return [x for x in p if rng.random() < fraction]

        return self._derive(fn, "sample")

    def union(self, other: "RDD") -> "RDD":
        left, right = self, other

        def compute(i: int) -> List[Any]:
            if i < left._num_partitions:
                return left._partition(i)
            return right._partition(i - left._num_partitions)

        return RDD(self._sc, left._num_partitions + right._num_partitions,
                   compute, (left, right), "union")

    def glom(self) -> "RDD":
        return self._derive(lambda i, p: [p], "glom")

    # -- shuffle transformations ---------------------------------------------

    def _shuffle_by_key(self, num_partitions: Optional[int]) -> "RDD":
        """Hash-partition (k, v) pairs (reference: Partitioner.scala
        HashPartitioner; the mesh engine's peer is the all_to_all
        exchange in parallel/exchange.py)."""
        parent = self
        n = num_partitions or self._num_partitions
        state: dict = {}

        def compute(i: int) -> List[Any]:
            if "buckets" not in state:
                buckets: List[List[Any]] = [[] for _ in range(n)]
                for p in range(parent._num_partitions):
                    for kv in parent._partition(p):
                        buckets[hash(kv[0]) % n].append(kv)
                state["buckets"] = buckets
            return state["buckets"][i]

        return RDD(self._sc, n, compute, (parent,), "shuffle")

    def partitionBy(self, numPartitions: int) -> "RDD":
        return self._shuffle_by_key(numPartitions)

    def groupByKey(self, numPartitions: Optional[int] = None) -> "RDD":
        shuffled = self._shuffle_by_key(numPartitions)

        def fn(i: int, p: List[Any]) -> List[Any]:
            groups: dict = defaultdict(list)
            for k, v in p:
                groups[k].append(v)
            return list(groups.items())

        return shuffled._derive(fn, "groupByKey")

    def reduceByKey(self, f: Callable,
                    numPartitions: Optional[int] = None) -> "RDD":
        parent = self

        # map-side combine before the shuffle (reference:
        # Aggregator.scala combineValuesByKey)
        def combine(i: int, p: List[Any]) -> List[Any]:
            acc: dict = {}
            for k, v in p:
                acc[k] = f(acc[k], v) if k in acc else v
            return list(acc.items())

        return parent._derive(combine, "mapSideCombine") \
            ._shuffle_by_key(numPartitions) \
            ._derive(combine, "reduceByKey")

    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numPartitions: Optional[int] = None) -> "RDD":
        def fn(i: int, p: List[Any]) -> List[Any]:
            acc: dict = {}
            for k, v in p:
                acc[k] = mergeValue(acc[k], v) if k in acc \
                    else createCombiner(v)
            return list(acc.items())

        shuffled = self._derive(fn, "combineLocal") \
            ._shuffle_by_key(numPartitions)

        def merge(i: int, p: List[Any]) -> List[Any]:
            acc: dict = {}
            for k, c in p:
                acc[k] = mergeCombiners(acc[k], c) if k in acc else c
            return list(acc.items())

        return shuffled._derive(merge, "combineByKey")

    def aggregateByKey(self, zeroValue, seqFunc, combFunc,
                       numPartitions: Optional[int] = None) -> "RDD":
        import copy

        return self.combineByKey(
            lambda v: seqFunc(copy.deepcopy(zeroValue), v),
            seqFunc, combFunc, numPartitions)

    def distinct(self, numPartitions: Optional[int] = None) -> "RDD":
        return self.map(lambda x: (x, None)) \
            .reduceByKey(lambda a, b: a, numPartitions) \
            .map(lambda kv: kv[0])

    def cogroup(self, other: "RDD",
                numPartitions: Optional[int] = None) -> "RDD":
        tagged = self.mapValues(lambda v: (0, v)) \
            .union(other.mapValues(lambda v: (1, v)))
        grouped = tagged.groupByKey(
            numPartitions or max(self._num_partitions,
                                 other._num_partitions))

        def fn(i: int, p: List[Any]) -> List[Any]:
            out = []
            for k, tags in p:
                ls = [v for t, v in tags if t == 0]
                rs = [v for t, v in tags if t == 1]
                out.append((k, (ls, rs)))
            return out

        return grouped._derive(fn, "cogroup")

    def join(self, other: "RDD",
             numPartitions: Optional[int] = None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: [(kv[0], (l, r)) for l in kv[1][0]
                        for r in kv[1][1]])

    def leftOuterJoin(self, other: "RDD",
                      numPartitions: Optional[int] = None) -> "RDD":
        def expand(kv):
            k, (ls, rs) = kv
            return [(k, (l, r)) for l in ls for r in (rs or [None])]

        return self.cogroup(other, numPartitions).flatMap(expand)

    def sortBy(self, keyfunc: Callable, ascending: bool = True,
               numPartitions: Optional[int] = None) -> "RDD":
        parent = self
        n = numPartitions or self._num_partitions
        state: dict = {}

        def compute(i: int) -> List[Any]:
            if "parts" not in state:
                data = sorted((x for p in parent._all_partitions()
                               for x in p),
                              key=keyfunc, reverse=not ascending)
                step = max(1, (len(data) + n - 1) // n)
                state["parts"] = [data[j * step:(j + 1) * step]
                                  for j in range(n)]
            return state["parts"][i]

        return RDD(self._sc, n, compute, (parent,), "sortBy")

    def sortByKey(self, ascending: bool = True,
                  numPartitions: Optional[int] = None) -> "RDD":
        return self.sortBy(lambda kv: kv[0], ascending, numPartitions)

    def repartition(self, numPartitions: int) -> "RDD":
        parent = self
        state: dict = {}

        def compute(i: int) -> List[Any]:
            if "parts" not in state:
                data = [x for p in parent._all_partitions() for x in p]
                state["parts"] = [data[j::numPartitions]
                                  for j in range(numPartitions)]
            return state["parts"][i]

        return RDD(self._sc, numPartitions, compute, (parent,),
                   "repartition")

    def coalesce(self, numPartitions: int) -> "RDD":
        return self.repartition(min(numPartitions, self._num_partitions))

    # -- actions -------------------------------------------------------------

    def collect(self) -> List[Any]:
        return [x for p in self._all_partitions() for x in p]

    def count(self) -> int:
        return sum(len(p) for p in self._all_partitions())

    def first(self) -> Any:
        got = self.take(1)
        if not got:
            raise ValueError("RDD is empty")
        return got[0]

    def take(self, n: int) -> List[Any]:
        out: List[Any] = []
        for i in range(self._num_partitions):
            out.extend(self._partition(i))
            if len(out) >= n:
                break
        return out[:n]

    def top(self, n: int, key: Optional[Callable] = None) -> List[Any]:
        return sorted(self.collect(), key=key, reverse=True)[:n]

    def reduce(self, f: Callable) -> Any:
        parts = [p for p in self._all_partitions() if p]
        if not parts:
            raise ValueError("RDD is empty")
        import functools

        partials = [functools.reduce(f, p) for p in parts]
        return functools.reduce(f, partials)

    def fold(self, zeroValue, f: Callable) -> Any:
        acc = zeroValue
        for p in self._all_partitions():
            part = zeroValue
            for x in p:
                part = f(part, x)
            acc = f(acc, part)
        return acc

    def aggregate(self, zeroValue, seqOp, combOp) -> Any:
        import copy

        acc = copy.deepcopy(zeroValue)
        for p in self._all_partitions():
            part = copy.deepcopy(zeroValue)
            for x in p:
                part = seqOp(part, x)
            acc = combOp(acc, part)
        return acc

    def countByKey(self) -> dict:
        out: dict = defaultdict(int)
        for p in self._all_partitions():
            for k, _ in p:
                out[k] += 1
        return dict(out)

    def countByValue(self) -> dict:
        out: dict = defaultdict(int)
        for p in self._all_partitions():
            for x in p:
                out[x] += 1
        return dict(out)

    def foreach(self, f: Callable) -> None:
        for p in self._all_partitions():
            for x in p:
                f(x)

    def foreachPartition(self, f: Callable) -> None:
        for p in self._all_partitions():
            f(iter(p))

    def sum(self) -> Any:
        return builtins.sum(x for p in self._all_partitions() for x in p)

    def mean(self) -> float:
        total, n = 0.0, 0
        for p in self._all_partitions():
            total += builtins.sum(p)
            n += len(p)
        if n == 0:
            raise ValueError("RDD is empty")
        return total / n

    def max(self, key: Optional[Callable] = None) -> Any:
        return builtins.max(self.collect(), key=key)

    def min(self, key: Optional[Callable] = None) -> Any:
        return builtins.min(self.collect(), key=key)

    def isEmpty(self) -> bool:
        return not self.take(1)

    def saveAsTextFile(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        for i in range(self._num_partitions):
            with open(os.path.join(path, f"part-{i:05d}"), "w") as f:
                for x in self._partition(i):
                    f.write(str(x) + "\n")
        with open(os.path.join(path, "_SUCCESS"), "w"):
            pass

    # -- bridge to the columnar engine ---------------------------------------

    def toDF(self, schema: Optional[List[str]] = None):
        """Materialize into the columnar engine — the TPU compute path."""
        session = self._sc._session
        rows = self.collect()
        if rows and isinstance(rows[0], tuple) and schema is not None:
            return session.createDataFrame(rows, schema)
        if rows and isinstance(rows[0], dict):
            return session.createDataFrame(rows)
        if schema is None:
            schema = ["value"]
        return session.createDataFrame([(r,) if not isinstance(r, tuple)
                                        else r for r in rows], schema)

    def toDebugString(self) -> bytes:
        lines = []

        def walk(r: "RDD", depth: int) -> None:
            lines.append("  " * depth + f"({r._num_partitions}) "
                         f"{r._name} [{r.id}]")
            for p in r._parents:
                walk(p, depth + 1)

        walk(self, 0)
        return "\n".join(lines).encode()

    def __repr__(self):
        return f"RDD[{self.id}] {self._name} ({self._num_partitions} parts)"


class SparkContext:
    """Driver-side entry point for the RDD tier (reference:
    SparkContext.scala:85, pared to what exists without a JVM cluster:
    the 'cluster' is this process plus the device mesh)."""

    def __init__(self, session):
        self._session = session
        self._checkpoint_dir: Optional[str] = None

    def _conf_get(self, entry) -> Any:
        return self._session.conf.get(entry)

    @property
    def defaultParallelism(self) -> int:
        import jax

        return max(2, len(jax.devices()))

    def parallelize(self, data: Iterable,
                    numSlices: Optional[int] = None) -> RDD:
        items = list(data)
        n = numSlices or min(self.defaultParallelism,
                             builtins.max(1, len(items)))
        step = (len(items) + n - 1) // n if items else 1
        parts = [items[i * step:(i + 1) * step] for i in range(n)]

        return RDD(self, n, lambda i: parts[i], (), "parallelize")

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1, numSlices: Optional[int] = None) -> RDD:
        if end is None:
            start, end = 0, start
        return self.parallelize(builtins.range(start, end, step), numSlices)

    def emptyRDD(self) -> RDD:
        return RDD(self, 1, lambda i: [], (), "empty")

    def textFile(self, path: str,
                 minPartitions: Optional[int] = None) -> RDD:
        """One element per line; a directory reads every part file
        (reference: SparkContext.textFile -> HadoopRDD)."""
        paths: List[str]
        if os.path.isdir(path):
            paths = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if not f.startswith("_") and not f.startswith("."))
        else:
            paths = [path]

        def compute(i: int) -> List[str]:
            with open(paths[i]) as f:
                return [ln.rstrip("\n") for ln in f]

        return RDD(self, len(paths), compute, (), "textFile")

    def wholeTextFiles(self, path: str) -> RDD:
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if os.path.isfile(os.path.join(path, f)))

        def compute(i: int) -> List[Tuple[str, str]]:
            with open(files[i]) as f:
                return [(files[i], f.read())]

        return RDD(self, builtins.max(1, len(files)), compute, (),
                   "wholeTextFiles")

    def union(self, rdds: List[RDD]) -> RDD:
        out = rdds[0]
        for r in rdds[1:]:
            out = out.union(r)
        return out

    def broadcast(self, value: Any) -> Broadcast:
        return Broadcast(value)

    def accumulator(self, value: Any) -> Accumulator:
        return Accumulator(value)

    def setCheckpointDir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        self._checkpoint_dir = path
        # shared with DataFrame.checkpoint() (recovery.CHECKPOINT_DIR)
        self._session.conf.set("spark.checkpoint.dir", path)

    def stop(self) -> None:
        self._session.stop()

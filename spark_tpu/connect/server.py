"""HTTP + Arrow-IPC result server (reference:
SparkConnectService.scala — ExecutePlan returning Arrow batches;
SparkExecuteStatementOperation.scala for the SQL-string entry)."""

from __future__ import annotations

import io
import json
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import pyarrow as pa

from spark_tpu import deadline, faults, locks, metrics, trace
from spark_tpu.serve.ownership import EPOCH_HEADER, EpochRetry


class ConnectServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 heartbeat=None, scheduler=None,
                 replica_id: Optional[str] = None, result_cache=None):
        from spark_tpu.scheduler import QueryScheduler, SchedulerQueueFull
        from spark_tpu.slo.edf import InfeasibleDeadline

        self.session = session
        #: serve-tier plan-keyed result cache, shared across every
        #: in-process replica of this session so the single-flight
        #: guarantee spans the fleet (active only when
        #: spark.tpu.serve.resultCache.enabled)
        if result_cache is None:
            result_cache = getattr(session, "serve_result_cache", None)
            if result_cache is None:
                from spark_tpu.serve.result_cache import ResultCache

                result_cache = ResultCache(session.conf)
                session.serve_result_cache = result_cache
        self.result_cache = result_cache
        #: highest ownership epoch this replica has adopted (0 until a
        #: router broadcast or stamped request teaches it one); a
        #: request stamped with an OLDER epoch is fenced with a typed
        #: EPOCH_RETRY (409) instead of being served under a stale
        #: shard->owner view
        self.fleet_epoch = 0
        self._epoch_lock = locks.named_lock("serve.ownership")
        self._owned_shards: set = set()
        #: optional recovery.HeartbeatMonitor surfaced via GET /health;
        #: falls back to one attached to the session
        self.heartbeat = heartbeat if heartbeat is not None \
            else getattr(session, "heartbeat_monitor", None)
        #: the multi-tenant query scheduler replaces the old global
        #: _exec_lock: host-side stages run concurrently on its worker
        #: pool, device execution is gated by HBM admission control,
        #: and a full queue answers 429 + Retry-After instead of an
        #: unbounded backlog (reference: TaskSchedulerImpl + FAIR pools)
        self.scheduler = scheduler if scheduler is not None \
            else QueryScheduler(session)
        # the UI status page reads queue depth / per-pool counts here
        session.query_scheduler = self.scheduler
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str,
                      headers=None) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    tid = trace.current_trace_id()
                    if tid:
                        # echo the trace id so clients can fetch
                        # GET /trace/<id> for the waterfall
                        self.send_header("X-SparkTpu-Trace-Id", tid)
                    if outer.fleet_epoch:
                        # every response carries the adopted ownership
                        # epoch so routers and clients converge on the
                        # newest fence without a broadcast round trip
                        self.send_header(EPOCH_HEADER,
                                         str(outer.fleet_epoch))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # the client gave up (e.g. its deadline passed while
                    # the request hung) — nothing left to tell it
                    pass

            def do_GET(self):
                if self.path == "/tables":
                    body = json.dumps(
                        outer.session.catalog.listTables()).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/health":
                    hb = outer.heartbeat
                    body = json.dumps(
                        {"status": "ok",
                         "replica": outer.replica_id,
                         # live load snapshot the federation router's
                         # least_queued policy and shedding read
                         "queue_depth": outer.scheduler.queue_depth(),
                         "running": outer.scheduler.running_count(),
                         "heartbeat": hb.status() if hb is not None
                         else None,
                         "scheduler": outer.scheduler.status()}).encode()
                    self._send(
                        200, body, "application/json",
                        headers={"X-SparkTpu-Replica": outer.replica_id})
                elif self.path == "/shards":
                    # shard report: the federation router learns which
                    # scan file-sets this replica's catalog serves and
                    # rendezvous-maps them over the healthy fleet
                    from spark_tpu.serve.ownership import catalog_shards

                    body = json.dumps(
                        {"replica": outer.replica_id,
                         "epoch": outer.fleet_epoch,
                         "tables":
                             catalog_shards(outer.session)}).encode()
                    self._send(200, body, "application/json")
                elif self.path.startswith("/invalidations"):
                    # watermark replay for reconnecting caches:
                    # GET /invalidations?since=<version>
                    from urllib.parse import parse_qs, urlparse

                    from spark_tpu.serve.ownership import \
                        session_invalidation_log

                    q = parse_qs(urlparse(self.path).query)
                    since = int((q.get("since") or ["0"])[0])
                    log = session_invalidation_log(outer.session)
                    records, resync = log.since(since)
                    body = json.dumps(
                        {"version": log.version, "resync": resync,
                         "records": records}).encode()
                    self._send(200, body, "application/json")
                elif self.path.startswith("/queries"):
                    body = json.dumps(
                        {"status": outer.scheduler.status(),
                         "queries": outer.scheduler.describe()}).encode()
                    self._send(200, body, "application/json")
                elif self.path.startswith("/trace/"):
                    # Chrome trace-event JSON for one trace id, ready
                    # for Perfetto / chrome://tracing (in-process
                    # replicas share the metrics ring, so any replica
                    # can render the whole fleet-crossing trace)
                    from spark_tpu import history, metrics

                    tid = self.path.rsplit("/", 1)[1]
                    evs = metrics.query_events(tid)
                    if not evs:
                        self._send(
                            404,
                            json.dumps({"error": "unknown trace",
                                        "trace_id": tid}).encode(),
                            "application/json")
                    else:
                        body = json.dumps(
                            history.chrome_trace(evs)).encode()
                        self._send(200, body, "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path.startswith("/cancel/"):
                    try:
                        qid = int(self.path.rsplit("/", 1)[1])
                    except ValueError:
                        self._send(400, b"bad query id", "text/plain")
                        return
                    ok = outer.scheduler.cancel(qid)
                    self._send(200 if ok else 404,
                               json.dumps({"cancelled": ok}).encode(),
                               "application/json")
                    return
                if self.path == "/epoch":
                    # router broadcast of a freshly minted epoch +
                    # shard->owner map: adopt it and eagerly rebuild
                    # any shards this replica just gained
                    n = int(self.headers.get("Content-Length", "0"))
                    try:
                        payload = json.loads(
                            self.rfile.read(n) or b"{}")
                        resp = outer._adopt_epoch(payload)
                        self._send(200, json.dumps(resp).encode(),
                                   "application/json")
                    except Exception as e:
                        self._send(400, json.dumps(
                            {"error": type(e).__name__,
                             "message": str(e)}).encode(),
                            "application/json")
                    return
                if self.path == "/lint":
                    # static analysis of a SQL query WITHOUT executing
                    # it: build the lazy DataFrame, analyze, return the
                    # report as JSON (the remote twin of
                    # df.explain(mode="lint"))
                    n = int(self.headers.get("Content-Length", "0"))
                    try:
                        req = json.loads(self.rfile.read(n))
                        from spark_tpu import analysis

                        df = outer.session.sql(req["query"])
                        report = analysis.analyze(
                            df._plan, outer.session.conf,
                            intent=req.get("intent"))
                        body = json.dumps(report.to_dict()).encode()
                        self._send(200, body, "application/json")
                    except Exception as e:
                        body = json.dumps(
                            {"error": type(e).__name__,
                             "message": str(e)}).encode()
                        self._send(400, body, "application/json")
                    return
                if self.path not in ("/sql", "/plan"):
                    self._send(404, b"not found", "text/plain")
                    return
                stale = outer._fence_epoch(
                    self.headers.get(EPOCH_HEADER))
                if stale is not None:
                    # epoch fence: the sender's shard->owner map
                    # predates a failover this replica knows about —
                    # answer typed-retryable instead of serving under
                    # stale ownership; the router/client re-dispatch
                    # with a fresh stamp under the unified RetryBudget
                    err = EpochRetry(*stale)
                    metrics.note_serve("epoch_fences")
                    metrics.record("serve", phase="epoch_fence",
                                   replica=outer.replica_id,
                                   request_epoch=err.request_epoch,
                                   fleet_epoch=err.fleet_epoch)
                    body = json.dumps(
                        {"error": "EpochRetry",
                         "message": str(err),
                         "epoch": err.fleet_epoch}).encode()
                    self._send(409, body, "application/json")
                    return
                n = int(self.headers.get("Content-Length", "0"))
                # adopt the caller's trace (client or federation
                # router) so this request's spans — scheduler, stages,
                # faults — join the fleet-wide trace; a missing/bad
                # header starts a fresh root here. The caller's
                # absolute deadline rides the same hop: binding it
                # here puts it in scope for the scheduler ticket and
                # every retry/wait seam under this request.
                rctx = trace.from_header(
                    self.headers.get(trace.TRACE_HEADER))
                rdl = deadline.from_header(
                    self.headers.get(deadline.DEADLINE_HEADER))
                with trace.attach(rctx), deadline.bind(rdl), \
                        trace.span("connect.request", path=self.path,
                                   replica=outer.replica_id):
                    self._handle_query(n)

            def _handle_query(self, n: int) -> None:
                try:
                    faults.inject("connect.request", outer.session.conf)
                    # a request whose caller-deadline already passed in
                    # transit is dead on arrival: answer typed with
                    # ZERO scheduler submits and zero device work
                    deadline.check("connect.request")
                    req = json.loads(self.rfile.read(n))

                    def build_df():
                        if self.path == "/sql":
                            return outer.session.sql(req["query"])
                        # typed logical-plan protocol (reference:
                        # relations.proto decoded by
                        # SparkConnectPlanner.scala:67)
                        from spark_tpu.api.dataframe import DataFrame
                        from spark_tpu.connect.proto import decode_plan

                        return DataFrame(
                            outer.session,
                            decode_plan(req["plan"], outer.session))

                    pool = req.get("pool") \
                        or self.headers.get("X-Spark-Pool")
                    deadline_s = req.get("deadline_s")
                    description = req.get("query", f"plan:{self.path}")

                    def submit(bdf):
                        return outer.scheduler.submit_query(
                            bdf, pool=pool, description=description,
                            deadline_s=float(deadline_s)
                            if deadline_s is not None else None,
                            sql=req.get("query"))

                    cache = outer.result_cache
                    key = None
                    if cache is not None and cache.enabled():
                        # cache hook BEFORE submit_query: a hit (or a
                        # piggyback on an identical in-flight query)
                        # never touches the scheduler at all — the
                        # dispatch+execution cost of a repeated
                        # dashboard query is one dict lookup. The key
                        # goes through THIS cache's fingerprint probe
                        # (TTL-amortized under fingerprintCacheSeconds;
                        # kept fresh by the fleet invalidation log).
                        try:
                            df = build_df()
                            key = cache.result_key(df._plan)
                        except Exception:
                            key = None  # unkeyable: uncached path
                    if key is not None:
                        holder = {}

                        def execute():
                            t = holder["ticket"] = submit(lambda: df)
                            return t.result()

                        with trace.span("result_cache.probe"):
                            blob, status = cache.get_or_execute(
                                key, execute)
                        headers = {
                            "X-SparkTpu-Replica": outer.replica_id,
                            "X-Cache": status}
                        t = holder.get("ticket")
                        if t is not None:
                            headers["X-Query-Id"] = str(t.id)
                            headers["X-Queue-Wait-Ms"] = \
                                f"{t.queue_wait_ms():.2f}"
                        headers.update(outer._slo_headers(t))
                        self._send(
                            200, blob,
                            "application/vnd.apache.arrow.stream",
                            headers=headers)
                        return
                    ticket = submit(build_df)
                    tbl = ticket.result()
                    sink = io.BytesIO()
                    with pa.ipc.new_stream(sink, tbl.schema) as w:
                        w.write_table(tbl)
                    self._send(
                        200, sink.getvalue(),
                        "application/vnd.apache.arrow.stream",
                        headers={
                            "X-Query-Id": str(ticket.id),
                            "X-Queue-Wait-Ms":
                                f"{ticket.queue_wait_ms():.2f}",
                            "X-SparkTpu-Replica": outer.replica_id,
                            **outer._slo_headers(ticket)})
                except SchedulerQueueFull as e:
                    # backpressure, not failure: the client should back
                    # off and retry (Client honors Retry-After); the
                    # federation router instead sheds the request to
                    # the least-loaded healthy replica
                    body = json.dumps(
                        {"error": "SchedulerQueueFull",
                         "message": str(e),
                         "retry_after_s": e.retry_after_s}).encode()
                    self._send(429, body, "application/json",
                               headers={
                                   "Retry-After":
                                       f"{e.retry_after_s:g}",
                                   "X-SparkTpu-Replica":
                                       outer.replica_id})
                except InfeasibleDeadline as e:
                    # SLO reject-at-admission: the latency model says
                    # this query cannot finish inside its deadline, so
                    # it was shed BEFORE costing a queue slot or any
                    # device time. 503 (not 429): the queue is not
                    # full — retrying the same replica with the same
                    # deadline yields the same prediction. The
                    # federation router may still re-dispatch it to a
                    # less-loaded replica under the retry budget.
                    metrics.record("serve", phase="slo_reject",
                                   replica=outer.replica_id,
                                   predicted_ms=round(e.predicted_ms, 2))
                    body = json.dumps(
                        {"error": "InfeasibleDeadline",
                         "message": str(e),
                         "predicted_ms": round(e.predicted_ms, 3),
                         "queue_ms": round(e.queue_ms, 3),
                         "run_ms": round(e.run_ms, 3),
                         "deadline": e.deadline}).encode()
                    self._send(503, body, "application/json",
                               headers={
                                   "X-SparkTpu-Predicted-Ms":
                                       f"{e.predicted_ms:.2f}",
                                   "X-SparkTpu-Sched-Policy": "EDF",
                                   "X-SparkTpu-Replica":
                                       outer.replica_id})
                except Exception as e:  # error -> JSON with message
                    body = json.dumps(
                        {"error": type(e).__name__,
                         "message": str(e),
                         "traceback": traceback.format_exc()}).encode()
                    self._send(400, body, "application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        #: stable identity the federation router routes affinity by;
        #: defaults to the bound port (unique per in-process fleet)
        self.replica_id = replica_id or f"r{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- SLO surface -----------------------------------------------------------

    def _slo_headers(self, ticket=None) -> dict:
        """Response headers surfacing the SLO outcome (predicted
        latency, scheduling policy, predictive-brownout level). Empty
        when spark.tpu.slo.enabled is off so the off-path response is
        byte-identical to the pre-SLO server."""
        if getattr(self.scheduler, "_slo", None) is None:
            return {}
        h = {"X-SparkTpu-Sched-Policy": "EDF",
             "X-SparkTpu-Brownout": str(metrics.brownout_level())}
        pred = getattr(ticket, "slo_predicted_ms", None) \
            if ticket is not None else None
        if pred is not None:
            h["X-SparkTpu-Predicted-Ms"] = f"{pred:.2f}"
        return h

    # -- fleet ownership ------------------------------------------------------

    def _fence_epoch(self, header_value):
        """None = admit the request; ``(request_epoch, fleet_epoch)``
        = fence it (typed EPOCH_RETRY). A NEWER stamp is adopted
        monotonically — the broadcast that should have carried it may
        have been lost, and the stamp itself is proof the epoch
        exists."""
        if header_value is None:
            return None
        try:
            e = int(header_value)
        except (TypeError, ValueError):
            return None  # malformed stamp: route by policy, no fence
        with self._epoch_lock:
            if e > self.fleet_epoch:
                self.fleet_epoch = e
                return None
            if e < self.fleet_epoch:
                return (e, self.fleet_epoch)
        return None

    def _adopt_epoch(self, payload: dict) -> dict:
        """Adopt a broadcast epoch + owner map; eagerly rebuild any
        shards this replica just GAINED (the lineage-recompute
        analogue: state is re-derived from source files, so a lost or
        failed rebuild only costs latency on the first owned query,
        never bytes)."""
        epoch = int(payload.get("epoch", 0))
        owners = payload.get("owners") or {}
        shard_paths = payload.get("shards") or {}
        with self._epoch_lock:
            if epoch > self.fleet_epoch:
                self.fleet_epoch = epoch
            mine = {s for s, rid in owners.items()
                    if rid == self.replica_id}
            gained = sorted(mine - self._owned_shards)
            self._owned_shards = mine
            fleet = self.fleet_epoch
        if gained:
            self._rebuild_shards(gained, shard_paths)
        return {"replica": self.replica_id, "epoch": fleet,
                "owned": sorted(mine), "gained": gained}

    def _rebuild_shards(self, gained, shard_paths) -> None:
        """Warm the dataset + schema of every newly-gained shard from
        its source files, deadline-capped; ANY failure (including an
        injected ``serve.ownership`` fault) degrades to lazy rebuild
        on the first owned query."""
        from spark_tpu.plan import logical as L
        from spark_tpu.serve.ownership import (
            SERVE_OWNERSHIP_REBUILD,
            SERVE_OWNERSHIP_REBUILD_TIMEOUT_S, shard_key)

        conf = self.session.conf
        try:
            if not bool(conf.get(SERVE_OWNERSHIP_REBUILD)):
                return
            tmo = float(conf.get(SERVE_OWNERSHIP_REBUILD_TIMEOUT_S))
        except Exception:
            return
        warmed = 0
        wanted = set(gained)
        views = getattr(getattr(self.session, "catalog", None),
                        "_views", None) or {}
        try:
            with deadline.bind(deadline.mint(tmo)):
                faults.inject("serve.ownership", conf)
                for name, plan in list(views.items()):
                    scans = L.collect_nodes(plan, L.UnresolvedScan)
                    if len(scans) != 1:
                        continue
                    src = scans[0].source
                    paths = getattr(src, "paths", None)
                    if not paths or shard_key(paths) not in wanted:
                        continue
                    deadline.check("serve.ownership")
                    # warm the REAL session-shared source: dataset
                    # discovery + schema, so the first owned query
                    # pays only the device execution
                    src._open()
                    src.schema()
                    warmed += 1
            metrics.note_serve("rebuilds")
            metrics.record("serve", phase="rebuild",
                           replica=self.replica_id,
                           shards=len(gained), warmed=warmed)
        except Exception as e:
            metrics.record("fault_recovered", point="serve.ownership",
                           how="lazy_rebuild",
                           replica=self.replica_id, warmed=warmed,
                           error=type(e).__name__)

    def start(self) -> "ConnectServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="spark-tpu-connect", daemon=True)
        self._thread.start()
        # AOT pre-warm: replay the served-plan history on a background
        # worker so the plan space is traced/compiled (or loaded from
        # the executable store) before the first client query arrives
        try:
            from spark_tpu import conf as _CF

            svc = self.session.compile_service
            if svc is not None and bool(
                    self.session.conf.get(_CF.COMPILE_PREWARM_ENABLED)):
                svc.prewarm(self.session, block=False)
        except Exception:
            pass  # pre-warm is an optimization, never a startup failure
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.scheduler.stop()
        if getattr(self.session, "query_scheduler", None) \
                is self.scheduler:
            self.session.query_scheduler = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def serve(session, host: str = "127.0.0.1", port: int = 15002,
          heartbeat=None) -> ConnectServer:
    """Start the server (default port mirrors Spark Connect's 15002)."""
    return ConnectServer(session, host, port,
                         heartbeat=heartbeat).start()


class _RetryableHTTP(RuntimeError):
    """A 429 backpressure response; carries the server's Retry-After."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class Client:
    """Minimal client: sql() -> pyarrow.Table (reference client surface:
    pyspark.sql.connect.session.SparkSession.sql).

    Transient connection errors (refused/reset — a flapping or
    restarting server) and 429 backpressure responses are retried with
    FULL-JITTER bounded exponential backoff (delay drawn uniformly
    from [0, min(cap, base * 2^attempt)]): N clients rejected by the
    same full queue would otherwise all sleep the identical
    deterministic delay and stampede the queue again the moment it
    expires. A 429's Retry-After hint is still honored as an additive
    floor — the jitter spreads arrivals across the window AFTER the
    server said capacity may exist. Timeouts and real query errors
    are NOT retried — a deadline that passed once will pass again, and
    retrying a genuine bug only quadruples its latency.

    When the server echoes an ``X-SparkTpu-Replica`` header (a
    federation router does, naming the replica that served the
    request), the client sends it back on subsequent requests as
    session affinity, keeping one client's queries on one replica's
    warm scheduler/compile state."""

    def __init__(self, url: str, timeout: float = 60.0,
                 retries: int = 3, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0):
        self.url = url.rstrip("/")
        #: per-request deadline — urllib otherwise blocks forever on a
        #: hung server
        self.timeout = float(timeout)
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        #: replica affinity echoed by a federation router; None until
        #: the first routed response
        self.affinity: Optional[str] = None
        #: trace id of the last completed request (the server echoes
        #: it via X-SparkTpu-Trace-Id); fetch the waterfall with
        #: ``trace(client.last_trace_id)``
        self.last_trace_id: Optional[str] = None
        #: metadata of the last completed request: ``replica`` (which
        #: backend served it), ``cache`` (X-Cache: hit/miss/wait),
        #: ``epoch`` (the fleet ownership epoch the response carried),
        #: ``query_id``, ``queue_wait_ms``, ``trace_id``
        self.last_query: dict = {}

    def _jitter(self, attempt: int) -> float:
        import random as _random

        return _random.uniform(
            0.0, min(self.max_backoff_s,
                     self.backoff_s * (2.0 ** attempt)))

    def _post(self, path: str, payload: dict,
              pool: Optional[str] = None) -> pa.Table:
        # one client-side span across every retry attempt: the whole
        # request (including backoff) is a single unit of the trace,
        # and each attempt ships the span context in X-SparkTpu-Trace.
        # The per-request timeout mints the ABSOLUTE deadline the
        # whole fleet honors (X-SparkTpu-Deadline); an already-bound
        # tighter caller deadline wins inside bind().
        with deadline.bind(deadline.mint(self.timeout)), \
                trace.span("connect.client", path=path):
            return self._post_retrying(path, payload, pool)

    def _post_retrying(self, path: str, payload: dict,
                       pool: Optional[str] = None) -> pa.Table:
        import time as _time

        from spark_tpu.recovery import RetryBudget

        # a request-local budget (the client has no session conf):
        # same draw discipline and counters as the server-side seams
        budget = RetryBudget(self.retries, layer_floor=0,
                             backoff_base_s=self.backoff_s,
                             backoff_cap_s=self.max_backoff_s)
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return self._post_once(path, payload, pool)
            except _RetryableHTTP as e:
                # 429 backpressure: the server's Retry-After hint is
                # the floor, full jitter desynchronizes the herd above
                # it
                last = e
                delay = e.retry_after_s + self._jitter(attempt)
            except (ConnectionRefusedError, ConnectionResetError,
                    ConnectionAbortedError, BrokenPipeError) as e:
                last = e
                delay = self._jitter(attempt)
            if attempt >= self.retries \
                    or not budget.draw("connect.client"):
                break
            # never sleep past the request deadline (a Retry-After
            # floor beyond it used to put the client to sleep through
            # its own timeout), and fail FAST with the typed error
            # once the window closes instead of burning an attempt on
            # a doomed round trip
            _time.sleep(deadline.cap_sleep(delay))
            deadline.check("connect.client")
        raise RuntimeError(
            f"connect request to {self.url + path} failed after "
            f"{attempt + 1} attempts (last: {last!r})") from last

    def _post_once(self, path: str, payload: dict,
                   pool: Optional[str] = None) -> pa.Table:
        import socket
        import urllib.error
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if pool:
            headers["X-Spark-Pool"] = pool
        if self.affinity:
            headers["X-SparkTpu-Replica"] = self.affinity
        hv = trace.header_value()
        if hv:
            headers[trace.TRACE_HEADER] = hv
        dv = deadline.header_value()
        if dv:
            headers[deadline.DEADLINE_HEADER] = dv
        # the socket timeout shrinks with the request deadline: a
        # retry attempt near the window's end gets only what is left
        timeout = self.timeout
        rem = deadline.remaining()
        if rem is not None:
            timeout = max(0.001, min(timeout, rem))
        req = urllib.request.Request(
            self.url + path,
            data=json.dumps(payload).encode(), headers=headers)
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req,
                                        timeout=timeout) as resp:
                data = resp.read()
                rid = resp.headers.get("X-SparkTpu-Replica")
                if rid:
                    self.affinity = rid
                tid = resp.headers.get("X-SparkTpu-Trace-Id")
                if tid:
                    self.last_trace_id = tid
                epoch = resp.headers.get("X-SparkTpu-Epoch")
                pred = resp.headers.get("X-SparkTpu-Predicted-Ms")
                self.last_query = {
                    "replica": rid,
                    "cache": resp.headers.get("X-Cache"),
                    "epoch": int(epoch) if epoch else None,
                    "query_id": resp.headers.get("X-Query-Id"),
                    "queue_wait_ms":
                        resp.headers.get("X-Queue-Wait-Ms"),
                    "trace_id": tid,
                    # SLO outcome: predicted vs (client-measured)
                    # actual latency, the policy that scheduled it,
                    # and reject/brownout status — None/False with
                    # SLO off, so consumers need no feature check
                    "slo_predicted_ms":
                        float(pred) if pred else None,
                    "slo_actual_ms": round(
                        (time.monotonic() - t0) * 1e3, 2),
                    "sched_policy": resp.headers.get(
                        "X-SparkTpu-Sched-Policy"),
                    "brownout": resp.headers.get("X-SparkTpu-Brownout"),
                    "slo_rejected": False,
                }
        except urllib.error.HTTPError as e:
            detail = json.loads(e.read())
            if e.code == 503 \
                    and detail.get("error") == "InfeasibleDeadline":
                # typed SLO reject: NOT retried here (same replica +
                # same deadline = same prediction); surfaces to the
                # caller with the prediction that condemned it
                from spark_tpu.slo.edf import InfeasibleDeadline

                rid = e.headers.get("X-SparkTpu-Replica")
                if rid:
                    self.affinity = rid
                self.last_query = {
                    "replica": rid,
                    "slo_predicted_ms": detail.get("predicted_ms"),
                    "slo_actual_ms": round(
                        (time.monotonic() - t0) * 1e3, 2),
                    "sched_policy": e.headers.get(
                        "X-SparkTpu-Sched-Policy"),
                    "brownout": e.headers.get("X-SparkTpu-Brownout"),
                    "slo_rejected": True,
                }
                raise InfeasibleDeadline(
                    float(detail.get("predicted_ms") or 0.0),
                    float(detail.get("deadline") or 0.0),
                    queue_ms=float(detail.get("queue_ms") or 0.0),
                    run_ms=float(detail.get("run_ms") or 0.0)) \
                    from None
            if e.code == 429:
                ra = e.headers.get("Retry-After") \
                    or detail.get("retry_after_s") or 0.0
                raise _RetryableHTTP(
                    f"429 {detail.get('message')}",
                    retry_after_s=float(ra)) from None
            if e.code == 409:
                # typed EPOCH_RETRY from an un-routed replica (direct
                # connection): immediately retryable with no backoff
                # floor — the fence is about staleness, not load; the
                # exhaustion error keeps the EPOCH_RETRY marker so it
                # stays typed for the chaos contract
                raise _RetryableHTTP(
                    f"409 {detail.get('message')}",
                    retry_after_s=0.0) from None
            msg = f"{detail.get('error')}: {detail.get('message')}"
            tb = detail.get("traceback")
            if tb:
                msg += f"\n--- server traceback ---\n{tb}"
            raise RuntimeError(msg) from None
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", None)
            if isinstance(reason, (socket.timeout, TimeoutError)):
                raise RuntimeError(
                    f"DEADLINE_EXCEEDED: connect request to "
                    f"{self.url + path} timed out after "
                    f"{self.timeout:g}s") from e
            if isinstance(reason, (ConnectionRefusedError,
                                   ConnectionResetError,
                                   ConnectionAbortedError,
                                   BrokenPipeError)):
                raise reason  # unwrapped: the retry loop classifies it
            raise
        except (socket.timeout, TimeoutError) as e:
            raise RuntimeError(
                f"DEADLINE_EXCEEDED: connect request to "
                f"{self.url + path} timed out after "
                f"{self.timeout:g}s") from e
        return pa.ipc.open_stream(io.BytesIO(data)).read_all()

    def sql(self, query: str, pool: Optional[str] = None,
            deadline_s: Optional[float] = None) -> pa.Table:
        payload = {"query": query}
        if deadline_s is not None:
            payload["deadline_s"] = float(deadline_s)
        return self._post("/sql", payload, pool=pool)

    def queries(self) -> dict:
        """Scheduler status + recent query lifecycle records."""
        import urllib.request

        with urllib.request.urlopen(self.url + "/queries",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def cancel(self, query_id: int) -> bool:
        import urllib.request

        req = urllib.request.Request(
            f"{self.url}/cancel/{int(query_id)}", data=b"{}",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return bool(json.loads(resp.read()).get("cancelled"))
        except Exception:
            return False

    def tables(self):
        import urllib.request

        with urllib.request.urlopen(self.url + "/tables",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def health(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.url + "/health",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def trace(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable) for a trace id
        (default: the last completed request's)."""
        import urllib.request

        tid = trace_id or self.last_trace_id
        if not tid:
            raise ValueError("no trace id: run a query first or pass "
                             "trace_id explicitly")
        with urllib.request.urlopen(f"{self.url}/trace/{tid}",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def _execute_plan(self, plan: dict) -> pa.Table:
        return self._post("/plan", {"plan": plan})

    def table(self, name: str) -> "RemoteDataFrame":
        """Lazy remote DataFrame over the typed plan protocol
        (connect/proto.py; reference: relations.proto + the pyspark
        connect client's plan builders)."""
        return RemoteDataFrame(self, {"op": "read", "table": name})


def col(name: str) -> dict:
    return {"e": "col", "name": name}


def lit(value, type_: str = None) -> dict:  # noqa: A002
    out = {"e": "lit", "value": value}
    if type_:
        out["type"] = type_
    return out


def fn(name: str, *args, distinct: bool = False) -> dict:
    out = {"e": "fn", "name": name,
           "args": [_e(a) for a in args]}
    if distinct:
        out["distinct"] = True
    return out


def _e(x) -> dict:
    if isinstance(x, dict):
        return x
    if isinstance(x, str):
        return col(x)
    return lit(x)


def _alias(e: dict, name: str) -> dict:
    return {"e": "alias", "name": name, "child": e}


class RemoteDataFrame:
    """Client-side lazy plan builder with NO engine imports — every
    method appends a typed relation node; collect() ships the JSON plan
    and reads back Arrow (the decoupled-client shape of
    pyspark.sql.connect.dataframe.DataFrame)."""

    def __init__(self, client: Client, plan: dict):
        self._client = client
        self._plan = plan

    def filter(self, condition: dict) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "filter", "condition": condition, "child": self._plan})

    def select(self, *exprs) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "project", "exprs": [_e(x) for x in exprs],
            "child": self._plan})

    def groupBy(self, *keys) -> "RemoteGroupedData":  # noqa: N802
        return RemoteGroupedData(self, [_e(k) for k in keys])

    def join(self, other: "RemoteDataFrame", on,
             how: str = "inner") -> "RemoteDataFrame":
        names = [on] if isinstance(on, str) else list(on)
        return RemoteDataFrame(self._client, {
            "op": "join", "how": how, "on": names,
            "left": self._plan, "right": other._plan})

    def sort(self, *exprs, ascending: bool = True) -> "RemoteDataFrame":
        orders = [{"expr": _e(x), "asc": bool(ascending)}
                  for x in exprs]
        return RemoteDataFrame(self._client, {
            "op": "sort", "orders": orders, "child": self._plan})

    orderBy = sort

    def limit(self, n: int, offset: int = 0) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "limit", "n": int(n), "offset": int(offset),
            "child": self._plan})

    def union(self, other: "RemoteDataFrame") -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "union", "left": self._plan, "right": other._plan})

    def distinct(self) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client,
                               {"op": "distinct", "child": self._plan})

    def toArrow(self) -> pa.Table:  # noqa: N802
        return self._client._execute_plan(self._plan)

    def collect(self):
        return self.toArrow().to_pylist()


class RemoteGroupedData:
    def __init__(self, df: RemoteDataFrame, keys):
        self._df = df
        self._keys = keys

    def agg(self, **aliased) -> RemoteDataFrame:
        aggs = [_alias(e, name) for name, e in aliased.items()]
        return RemoteDataFrame(self._df._client, {
            "op": "aggregate", "groupings": self._keys,
            "aggregates": aggs, "child": self._df._plan})

"""HTTP + Arrow-IPC result server (reference:
SparkConnectService.scala — ExecutePlan returning Arrow batches;
SparkExecuteStatementOperation.scala for the SQL-string entry)."""

from __future__ import annotations

import io
import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import pyarrow as pa

from spark_tpu import faults


class ConnectServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 heartbeat=None):
        self.session = session
        #: optional recovery.HeartbeatMonitor surfaced via GET /health;
        #: falls back to one attached to the session
        self.heartbeat = heartbeat if heartbeat is not None \
            else getattr(session, "heartbeat_monitor", None)
        #: the engine session is not thread-safe (LRU caches, catalog,
        #: conf) — queries execute serially, handlers stay concurrent
        #: for health/metadata (reference: thriftserver runs statements
        #: on a session-scoped executor too)
        self._exec_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # the client gave up (e.g. its deadline passed while
                    # the request hung) — nothing left to tell it
                    pass

            def do_GET(self):
                if self.path == "/tables":
                    body = json.dumps(
                        outer.session.catalog.listTables()).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/health":
                    hb = outer.heartbeat
                    body = json.dumps(
                        {"status": "ok",
                         "heartbeat": hb.status() if hb is not None
                         else None}).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path not in ("/sql", "/plan"):
                    self._send(404, b"not found", "text/plain")
                    return
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    faults.inject("connect.request", outer.session.conf)
                    req = json.loads(self.rfile.read(n))
                    with outer._exec_lock:
                        if self.path == "/sql":
                            df = outer.session.sql(req["query"])
                        else:
                            # typed logical-plan protocol (reference:
                            # relations.proto decoded by
                            # SparkConnectPlanner.scala:67)
                            from spark_tpu.api.dataframe import DataFrame
                            from spark_tpu.connect.proto import \
                                decode_plan

                            df = DataFrame(
                                outer.session,
                                decode_plan(req["plan"], outer.session))
                        tbl = df.toArrow()
                    sink = io.BytesIO()
                    with pa.ipc.new_stream(sink, tbl.schema) as w:
                        w.write_table(tbl)
                    self._send(200, sink.getvalue(),
                               "application/vnd.apache.arrow.stream")
                except Exception as e:  # error -> JSON with message
                    body = json.dumps(
                        {"error": type(e).__name__,
                         "message": str(e),
                         "traceback": traceback.format_exc()}).encode()
                    self._send(400, body, "application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ConnectServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def serve(session, host: str = "127.0.0.1", port: int = 15002,
          heartbeat=None) -> ConnectServer:
    """Start the server (default port mirrors Spark Connect's 15002)."""
    return ConnectServer(session, host, port,
                         heartbeat=heartbeat).start()


class Client:
    """Minimal client: sql() -> pyarrow.Table (reference client surface:
    pyspark.sql.connect.session.SparkSession.sql)."""

    def __init__(self, url: str, timeout: float = 60.0):
        self.url = url.rstrip("/")
        #: per-request deadline — urllib otherwise blocks forever on a
        #: hung server
        self.timeout = float(timeout)

    def _post(self, path: str, payload: dict) -> pa.Table:
        import socket
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                data = resp.read()
        except urllib.error.HTTPError as e:
            detail = json.loads(e.read())
            msg = f"{detail.get('error')}: {detail.get('message')}"
            tb = detail.get("traceback")
            if tb:
                msg += f"\n--- server traceback ---\n{tb}"
            raise RuntimeError(msg) from None
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None),
                          (socket.timeout, TimeoutError)):
                raise RuntimeError(
                    f"DEADLINE_EXCEEDED: connect request to "
                    f"{self.url + path} timed out after "
                    f"{self.timeout:g}s") from e
            raise
        except (socket.timeout, TimeoutError) as e:
            raise RuntimeError(
                f"DEADLINE_EXCEEDED: connect request to "
                f"{self.url + path} timed out after "
                f"{self.timeout:g}s") from e
        return pa.ipc.open_stream(io.BytesIO(data)).read_all()

    def sql(self, query: str) -> pa.Table:
        return self._post("/sql", {"query": query})

    def tables(self):
        import urllib.request

        with urllib.request.urlopen(self.url + "/tables",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def health(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.url + "/health",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def _execute_plan(self, plan: dict) -> pa.Table:
        return self._post("/plan", {"plan": plan})

    def table(self, name: str) -> "RemoteDataFrame":
        """Lazy remote DataFrame over the typed plan protocol
        (connect/proto.py; reference: relations.proto + the pyspark
        connect client's plan builders)."""
        return RemoteDataFrame(self, {"op": "read", "table": name})


def col(name: str) -> dict:
    return {"e": "col", "name": name}


def lit(value, type_: str = None) -> dict:  # noqa: A002
    out = {"e": "lit", "value": value}
    if type_:
        out["type"] = type_
    return out


def fn(name: str, *args, distinct: bool = False) -> dict:
    out = {"e": "fn", "name": name,
           "args": [_e(a) for a in args]}
    if distinct:
        out["distinct"] = True
    return out


def _e(x) -> dict:
    if isinstance(x, dict):
        return x
    if isinstance(x, str):
        return col(x)
    return lit(x)


def _alias(e: dict, name: str) -> dict:
    return {"e": "alias", "name": name, "child": e}


class RemoteDataFrame:
    """Client-side lazy plan builder with NO engine imports — every
    method appends a typed relation node; collect() ships the JSON plan
    and reads back Arrow (the decoupled-client shape of
    pyspark.sql.connect.dataframe.DataFrame)."""

    def __init__(self, client: Client, plan: dict):
        self._client = client
        self._plan = plan

    def filter(self, condition: dict) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "filter", "condition": condition, "child": self._plan})

    def select(self, *exprs) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "project", "exprs": [_e(x) for x in exprs],
            "child": self._plan})

    def groupBy(self, *keys) -> "RemoteGroupedData":  # noqa: N802
        return RemoteGroupedData(self, [_e(k) for k in keys])

    def join(self, other: "RemoteDataFrame", on,
             how: str = "inner") -> "RemoteDataFrame":
        names = [on] if isinstance(on, str) else list(on)
        return RemoteDataFrame(self._client, {
            "op": "join", "how": how, "on": names,
            "left": self._plan, "right": other._plan})

    def sort(self, *exprs, ascending: bool = True) -> "RemoteDataFrame":
        orders = [{"expr": _e(x), "asc": bool(ascending)}
                  for x in exprs]
        return RemoteDataFrame(self._client, {
            "op": "sort", "orders": orders, "child": self._plan})

    orderBy = sort

    def limit(self, n: int, offset: int = 0) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "limit", "n": int(n), "offset": int(offset),
            "child": self._plan})

    def union(self, other: "RemoteDataFrame") -> "RemoteDataFrame":
        return RemoteDataFrame(self._client, {
            "op": "union", "left": self._plan, "right": other._plan})

    def distinct(self) -> "RemoteDataFrame":
        return RemoteDataFrame(self._client,
                               {"op": "distinct", "child": self._plan})

    def toArrow(self) -> pa.Table:  # noqa: N802
        return self._client._execute_plan(self._plan)

    def collect(self):
        return self.toArrow().to_pylist()


class RemoteGroupedData:
    def __init__(self, df: RemoteDataFrame, keys):
        self._df = df
        self._keys = keys

    def agg(self, **aliased) -> RemoteDataFrame:
        aggs = [_alias(e, name) for name, e in aliased.items()]
        return RemoteDataFrame(self._df._client, {
            "op": "aggregate", "groupings": self._keys,
            "aggregates": aggs, "child": self._df._plan})

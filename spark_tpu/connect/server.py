"""HTTP + Arrow-IPC result server (reference:
SparkConnectService.scala — ExecutePlan returning Arrow batches;
SparkExecuteStatementOperation.scala for the SQL-string entry)."""

from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import pyarrow as pa


class ConnectServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self.session = session
        #: the engine session is not thread-safe (LRU caches, catalog,
        #: conf) — queries execute serially, handlers stay concurrent
        #: for health/metadata (reference: thriftserver runs statements
        #: on a session-scoped executor too)
        self._exec_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/tables":
                    body = json.dumps(
                        outer.session.catalog.listTables()).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/health":
                    self._send(200, b"ok", "text/plain")
                else:
                    self._send(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path != "/sql":
                    self._send(404, b"not found", "text/plain")
                    return
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    req = json.loads(self.rfile.read(n))
                    with outer._exec_lock:
                        tbl = outer.session.sql(req["query"]).toArrow()
                    sink = io.BytesIO()
                    with pa.ipc.new_stream(sink, tbl.schema) as w:
                        w.write_table(tbl)
                    self._send(200, sink.getvalue(),
                               "application/vnd.apache.arrow.stream")
                except Exception as e:  # error -> JSON with message
                    body = json.dumps(
                        {"error": type(e).__name__,
                         "message": str(e)}).encode()
                    self._send(400, body, "application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ConnectServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def serve(session, host: str = "127.0.0.1",
          port: int = 15002) -> ConnectServer:
    """Start the server (default port mirrors Spark Connect's 15002)."""
    return ConnectServer(session, host, port).start()


class Client:
    """Minimal client: sql() -> pyarrow.Table (reference client surface:
    pyspark.sql.connect.session.SparkSession.sql)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def sql(self, query: str) -> pa.Table:
        import urllib.request

        req = urllib.request.Request(
            self.url + "/sql",
            data=json.dumps({"query": query}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req) as resp:
                data = resp.read()
        except urllib.error.HTTPError as e:
            detail = json.loads(e.read())
            raise RuntimeError(
                f"{detail.get('error')}: {detail.get('message')}") from None
        return pa.ipc.open_stream(io.BytesIO(data)).read_all()

    def tables(self):
        import urllib.request

        with urllib.request.urlopen(self.url + "/tables") as resp:
            return json.loads(resp.read())

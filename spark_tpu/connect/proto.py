"""Typed logical-plan protocol for decoupled clients (reference:
connector/connect/common/src/main/protobuf/spark/connect/relations.proto
+ expressions.proto, decoded by SparkConnectPlanner.scala:67).

The wire format is plain JSON (no protoc dependency in clients): a
relation tree of ``{"op": ...}`` nodes over ``{"e": ...}`` expression
nodes. The client side (connect.server.Client.dataframe) builds these
dicts with no engine imports; the server decodes them into the SAME
logical plan nodes SQL parsing produces, so every optimizer rule and
physical path applies identically.

Relations: read, sql, project, filter, aggregate, join (USING names),
sort, limit, union, distinct.
Expressions: col, lit (typed), alias, bin (arith/cmp/bool), not,
isnull, fn (function-registry call, aggregates with distinct).
"""

from __future__ import annotations

import datetime
from typing import Any, Dict

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L

_BIN_ARITH = {"+", "-", "*", "/", "%"}
_BIN_CMP = {"==", "!=", "<", "<=", ">", ">="}

_AGG_FNS = {
    "sum": E.Sum, "avg": E.Avg, "min": E.Min, "max": E.Max,
}

_TYPES = {
    "int": T.INT64, "long": T.INT64, "double": T.FLOAT64,
    "string": T.STRING, "boolean": T.BOOLEAN, "date": T.DATE,
    "timestamp": T.TIMESTAMP,
}

#: Scalar functions a remote client may call by name. An explicit
#: allowlist, not getattr-on-module: the function registry is the wire
#: protocol surface, and module attributes that happen to be callable
#: (helpers, imports, session-side constructors) are not part of it.
_SCALAR_FNS = frozenset({
    "abs", "coalesce", "isnull", "isnotnull", "floor", "ceil", "sqrt",
    "exp", "log", "log2", "log10", "signum", "round", "pow", "pmod",
    "degrees", "radians", "negative", "positive",
    "upper", "lower", "trim", "ltrim", "rtrim", "length", "initcap",
    "reverse", "repeat", "lpad", "rpad", "translate", "concat",
    "concat_ws", "substring", "startswith", "endswith", "contains",
    "like", "rlike", "regexp_extract", "regexp_replace", "replace",
    "split",
    "year", "month", "dayofmonth", "quarter", "dayofweek", "weekday",
    "dayofyear", "hour", "minute", "second", "add_months", "date_add",
    "date_sub", "datediff", "months_between", "to_date", "date_trunc",
    "last_day",
    "greatest", "least", "ifnull", "nvl2", "nullif",
})


def decode_expr(obj: Dict[str, Any]) -> E.Expression:
    kind = obj.get("e")
    if kind == "col":
        return E.Col(obj["name"])
    if kind == "lit":
        v = obj.get("value")
        t = obj.get("type")
        if t == "date" and isinstance(v, str):
            v = datetime.date.fromisoformat(v)
        dtype = _TYPES.get(t) if t else None
        return E.Literal(v, dtype) if dtype is not None else E.Literal(v)
    if kind == "alias":
        return E.Alias(decode_expr(obj["child"]), obj["name"])
    if kind == "bin":
        op = obj["op"]
        lhs = decode_expr(obj["left"])
        rhs = decode_expr(obj["right"])
        if op in _BIN_ARITH:
            return E.Arith(op, lhs, rhs)
        if op in _BIN_CMP:
            return E.Cmp(op, lhs, rhs)
        if op == "and":
            return E.And(lhs, rhs)
        if op == "or":
            return E.Or(lhs, rhs)
        raise ValueError(f"unknown binary op {op!r}")
    if kind == "not":
        return E.Not(decode_expr(obj["child"]))
    if kind == "isnull":
        return E.IsNull(decode_expr(obj["child"]))
    if kind == "fn":
        name = obj["name"].lower()
        args = [decode_expr(a) for a in obj.get("args", [])]
        if name == "count":
            child = args[0] if args else None
            return E.Count(child, distinct=bool(obj.get("distinct")))
        if name in _AGG_FNS:
            cls = _AGG_FNS[name]
            if name in ("min", "max"):
                return cls(args[0])
            return cls(args[0], distinct=bool(obj.get("distinct")))
        from spark_tpu.api import functions as F

        if name not in _SCALAR_FNS:
            raise ValueError(f"unknown function {obj['name']!r}")
        return getattr(F, name)(*args)
    raise ValueError(f"unknown expression node {kind!r}")


def decode_plan(obj: Dict[str, Any], session) -> L.LogicalPlan:
    op = obj.get("op")
    if op == "read":
        df = session.table(obj["table"])
        return df._plan
    if op == "sql":
        return session.sql(obj["query"])._plan
    if op == "project":
        return L.Project(tuple(decode_expr(e) for e in obj["exprs"]),
                         decode_plan(obj["child"], session))
    if op == "filter":
        return L.Filter(decode_expr(obj["condition"]),
                        decode_plan(obj["child"], session))
    if op == "aggregate":
        groupings = tuple(decode_expr(e) for e in obj.get("groupings",
                                                          []))
        aggs = tuple(decode_expr(e) for e in obj["aggregates"])
        return L.Aggregate(groupings, groupings + aggs,
                           decode_plan(obj["child"], session))
    if op == "join":
        left = decode_plan(obj["left"], session)
        right = decode_plan(obj["right"], session)
        names = obj.get("on", [])
        keys = tuple(E.Col(n) for n in names)
        how = obj.get("how", "inner")
        joined = L.Join(left, right, how, keys, keys)
        # USING semantics: key columns appear once; output names map
        # positionally onto each side's schema. For a RIGHT join the
        # key values must come from the RIGHT side — unmatched right
        # rows carry NULL in the left region — surfaced under the
        # left's (un-suffixed) output name. For a FULL join either
        # region may hold the NULL, so the key is
        # coalesce(left_key, right_key).
        if names and how in ("inner", "left", "right", "full"):
            ln = len(left.schema.names)
            lout = list(joined.schema.names)[:ln]
            rout = list(joined.schema.names)[ln:]
            rmap = dict(zip(right.schema.names, rout))
            exprs = []
            for o, src in zip(lout, left.schema.names):
                if src in names and how == "right":
                    exprs.append(E.Alias(E.Col(rmap[src]), o))
                elif src in names and how == "full":
                    exprs.append(E.Alias(
                        E.Coalesce((E.Col(o), E.Col(rmap[src]))), o))
                else:
                    exprs.append(E.Col(o))
            exprs.extend(E.Col(o) for o, src in zip(rout, right.schema.names)
                         if src not in names)
            return L.Project(tuple(exprs), joined)
        return joined
    if op == "sort":
        orders = tuple(
            E.SortOrder(decode_expr(o["expr"]),
                        bool(o.get("asc", True)),
                        o.get("nulls_first"))
            for o in obj["orders"])
        return L.Sort(orders, decode_plan(obj["child"], session))
    if op == "limit":
        return L.Limit(int(obj["n"]),
                       decode_plan(obj["child"], session),
                       offset=int(obj.get("offset", 0)))
    if op == "union":
        return L.Union(decode_plan(obj["left"], session),
                       decode_plan(obj["right"], session))
    if op == "distinct":
        return L.Distinct(decode_plan(obj["child"], session))
    raise ValueError(f"unknown relation node {op!r}")

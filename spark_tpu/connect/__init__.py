"""Connect server — the Spark Connect / thriftserver analogue
(reference: connector/connect/.../service/SparkConnectService.scala,
sql/hive-thriftserver/.../SparkExecuteStatementOperation.scala).

The reference speaks gRPC+protobuf (Connect) or the HiveServer2 thrift
protocol; both ultimately execute SQL and stream Arrow batches back.
Here the wire is plain HTTP + Arrow IPC streams — no JVM, no thrift,
and any language with an HTTP client and an Arrow reader can talk to
the TPU engine:

    POST /sql  {"query": "select ..."}  ->  arrow IPC stream
    GET  /tables                        ->  JSON list

Server: `spark_tpu.connect.serve(spark, port)`. Client:
`spark_tpu.connect.Client("http://host:port").sql("...")` -> pyarrow
Table."""

from spark_tpu.connect.server import Client, ConnectServer, serve

__all__ = ["ConnectServer", "Client", "serve"]

"""SQL parser: text -> logical plans.

Hand-written tokenizer + Pratt expression parser + statement builder,
covering the dialect the engine executes: SELECT/FROM/WHERE/GROUP BY/
HAVING/ORDER BY/LIMIT, explicit and comma joins, subqueries (FROM,
scalar, IN, EXISTS — with correlation via OuterRef), CASE, BETWEEN,
IN, LIKE, CAST, EXTRACT, date/interval literals, set operations, and
CREATE/DROP VIEW. The reference parses with a 1,819-line ANTLR grammar
(reference: sql/catalyst/src/main/antlr4/.../SqlBaseParser.g4:1 +
parser/AstBuilder.scala); name resolution here happens during parsing
against the FROM-clause scope, folding the Analyzer's resolution tier
(reference: analysis/Analyzer.scala:188) into plan construction.
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from spark_tpu import types as T
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.sql.ddl import parse_type

# ---- tokenizer --------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>"[^"]*"|`[^`]*`)
  | (?P<op><>|!=|>=|<=|\|\||->|[=<>+\-*/%(),.;\[\]])
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
""", re.VERBOSE | re.DOTALL)


@dataclass
class Token:
    kind: str  # 'num' | 'str' | 'id' | 'qid' | 'op' | 'eof'
    value: str
    pos: int

    @property
    def upper(self) -> str:
        return self.value.upper()


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SQLParseError(
                f"unexpected character {text[pos]!r} at {pos}: "
                f"...{text[max(0, pos - 20):pos + 20]}...")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "qid":
            val = val[1:-1]
        out.append(Token(kind, val, m.start()))
    out.append(Token("eof", "", n))
    return out


class SQLParseError(ValueError):
    pass


_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "ON",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "OUTER", "UNION",
    "INTERSECT", "EXCEPT", "AS", "AND", "OR", "NOT", "BY", "ASC", "DESC",
    "THEN", "WHEN", "ELSE", "END", "USING", "SEMI", "ANTI", "NULLS",
    "LATERAL",
}


# ---- name resolution scope --------------------------------------------------


class Scope:
    """FROM-clause namespace: per-alias source->output column mapping.

    Join output names deduplicate with '#2' suffixes (logical.Join.schema
    semantics); the scope tracks, for every relation in the FROM clause,
    what each of its columns is called in the joined output, so
    ``alias.col`` and bare ``col`` resolve to output Col names."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.entries: List[Tuple[Optional[str], List[Tuple[str, str]]]] = []
        self.parent = parent

    def add_relation(self, alias: Optional[str],
                     src_names: Sequence[str]) -> List[str]:
        """Register a relation; returns the OUTPUT names its columns get
        after join-dedup against everything already in scope."""
        seen = {out for _, cols in self.entries for _, out in cols}
        mapping = []
        for n in src_names:
            out = n
            while out in seen:
                out = out + "#2"
            seen.add(out)
            mapping.append((n, out))
        self.entries.append((alias.lower() if alias else None, mapping))
        return [out for _, out in mapping]

    def resolve(self, qualifier: Optional[str], name: str) -> Optional[str]:
        name_l = name.lower()
        if qualifier is not None:
            q = qualifier.lower()
            for alias, cols in self.entries:
                if alias == q:
                    for src, out in cols:
                        if src.lower() == name_l:
                            return out
            return None
        hits = []
        for _, cols in self.entries:
            for src, out in cols:
                if src.lower() == name_l:
                    hits.append(out)
        if len(hits) > 1:
            raise SQLParseError(f"ambiguous column reference {name!r}")
        if not hits and not name_l.endswith("#keys"):
            # a MAP column decomposes into '<m>#keys'/'<m>#vals'
            # (types.MapType); a bare reference resolves to the keys
            # component, the canonical map handle
            return self.resolve(qualifier, name + "#keys")
        return hits[0] if hits else None

    def all_output_names(self) -> List[str]:
        return [out for _, cols in self.entries for _, out in cols]

    def relation_outputs(self, alias: str) -> Optional[List[str]]:
        q = alias.lower()
        for a, cols in self.entries:
            if a == q:
                return [out for _, out in cols]
        return None


# ---- expression parser (Pratt) ----------------------------------------------

Resolver = Callable[[Optional[str], str], E.Expression]


class _ExprParser:
    def __init__(self, tokens: List[Token], pos: int, resolver: Resolver,
                 subquery_parser=None):
        self.toks = tokens
        self.pos = pos
        self.resolve = resolver
        self.subquery_parser = subquery_parser  # parses ( SELECT ... )

    # -- token helpers -------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.pos + ahead, len(self.toks) - 1)]

    def next(self) -> Token:  # noqa: A003
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def accept(self, *values: str) -> Optional[Token]:
        t = self.peek()
        if t.kind in ("id", "op") and t.upper in values:
            return self.next()
        return None

    def expect(self, value: str) -> Token:
        t = self.next()
        if t.upper != value:
            raise SQLParseError(
                f"expected {value!r}, found {t.value!r} at {t.pos}")
        return t

    def at_keyword(self, *values: str) -> bool:
        t = self.peek()
        return t.kind == "id" and t.upper in values

    # -- grammar -------------------------------------------------------------

    def parse(self) -> E.Expression:
        return self.parse_or()

    def parse_or(self) -> E.Expression:
        left = self.parse_and()
        while self.accept("OR"):
            left = E.Or(left, self.parse_and())
        return left

    def parse_and(self) -> E.Expression:
        left = self.parse_not()
        while self.accept("AND"):
            left = E.And(left, self.parse_not())
        return left

    def parse_not(self) -> E.Expression:
        if self.accept("NOT"):
            inner = self.parse_not()
            if isinstance(inner, E.Exists):
                return E.Exists(inner.plan, not inner.negated)
            return E.Not(inner)
        return self.parse_predicate()

    def parse_predicate(self) -> E.Expression:
        if self.at_keyword("EXISTS"):
            nt = self.peek(2)
            if nt.kind == "id" and nt.upper == "SELECT":
                self.next()
                self.expect("(")
                plan = self.subquery_parser(self)
                self.expect(")")
                return E.Exists(plan)
            # exists(array, x -> pred): the higher-order function form
            name_tok = self.next()
            return self._parse_function_inner(name_tok)
        left = self.parse_additive()
        negated = bool(self.accept("NOT"))
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "==", "<>", "!=", "<", "<=",
                                          ">", ">=") and not negated:
            op = self.next().value
            op = {"=": "==", "<>": "!="}.get(op, op)
            right = self.parse_additive()
            return E.Cmp(op, left, right)
        if self.accept("BETWEEN"):
            lo = self.parse_additive()
            self.expect("AND")
            hi = self.parse_additive()
            e: E.Expression = E.And(E.Cmp(">=", left, lo),
                                    E.Cmp("<=", left, hi))
            return E.Not(e) if negated else e
        if self.accept("IN"):
            self.expect("(")
            if self.at_keyword("SELECT", "WITH"):
                plan = self.subquery_parser(self)
                self.expect(")")
                return E.InSubquery(left, plan, negated)
            values = [self._literal_value(self.parse_additive())]
            while self.accept(","):
                values.append(self._literal_value(self.parse_additive()))
            self.expect(")")
            e = E.In(left, tuple(values))
            return E.Not(e) if negated else e
        if self.accept("LIKE"):
            pat = self.next()
            if pat.kind != "str":
                raise SQLParseError(f"LIKE needs a string pattern at {pat.pos}")
            e = E.Like(left, _unquote(pat.value))
            return E.Not(e) if negated else e
        if self.accept("IS"):
            neg2 = bool(self.accept("NOT"))
            self.expect("NULL")
            e = E.IsNull(left)
            return E.Not(e) if (neg2 != negated) else e
        if negated:
            raise SQLParseError(
                f"dangling NOT before {self.peek().value!r}")
        return left

    def parse_additive(self) -> E.Expression:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                right = self.parse_multiplicative()
                left = self._date_arith(t.value, left, right)
            elif t.kind == "op" and t.value == "||":
                self.next()
                right = self.parse_multiplicative()
                left = E.Concat((left, right))
            else:
                return left

    def _date_arith(self, op: str, left: E.Expression,
                    right: E.Expression) -> E.Expression:
        """Fold interval literals into date arithmetic at parse time."""
        if isinstance(right, _Interval):
            if right.months:
                months = right.months if op == "+" else -right.months
                base = E.AddMonths(left, months)
            else:
                base = left
            if right.days:
                base = E.Arith(op, base, E.Literal(right.days))
            return base
        if isinstance(left, _Interval):
            raise SQLParseError("interval must be the right operand")
        return E.Arith(op, left, right)

    def parse_multiplicative(self) -> E.Expression:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = E.Arith(t.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> E.Expression:
        t = self.peek()
        if t.kind == "op" and t.value == "-":
            self.next()
            return E.Neg(self.parse_unary())
        if t.kind == "op" and t.value == "+":
            self.next()
            return self.parse_unary()
        return self.parse_primary()

    # -- primaries -----------------------------------------------------------

    def parse_primary(self) -> E.Expression:
        e = self._parse_primary_base()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value == "[":
                # x[i]: 0-based array item / map key lookup (reference:
                # GetArrayItem / GetMapValue, complexTypeExtractors.scala)
                self.next()
                key = self.parse()
                self.expect("]")
                e = E.ElementAt(e, key, sql_subscript=True)
            else:
                break
        return e

    def _parse_primary_base(self) -> E.Expression:
        t = self.next()
        if t.kind == "num":
            text = t.value
            if "." in text or "e" in text.lower():
                return E.Literal(float(text))
            return E.Literal(int(text))
        if t.kind == "str":
            return E.Literal(_unquote(t.value))
        if t.kind == "op" and t.value == "(":
            if self.at_keyword("SELECT", "WITH"):
                plan = self.subquery_parser(self)
                self.expect(")")
                return E.ScalarSubquery(plan)
            e = self.parse()
            if self.peek().kind == "op" and self.peek().value == ",":
                items = [e]
                while self.accept(","):
                    items.append(self.parse())
                self.expect(")")
                return E.TupleExpr(tuple(items))
            self.expect(")")
            return e
        if t.kind in ("id", "qid"):
            return self._parse_identifier(t)
        raise SQLParseError(f"unexpected token {t.value!r} at {t.pos}")

    def _parse_identifier(self, t: Token) -> E.Expression:
        u = t.upper if t.kind == "id" else None
        if u == "NULL":
            return E.Literal(None, T.BOOLEAN)
        if u == "TRUE":
            return E.Literal(True)
        if u == "FALSE":
            return E.Literal(False)
        if u == "DATE" and self.peek().kind == "str":
            s = _unquote(self.next().value)
            return E.Literal(datetime.date.fromisoformat(s))
        if u == "TIMESTAMP" and self.peek().kind == "str":
            s = _unquote(self.next().value)
            return E.Literal(datetime.datetime.fromisoformat(s))
        if u == "INTERVAL":
            return self._parse_interval()
        if u == "CASE":
            return self._parse_case()
        if u == "CAST":
            self.expect("(")
            e = self.parse()
            self.expect("AS")
            type_toks = []
            depth = 0
            while True:
                nt = self.peek()
                if nt.kind == "op" and nt.value == "(":
                    depth += 1
                if nt.kind == "op" and nt.value == ")":
                    if depth == 0:
                        break
                    depth -= 1
                type_toks.append(self.next().value)
            self.expect(")")
            return E.Cast(e, parse_type(" ".join(type_toks)))
        if u == "EXTRACT":
            self.expect("(")
            part = self.next().value.lower()
            self.expect("FROM")
            e = self.parse()
            self.expect(")")
            return E.ExtractDatePart(part, e)
        # function call?
        nxt = self.peek()
        if nxt.kind == "op" and nxt.value == "(":
            return self._parse_function(t)
        # [qualifier .] column
        if nxt.kind == "op" and nxt.value == "." and \
                self.peek(1).kind in ("id", "qid"):
            self.next()
            col = self.next()
            return self.resolve(t.value, col.value)
        return self.resolve(None, t.value)

    def _parse_interval(self) -> "_Interval":
        t = self.next()
        if t.kind == "str":
            qty = int(_unquote(t.value))
        elif t.kind == "num":
            qty = int(t.value)
        else:
            raise SQLParseError(f"bad interval quantity at {t.pos}")
        unit = self.next().upper.rstrip("S")
        if unit == "YEAR":
            return _Interval(months=12 * qty)
        if unit == "MONTH":
            return _Interval(months=qty)
        if unit == "DAY":
            return _Interval(days=qty)
        if unit == "WEEK":
            return _Interval(days=7 * qty)
        raise SQLParseError(f"unsupported interval unit {unit!r}")

    def _parse_case(self) -> E.Expression:
        branches = []
        operand = None
        if not self.at_keyword("WHEN"):
            operand = self.parse()
        while self.accept("WHEN"):
            cond = self.parse()
            if operand is not None:
                cond = E.Cmp("==", operand, cond)
            self.expect("THEN")
            val = self.parse()
            branches.append((cond, val))
        else_v = None
        if self.accept("ELSE"):
            else_v = self.parse()
        self.expect("END")
        return E.Case(tuple(branches), else_v)

    _AGG_FNS = {"SUM": E.Sum, "AVG": E.Avg, "MIN": E.Min, "MAX": E.Max}

    def _parse_function(self, name_tok: Token) -> E.Expression:
        e = self._parse_function_inner(name_tok)
        if self.at_keyword("OVER"):
            self.next()
            return self._parse_window_spec(e)
        if isinstance(e, (E.RowNumber, E.Rank, E.NTile, E.LagLead)):
            raise SQLParseError(
                f"{name_tok.value} requires an OVER clause at "
                f"{name_tok.pos}")
        return e

    def _parse_window_spec(self, func: E.Expression) -> E.WindowExpr:
        """OVER ( [PARTITION BY ...] [ORDER BY ...] [ROWS|RANGE BETWEEN
        bound AND bound] ) (reference grammar: SqlBaseParser.g4
        windowSpec)."""
        self.expect("(")
        partition: List[E.Expression] = []
        orders: List[E.SortOrder] = []
        frame = None
        if self.at_keyword("PARTITION"):
            self.next()
            self.expect("BY")
            partition.append(self.parse())
            while self.accept(","):
                partition.append(self.parse())
        if self.at_keyword("ORDER"):
            self.next()
            self.expect("BY")
            while True:
                e = self.parse()
                asc = True
                if self.accept("DESC"):
                    asc = False
                else:
                    self.accept("ASC")
                nulls_first = None
                if self.accept("NULLS"):
                    nulls_first = self.next().upper == "FIRST"
                orders.append(E.SortOrder(e, asc, nulls_first))
                if not self.accept(","):
                    break
        if self.at_keyword("ROWS", "RANGE"):
            mode = self.next().upper.lower()

            def bound() -> Tuple[str, Optional[int]]:
                if self.accept("UNBOUNDED"):
                    side = self.next()
                    if side.upper not in ("PRECEDING", "FOLLOWING"):
                        raise SQLParseError(
                            f"expected PRECEDING or FOLLOWING at "
                            f"{side.pos}: {side.value!r}")
                    return side.upper, None
                if self.accept("CURRENT"):
                    self.expect("ROW")
                    return "CURRENT", 0
                n = self._int_literal()
                side = self.next()
                if side.upper not in ("PRECEDING", "FOLLOWING"):
                    raise SQLParseError(
                        f"expected PRECEDING or FOLLOWING at "
                        f"{side.pos}: {side.value!r}")
                return side.upper, n

            if self.accept("BETWEEN"):
                s_side, s_n = bound()
                self.expect("AND")
                e_side, e_n = bound()
            else:
                s_side, s_n = bound()
                e_side, e_n = "CURRENT", 0
            start = None if s_n is None else (
                -s_n if s_side == "PRECEDING" else s_n)
            end = None if e_n is None else (
                -e_n if e_side == "PRECEDING" else e_n)
            frame = (mode, start, end)
        self.expect(")")
        return E.WindowExpr(func, tuple(partition), tuple(orders), frame)

    def _parse_function_inner(self, name_tok: Token) -> E.Expression:
        name = name_tok.upper
        self.expect("(")
        if name in ("ROW_NUMBER", "RANK", "DENSE_RANK"):
            self.expect(")")
            if name == "ROW_NUMBER":
                return E.RowNumber()
            return E.Rank(dense=(name == "DENSE_RANK"))
        if name == "NTILE":
            n = self._int_literal()
            self.expect(")")
            return E.NTile(n)
        if name in ("LAG", "LEAD"):
            e = self.parse()
            offset, default = 1, None
            if self.accept(","):
                offset = self._int_literal()
                if self.accept(","):
                    default = self.parse()
            self.expect(")")
            return E.LagLead(e, offset, default, lead=(name == "LEAD"))
        if name == "COUNT":
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                self.expect(")")
                return E.Count(None)
            distinct = bool(self.accept("DISTINCT"))
            e = self.parse()
            self.expect(")")
            return E.Count(e, distinct=distinct)
        if name in self._AGG_FNS:
            distinct = bool(self.accept("DISTINCT"))
            e = self.parse()
            self.expect(")")
            cls = self._AGG_FNS[name]
            if name in ("MIN", "MAX"):
                return cls(e)
            return cls(e, distinct=distinct)
        if name in ("STDDEV", "STDDEV_SAMP", "STDDEV_POP", "VARIANCE",
                    "VAR_SAMP", "VAR_POP"):
            e = self.parse()
            self.expect(")")
            kind = {"STDDEV": "stddev_samp", "VARIANCE": "var_samp"}.get(
                name, name.lower())
            return E.StddevVariance(kind, e)
        if name == "SUBSTRING" or name == "SUBSTR":
            e = self.parse()
            if self.accept("FROM"):
                pos = self._int_literal()
                self.expect("FOR")
                length = self._int_literal()
            else:
                self.expect(",")
                pos = self._int_literal()
                if self.accept(","):
                    length = self._int_literal()
                else:  # substr(s, pos): to the end of the string
                    length = 1 << 30
            self.expect(")")
            return E.Substring(e, pos, length)
        if name == "COALESCE":
            args = [self.parse()]
            while self.accept(","):
                args.append(self.parse())
            self.expect(")")
            return E.Coalesce(tuple(args))
        if name in ("YEAR", "MONTH", "DAY", "DAYOFMONTH"):
            e = self.parse()
            self.expect(")")
            part = {"DAYOFMONTH": "day"}.get(name, name.lower())
            return E.ExtractDatePart(part, e)
        if name == "ABS":
            e = self.parse()
            self.expect(")")
            return E.Abs(e)
        if name in ("FLOOR", "CEIL", "CEILING", "SQRT", "EXP", "LN",
                    "LOG10", "SIGN"):
            e = self.parse()
            self.expect(")")
            op = {"CEILING": "ceil"}.get(name, name.lower())
            return E.UnaryMath(op, e)
        if name == "ROUND":
            e = self.parse()
            scale = 0
            if self.accept(","):
                scale = self._int_literal()
            self.expect(")")
            return E.Round(e, scale)
        if name in ("POWER", "POW"):
            a = self.parse()
            self.expect(",")
            b = self.parse()
            self.expect(")")
            return E.Pow(a, b)
        if name in ("UPPER", "LOWER", "TRIM", "LTRIM", "RTRIM"):
            e = self.parse()
            self.expect(")")
            return E.StringTransform(name.lower(), e)
        if name in ("LENGTH", "LEN", "CHAR_LENGTH"):
            e = self.parse()
            self.expect(")")
            return E.StrLength(e)
        if name == "REGEXP_EXTRACT":
            e = self.parse()
            self.expect(",")
            pat = self._str_literal()
            group = 1
            if self.accept(","):
                group = self._int_literal()
            self.expect(")")
            return E.RegexpExtract(e, pat, group)
        if name == "REGEXP_REPLACE":
            e = self.parse()
            self.expect(",")
            pat = self._str_literal()
            self.expect(",")
            rep = self._str_literal()
            self.expect(")")
            return E.RegexpReplace(e, pat, rep)
        if name == "REGEXP_LIKE":
            e = self.parse()
            self.expect(",")
            pat = self._str_literal()
            self.expect(")")
            return E.RegexpLike(e, pat)
        if name == "DATE_TRUNC":
            unit = self._str_literal().lower()
            self.expect(",")
            e = self.parse()
            self.expect(")")
            return E.DateTrunc(unit, e)
        if name == "LAST_DAY":
            e = self.parse()
            self.expect(")")
            return E.LastDay(e)
        if name == "APPROX_COUNT_DISTINCT":
            e = self.parse()
            if self.accept(","):
                self.parse()  # rsd accepted, unused (result is exact)
            self.expect(")")
            return E.Count(e, distinct=True)
        if name == "NULLIF":
            a = self.parse()
            self.expect(",")
            b = self.parse()
            self.expect(")")
            return E.Case(((E.Cmp("==", a, b), E.NullOf(a)),), a)
        if name == "CONCAT":
            args = [self.parse()]
            while self.accept(","):
                args.append(self.parse())
            self.expect(")")
            return E.Concat(tuple(args))
        if name in ("DATE_ADD", "DATE_SUB"):
            e = self.parse()
            self.expect(",")
            d = self._int_literal()
            self.expect(")")
            op = "+" if name == "DATE_ADD" else "-"
            return E.Arith(op, e, E.Literal(d))
        if name == "ADD_MONTHS":
            e = self.parse()
            self.expect(",")
            m = self._int_literal()
            self.expect(")")
            return E.AddMonths(e, m)
        if name in ("LPAD", "RPAD"):
            e = self.parse()
            self.expect(",")
            ln = self._int_literal()
            pad = " "
            if self.accept(","):
                pad = self._str_literal()
            self.expect(")")
            return E.StringTransform(name.lower(), e, (ln, pad))
        if name == "REPEAT":
            e = self.parse()
            self.expect(",")
            nrep = self._int_literal()
            self.expect(")")
            return E.StringTransform("repeat", e, (nrep,))
        if name == "REPLACE":
            e = self.parse()
            self.expect(",")
            find = self._str_literal()
            self.expect(",")
            repl = self._str_literal()
            self.expect(")")
            import re as _re

            # REPLACE is literal: escape pattern syntax in the needle
            # and backslashes in the replacement (special in re.sub
            # templates — \1 would act as a backreference)
            return E.RegexpReplace(e, _re.escape(find),
                                   repl.replace("\\", "\\\\"))
        if name == "TRANSLATE":
            e = self.parse()
            self.expect(",")
            m = self._str_literal()
            self.expect(",")
            r = self._str_literal()
            self.expect(")")
            return E.StringTransform("translate", e, (m, r))
        if name == "SPLIT":
            e = self.parse()
            self.expect(",")
            d = self._str_literal()
            self.expect(")")
            return E.Split(e, d)
        if name == "CONCAT_WS":
            sep = self._str_literal()
            args = []
            while self.accept(","):
                args.append(self.parse())
            self.expect(")")
            from spark_tpu.api import functions as F

            return F.concat_ws(sep, *args)
        if name in ("TRANSFORM", "FILTER", "EXISTS", "FORALL"):
            arr = self.parse()
            self.expect(",")
            lam = self._parse_lambda()
            self.expect(")")
            return E.HigherOrder(name.lower(), arr, lam)
        if name in ("AGGREGATE", "REDUCE"):
            arr = self.parse()
            self.expect(",")
            zero = self.parse()
            self.expect(",")
            merge = self._parse_lambda()
            finish = None
            if self.accept(","):
                finish = self._parse_lambda()
            self.expect(")")
            return E.HigherOrder("aggregate", arr, merge, zero, finish)
        if name in ("COLLECT_LIST", "COLLECT_SET", "ARRAY_AGG"):
            e = self.parse()
            self.expect(")")
            return E.Collect(e, unique=(name == "COLLECT_SET"))
        if name in ("PERCENTILE", "PERCENTILE_APPROX", "APPROX_PERCENTILE",
                    "MEDIAN"):
            e = self.parse()
            if name == "MEDIAN":
                self.expect(")")
                return E.Percentile(e, 0.5, interpolate=True)
            self.expect(",")
            q = self.parse()
            if not isinstance(q, E.Literal):
                raise SQLParseError("percentile fraction must be a literal")
            if self.accept(","):
                self.parse()  # accuracy accepted, unused (exact result)
            self.expect(")")
            return E.Percentile(e, float(q.value),
                                interpolate=(name == "PERCENTILE"))
        if name in _COMPOSED_FUNCTIONS:
            args = []
            if not self.accept(")"):
                args.append(self.parse())
                while self.accept(","):
                    args.append(self.parse())
                self.expect(")")
            return _COMPOSED_FUNCTIONS[name](*args)
        # session-injected functions (reference:
        # SparkSessionExtensions.injectFunction:344)
        builder = _extension_function(name)
        if builder is not None:
            args = []
            if not self.accept(")"):
                args.append(self.parse())
                while self.accept(","):
                    args.append(self.parse())
                self.expect(")")
            return builder(*args)
        raise SQLParseError(f"unknown function {name_tok.value!r} "
                            f"at {name_tok.pos}")

    def _parse_lambda(self) -> "E.Lambda":
        """``x -> body`` / ``(x, i) -> body`` (reference: LambdaFunction,
        higherOrderFunctions.scala). Params shadow outer columns inside
        the body — resolution is wrapped, not scoped-table-based."""
        params = []
        if self.peek().kind == "op" and self.peek().value == "(":
            self.next()
            params.append(self.next().value)
            while self.accept(","):
                params.append(self.next().value)
            self.expect(")")
        else:
            params.append(self.next().value)
        self.expect("->")
        by_lower = {p.lower(): p for p in params}
        outer_resolve = self.resolve

        def resolve(qual, name):
            if qual is None and name.lower() in by_lower:
                return E.Col(by_lower[name.lower()])
            return outer_resolve(qual, name)

        self.resolve = resolve
        try:
            body = self.parse()
        finally:
            self.resolve = outer_resolve
        return E.Lambda(tuple(params), body)

    def _str_literal(self) -> str:
        e = self.parse_primary()
        if isinstance(e, E.Literal) and isinstance(e.value, str):
            return e.value
        raise SQLParseError("expected string literal")

    def _int_literal(self) -> int:
        e = self.parse_unary()
        if isinstance(e, E.Literal) and isinstance(e.value, int):
            return e.value
        if isinstance(e, E.Neg) and isinstance(e.child, E.Literal):
            return -e.child.value
        raise SQLParseError("expected integer literal")

    @staticmethod
    def _literal_value(e: E.Expression):
        if isinstance(e, E.Literal):
            return e.value
        if isinstance(e, E.Neg) and isinstance(e.child, E.Literal):
            return -e.child.value
        raise SQLParseError("IN list supports literals only")


@dataclass
class _Interval(E.Expression):
    months: int = 0
    days: int = 0


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


# ---- statement parser -------------------------------------------------------


class _StmtParser:
    """Parses one statement; ``catalog`` resolves table names, the
    optional ``outer`` scope enables correlated subqueries (inner lookups
    that miss fall through to the outer scope as OuterRef)."""

    def __init__(self, tokens: List[Token], pos: int, catalog,
                 outer: Optional[Scope] = None,
                 outer_schema=None):
        self.toks = tokens
        self.pos = pos
        self.catalog = catalog
        self.outer = outer
        self.outer_schema = outer_schema

    # token helpers shared with the expression parser via a tiny shim
    def _ep(self, resolver: Resolver) -> _ExprParser:
        ep = _ExprParser(self.toks, self.pos, resolver,
                         subquery_parser=self._parse_subquery_in_expr)
        return ep

    def _sync(self, ep: _ExprParser):
        self.pos = ep.pos

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.pos + ahead, len(self.toks) - 1)]

    def next(self) -> Token:  # noqa: A003
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def accept(self, *values: str) -> Optional[Token]:
        t = self.peek()
        if t.kind in ("id", "op") and t.upper in values:
            return self.next()
        return None

    def expect(self, value: str) -> Token:
        t = self.next()
        if t.upper != value:
            raise SQLParseError(
                f"expected {value!r}, found {t.value!r} at {t.pos}")
        return t

    def at_keyword(self, *values: str) -> bool:
        t = self.peek()
        return t.kind == "id" and t.upper in values

    # -- subquery hook from expression context -------------------------------

    def _parse_subquery_in_expr(self, ep: _ExprParser):
        """Called by the expression parser at '( SELECT'. The CURRENT
        query's scope becomes the subquery's outer scope."""
        sub = _StmtParser(self.toks, ep.pos, self.catalog,
                          outer=self._current_scope,
                          outer_schema=self._current_plan_schema)
        plan = sub.parse_query_body()
        ep.pos = sub.pos
        return plan

    # -- resolvers ------------------------------------------------------------

    def _make_resolver(self, scope: Scope, plan_schema) -> Resolver:
        def resolve(qual: Optional[str], name: str) -> E.Expression:
            out = scope.resolve(qual, name)
            if out is not None:
                if out.lower() == (name + "#keys").lower():
                    # bare map reference: mark so the select list can
                    # expand it to the '#keys'/'#vals' pair
                    return E.MapHandle(out)
                return E.Col(out)
            if self.outer is not None:
                out2 = self.outer.resolve(qual, name)
                if out2 is not None:
                    dtype = (self.outer_schema.field(out2).dtype
                             if self.outer_schema is not None
                             and out2 in self.outer_schema else None)
                    return E.OuterRef(out2, dtype)
            raise SQLParseError(
                f"cannot resolve column {qual + '.' if qual else ''}{name}")

        return resolve

    # -- FROM clause ----------------------------------------------------------

    def _parse_relation_primary(self, scope: Scope):
        """table [alias] | ( subquery ) alias — returns (plan, alias)."""
        if self.accept("("):
            sub = _StmtParser(self.toks, self.pos, self.catalog,
                              outer=self.outer,
                              outer_schema=self.outer_schema)
            plan = sub.parse_query_body()
            self.pos = sub.pos
            self.expect(")")
            alias = self._parse_alias()
            return plan, alias
        t = self.next()
        if t.kind not in ("id", "qid"):
            raise SQLParseError(f"expected table name at {t.pos}")
        plan = self.catalog.lookup(t.value)
        alias = self._parse_alias() or t.value
        return plan, alias

    def _parse_alias(self) -> Optional[str]:
        if self.accept("AS"):
            return self.next().value
        t = self.peek()
        if t.kind in ("id", "qid") and (t.kind == "qid"
                                        or t.upper not in _RESERVED_STOP):
            return self.next().value
        return None

    def _parse_from(self) -> Tuple[L.LogicalPlan, Scope]:
        scope = Scope()
        plan, alias = self._parse_relation_primary(scope)
        scope.add_relation(alias, plan.schema.names)
        while True:
            if self.accept(","):
                rplan, ralias = self._parse_relation_primary(scope)
                scope.add_relation(ralias, rplan.schema.names)
                plan = L.Join(plan, rplan, "cross", (), ())
                continue
            if self.peek(0).upper == "LATERAL" \
                    and self.peek(1).upper == "VIEW":
                # LATERAL VIEW [POS]EXPLODE(expr) viewAlias AS col[, pos]
                # (reference: hive LATERAL VIEW -> Generate; the view
                # alias itself is accepted and ignored — columns resolve
                # unqualified like the rest of this parser)
                self.next(); self.next()
                fn = self.next().upper
                if fn not in ("EXPLODE", "POSEXPLODE"):
                    raise SQLParseError(
                        f"LATERAL VIEW supports explode/posexplode, "
                        f"got {fn}")
                self.expect("(")
                resolver = self._make_resolver(scope, None)
                ep = self._ep(resolver)
                arr = ep.parse()
                self._sync(ep)
                self.expect(")")
                if self.peek().kind in ("id", "qid") \
                        and self.peek().upper != "AS":
                    self.next()  # optional view alias
                names = []
                if self.accept("AS"):
                    names.append(self.next().value)
                    while self.accept(","):
                        names.append(self.next().value)
                if fn == "POSEXPLODE":
                    pos_name = names[0] if len(names) > 1 else "pos"
                    out_name = (names[1] if len(names) > 1
                                else (names[0] if names else "col"))
                else:
                    pos_name = None
                    out_name = names[0] if names else "col"
                gen = E.Explode(arr, with_position=fn == "POSEXPLODE")
                plan = L.Generate(gen, out_name, pos_name, plan)
                scope.add_relation(
                    None, ([pos_name] if pos_name else []) + [out_name])
                continue
            how = self._peek_join_type()
            if how is None:
                break
            rplan, ralias = self._parse_relation_primary(scope)
            right_src = rplan.schema.names
            # output names the right side will take post-dedup
            out_names = scope.add_relation(ralias, right_src)
            right_sub = {out: src for out, src in zip(out_names, right_src)}
            if self.accept("ON"):
                resolver = self._make_resolver(scope, None)
                ep = self._ep(resolver)
                cond = ep.parse()
                self._sync(ep)
                plan = self._build_join(plan, rplan, how, cond, right_sub)
            elif self.accept("USING"):
                self.expect("(")
                cols = [self.next().value]
                while self.accept(","):
                    cols.append(self.next().value)
                self.expect(")")
                lk = tuple(E.Col(c) for c in cols)
                plan = L.Join(plan, rplan, how, lk, lk)
            else:
                if how != "cross":
                    raise SQLParseError("JOIN requires ON or USING")
                plan = L.Join(plan, rplan, "cross", (), ())
        return plan, scope

    def _peek_join_type(self) -> Optional[str]:
        mapping = [
            (("CROSS", "JOIN"), "cross"),
            (("INNER", "JOIN"), "inner"),
            (("LEFT", "SEMI", "JOIN"), "left_semi"),
            (("LEFT", "ANTI", "JOIN"), "left_anti"),
            (("LEFT", "OUTER", "JOIN"), "left"),
            (("LEFT", "JOIN"), "left"),
            (("RIGHT", "OUTER", "JOIN"), "right"),
            (("RIGHT", "JOIN"), "right"),
            (("FULL", "OUTER", "JOIN"), "full"),
            (("FULL", "JOIN"), "full"),
            (("JOIN",), "inner"),
        ]
        for words, how in mapping:
            if all(self.peek(i).upper == w for i, w in enumerate(words)):
                for _ in words:
                    self.next()
                return how
        return None

    def _build_join(self, left: L.LogicalPlan, right: L.LogicalPlan,
                    how: str, cond: E.Expression,
                    right_out_to_src: Dict[str, str]) -> L.LogicalPlan:
        """Split an ON condition into equi keys + residual. The condition
        references OUTPUT names; keys must be rewritten to each side's
        SOURCE names (the engines evaluate keys on child pipes)."""
        from spark_tpu.plan.optimizer import (combine_conjuncts,
                                              split_conjuncts)

        left_out = set(left.schema.names)
        right_out = set(right_out_to_src)

        def to_src(e: E.Expression) -> E.Expression:
            def fn(x):
                if isinstance(x, E.Col) and x.col_name in right_out_to_src:
                    return E.Col(right_out_to_src[x.col_name])
                return x

            return E.transform_expr(e, fn)

        lkeys: List[E.Expression] = []
        rkeys: List[E.Expression] = []
        residual: List[E.Expression] = []
        for c in split_conjuncts(cond):
            if isinstance(c, E.Cmp) and c.op == "==":
                lr, rr = c.left.references(), c.right.references()
                if lr and lr <= left_out and rr and rr <= right_out:
                    lkeys.append(c.left)
                    rkeys.append(to_src(c.right))
                    continue
                if rr and rr <= left_out and lr and lr <= right_out:
                    lkeys.append(c.right)
                    rkeys.append(to_src(c.left))
                    continue
            residual.append(c)
        res = combine_conjuncts(residual) if residual else None
        return L.Join(left, right, how, tuple(lkeys), tuple(rkeys), res)

    # -- SELECT core -----------------------------------------------------------

    def parse_query_body(self) -> L.LogicalPlan:
        """query := select_core (UNION [ALL] | INTERSECT | EXCEPT
        select_core)* [ORDER BY ...] [LIMIT n]"""
        plan = self.parse_select_core()
        while True:
            if self.accept("UNION"):
                all_ = bool(self.accept("ALL"))
                rhs = self.parse_select_core()
                plan = L.Union(plan, rhs)
                if not all_:
                    plan = L.Distinct(plan)
            elif self.accept("INTERSECT"):
                rhs = self.parse_select_core()
                cols = _null_safe_setop_keys(plan)
                rcols = _null_safe_setop_keys(rhs)
                plan = L.Distinct(
                    L.Join(plan, rhs, "left_semi", cols, rcols))
            elif self.accept("EXCEPT"):
                rhs = self.parse_select_core()
                cols = _null_safe_setop_keys(plan)
                rcols = _null_safe_setop_keys(rhs)
                plan = L.Distinct(
                    L.Join(plan, rhs, "left_anti", cols, rcols))
            else:
                break
        plan = self._parse_order_limit(plan)
        return plan

    def _parse_order_limit(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        if self.at_keyword("ORDER"):
            self.next()
            self.expect("BY")
            out_names = set(plan.schema.names)
            # ORDER BY may reference projection INPUT columns that the
            # select list dropped (reference: Analyzer
            # ResolveSortReferences — the sort sees a widened Project,
            # then the extra columns are projected away again)
            hidden: set = set()
            child_names = (set(plan.child.schema.names)
                           if isinstance(plan, L.Project) else set())

            def resolve(qual, name):
                # ORDER BY resolves against the select OUTPUT; a
                # qualifier (t1.a) is dropped — the output columns of a
                # join carry deduplicated bare names, so the bare name
                # identifies the column (ambiguity already got a _N
                # suffix at join time)
                if name in out_names:
                    return E.Col(name)
                for n in out_names:  # case-insensitive fallback
                    if n.lower() == name.lower():
                        return E.Col(n)
                for n in child_names:
                    if n.lower() == name.lower():
                        hidden.add(n)
                        return E.Col(n)
                raise SQLParseError(
                    f"ORDER BY column "
                    f"{(qual + '.' if qual else '') + name!r} is not in "
                    f"the select list output {sorted(out_names)}")

            orders = []
            while True:
                ep = self._ep(resolve)
                e = ep.parse()
                self._sync(ep)
                asc = True
                if self.accept("DESC"):
                    asc = False
                elif self.accept("ASC"):
                    pass
                nulls_first = None
                if self.accept("NULLS"):
                    nf = self.next().upper
                    nulls_first = nf == "FIRST"
                orders.append(E.SortOrder(e, asc, nulls_first))
                if not self.accept(","):
                    break
            if hidden:
                visible = tuple(plan.schema.names)
                widened = L.Project(
                    tuple(plan.exprs)
                    + tuple(E.Col(n) for n in sorted(hidden)
                            if n not in out_names),
                    plan.child)
                plan = L.Project(tuple(E.Col(n) for n in visible),
                                 L.Sort(tuple(orders), widened))
            else:
                plan = L.Sort(tuple(orders), plan)
        if self.at_keyword("LIMIT"):
            self.next()
            n = int(self.next().value)
            offset = 0
            if self.at_keyword("OFFSET"):
                self.next()
                offset = int(self.next().value)
            plan = L.Limit(n, plan, offset=offset)
        return plan

    def parse_select_core(self) -> L.LogicalPlan:
        self.expect("SELECT")
        distinct = bool(self.accept("DISTINCT"))
        self.accept("ALL")

        # select list is parsed AFTER from (resolution needs the scope),
        # so remember its token span and skip ahead to FROM
        select_start = self.pos
        depth = 0
        while True:
            t = self.peek()
            if t.kind == "eof":
                break
            if t.kind == "op" and t.value == "(":
                depth += 1
            elif t.kind == "op" and t.value == ")":
                if depth == 0:
                    break
                depth -= 1
            elif depth == 0 and t.kind == "id" and t.upper == "FROM":
                break
            self.next()
        select_end = self.pos

        if self.at_keyword("FROM"):
            self.next()
            plan, scope = self._parse_from()
        else:
            # SELECT without FROM: single-row relation
            plan, scope = L.Range(0, 1, 1, "__one"), Scope()

        self._current_scope = scope
        self._current_plan_schema = plan.schema
        resolver = self._make_resolver(scope, plan.schema)

        # WHERE
        if self.accept("WHERE"):
            ep = self._ep(resolver)
            cond = ep.parse()
            self._sync(ep)
            plan = L.Filter(cond, plan)
            self._current_plan_schema = plan.schema

        # parse the saved select list now
        saved = self.pos
        self.pos = select_start
        select_exprs = self._parse_select_list(select_end, scope, resolver)
        self.pos = saved

        # GROUP BY / HAVING / aggregate detection
        group_exprs: List[E.Expression] = []
        gsets = None  # (keys, index sets) for ROLLUP/CUBE/GROUPING SETS
        if self.at_keyword("GROUP"):
            self.next()
            self.expect("BY")
            gresolver = self._group_resolver(resolver, select_exprs)

            def parse_key_list():
                keys = []
                self.expect("(")
                if not self.accept(")"):
                    while True:
                        ep = self._ep(gresolver)
                        keys.append(E.strip_alias(ep.parse()))
                        self._sync(ep)
                        if self.accept(")"):
                            break
                        self.expect(",")
                return keys

            head = self.peek(0).upper
            if head in ("ROLLUP", "CUBE") and self.peek(1).value == "(":
                from spark_tpu.plan.grouping import cube_sets, rollup_sets

                self.next()
                keys = parse_key_list()
                sets = (rollup_sets(len(keys)) if head == "ROLLUP"
                        else cube_sets(len(keys)))
                gsets = (keys, sets)
            elif head == "GROUPING" and self.peek(1).upper == "SETS":
                self.next()
                self.next()
                self.expect("(")
                raw_sets = []
                while True:
                    if self.peek().value == "(":
                        raw_sets.append(tuple(parse_key_list()))
                    else:
                        # bare key = singleton set: GROUPING SETS (a, ())
                        ep = self._ep(gresolver)
                        raw_sets.append((E.strip_alias(ep.parse()),))
                        self._sync(ep)
                    if self.accept(")"):
                        break
                    self.expect(",")
                # keys = ordered union across sets; sets -> index tuples
                keys = []
                seen_keys = {}
                for s in raw_sets:
                    for e in s:
                        sk = E.expr_key(e)
                        if sk not in seen_keys:
                            seen_keys[sk] = len(keys)
                            keys.append(e)
                sets = [tuple(seen_keys[E.expr_key(e)] for e in s)
                        for s in raw_sets]
                gsets = (keys, sets)
            else:
                while True:
                    ep = self._ep(gresolver)
                    e = ep.parse()
                    self._sync(ep)
                    group_exprs.append(E.strip_alias(e))
                    if not self.accept(","):
                        break
        having = None
        if self.at_keyword("HAVING"):
            self.next()
            ep = self._ep(resolver)
            having = ep.parse()
            self._sync(ep)

        has_agg = any(E.contains_aggregate(e) for e in select_exprs)
        has_window = any(E.contains_window(e) for e in select_exprs)
        if has_window and (group_exprs or gsets or has_agg
                           or having is not None):
            raise NotImplementedError(
                "window functions combined with GROUP BY/HAVING in the "
                "same SELECT are not supported yet")
        if gsets is not None:
            from spark_tpu.plan.grouping import (contains_grouping_fns,
                                                 grouping_sets_aggregate,
                                                 rewrite_grouping_fns)

            keys, sets = gsets
            outputs = list(select_exprs)
            having_cond = None
            if having is not None:
                hidden, having_cond = self._pull_having_aggs(having)
                outputs = outputs + hidden
                if contains_grouping_fns(having_cond):
                    # HAVING reads the grouping id through a hidden
                    # output; key references resolve against the
                    # aggregate's ordinary output names
                    outputs.append(E.Alias(E.GroupingId(), "__gidh"))
                    having_cond = rewrite_grouping_fns(
                        having_cond, keys, "__gidh")
            plan, _ = grouping_sets_aggregate(
                plan, keys, sets, tuple(outputs))
            if having_cond is not None:
                plan = L.Filter(having_cond, plan)
                plan = L.Project(
                    tuple(E.Col(e.name) for e in select_exprs), plan)
        elif group_exprs or has_agg or having is not None:
            outputs = list(select_exprs)
            having_cond = None
            if having is not None:
                hidden, having_cond = self._pull_having_aggs(having)
                outputs = outputs + hidden
            plan = L.Aggregate(tuple(group_exprs), tuple(outputs), plan)
            if having_cond is not None:
                plan = L.Filter(having_cond, plan)
                plan = L.Project(
                    tuple(E.Col(e.name) for e in select_exprs), plan)
        else:
            plan = L.project_with_windows(tuple(select_exprs), plan)

        if distinct:
            plan = L.Distinct(plan)
        return plan

    def _pull_having_aggs(self, having: E.Expression):
        """Pull aggregate calls out of a HAVING predicate as hidden
        outputs so it becomes an ordinary Filter above the Aggregate
        (where subquery rewriting can reach it); the hidden columns are
        projected away afterwards."""
        hidden: List[E.Alias] = []
        seen_aggs: Dict[tuple, str] = {}

        def pull(e: E.Expression) -> E.Expression:
            if isinstance(e, E.AggregateExpression):
                sk = E.expr_key(e)
                if sk not in seen_aggs:
                    name = f"__h{len(hidden)}"
                    seen_aggs[sk] = name
                    hidden.append(E.Alias(e, name))
                return E.Col(seen_aggs[sk])
            return e

        return hidden, E.transform_expr(having, pull)

    def _group_resolver(self, resolver: Resolver,
                        select_exprs: List[E.Expression]) -> Resolver:
        """GROUP BY may name a select alias (GROUP BY revenue)."""
        by_alias = {e.name: E.strip_alias(e) for e in select_exprs
                    if isinstance(e, E.Alias)}

        def resolve(qual, name):
            try:
                return resolver(qual, name)
            except SQLParseError:
                if qual is None and name in by_alias:
                    return by_alias[name]
                raise

        return resolve

    def _parse_select_list(self, end: int, scope: Scope,
                           resolver: Resolver) -> List[E.Expression]:
        exprs: List[E.Expression] = []
        while self.pos < end:
            t = self.peek()
            if t.kind == "op" and t.value == "*":
                self.next()
                exprs.extend(E.Col(n) for n in scope.all_output_names())
            elif t.kind in ("id", "qid") and self.peek(1).value == "." \
                    and self.peek(2).value == "*":
                rel_outs = scope.relation_outputs(t.value)
                if rel_outs is None:
                    raise SQLParseError(f"unknown relation {t.value!r}")
                self.next()
                self.next()
                self.next()
                exprs.extend(E.Col(n) for n in rel_outs)
            else:
                ep = self._ep(resolver)
                e = ep.parse()
                self._sync(ep)
                if self.pos < end and self.accept("AS"):
                    e = E.Alias(e, self.next().value)
                elif self.pos < end and self.peek().kind in ("id", "qid") \
                        and self.peek().upper not in _RESERVED_STOP:
                    e = E.Alias(e, self.next().value)
                exprs.append(e)
            if self.pos < end:
                if not self.accept(","):
                    raise SQLParseError(
                        f"expected ',' in select list at "
                        f"{self.peek().pos}: {self.peek().value!r}")
        # a selected MAP handle ('m' resolved to Col('m#keys')) carries
        # its '#vals' sibling so the pair survives projection
        # (types.MapType decomposition)
        out: List[E.Expression] = []
        seen = {e.name for e in exprs}
        for e in exprs:
            out.append(e)
            inner = E.strip_alias(e)
            if isinstance(inner, E.MapHandle) \
                    and inner.col_name.endswith("#keys"):
                vals = inner.col_name[:-len("#keys")] + "#vals"
                if isinstance(e, E.Alias):
                    alias = e.alias_name
                    if alias.endswith("#keys"):
                        alias = alias[:-len("#keys")]
                    out[-1] = E.Alias(inner, alias + "#keys")
                    pair: E.Expression = E.Alias(E.Col(vals),
                                                 alias + "#vals")
                else:
                    pair = E.Col(vals)
                if pair.name not in seen:
                    out.append(pair)
                    seen.add(pair.name)
        return out


# ---- public entry points ----------------------------------------------------


def _null_safe_setop_keys(plan) -> tuple:
    """INTERSECT/EXCEPT compare rows with NULL-SAFE equality (SQL set
    semantics: NULL equals NULL — the reference plans these as
    null-aware joins, ReplaceIntersectWithSemiJoin + EqualNullSafe).
    Each nullable column becomes TWO join keys: a typed
    coalesce-to-zero payload and an is-null flag."""
    import datetime as _dt
    import decimal as _decimal

    from spark_tpu import types as T

    keys = []
    for f in plan.schema.fields:
        c = E.Col(f.name)
        if not f.nullable:
            keys.append(c)
            continue
        dt = f.dtype
        zero: object = 0
        if isinstance(dt, T.StringType):
            zero = ""
        elif isinstance(dt, T.DateType):
            zero = _dt.date(1970, 1, 1)
        elif isinstance(dt, (T.Float32Type, T.Float64Type)):
            zero = 0.0
        elif isinstance(dt, T.DecimalType):
            zero = _decimal.Decimal(0)
        elif isinstance(dt, T.BooleanType):
            zero = False
        keys.append(E.Coalesce((c, E.Literal(zero))))
        keys.append(E.IsNull(c))
    return tuple(keys)


class _NoCatalog:
    def lookup(self, name: str):
        raise SQLParseError(
            f"table or view not found: {name} (no catalog in scope)")


def _composed_functions() -> dict:
    """SQL names for the composition-built functions in api.functions
    (no dedicated expression nodes; reference: catalyst FunctionRegistry
    entries that expand to existing expressions)."""
    from spark_tpu.api import functions as F

    return {
        "GREATEST": F.greatest, "LEAST": F.least,
        "IFNULL": F.ifnull, "NVL": F.nvl, "NVL2": F.nvl2,
        "LOG2": F.log2, "DEGREES": F.degrees, "RADIANS": F.radians,
        "PMOD": F.pmod,
        "QUARTER": F.quarter, "DAYOFWEEK": F.dayofweek,
        "WEEKDAY": F.weekday, "DAYOFYEAR": F.dayofyear,
        "MONTHS_BETWEEN": F.months_between,
        "CURRENT_DATE": F.current_date,
        "HOUR": F.hour, "MINUTE": F.minute, "SECOND": F.second,
        "INITCAP": F.initcap, "REVERSE": F.reverse,
        "GROUPING": lambda c: E.Grouping(c),
        "GROUPING_ID": lambda: E.GroupingId(),
        "ARRAY": F.array, "SIZE": F.size, "CARDINALITY": F.size,
        "ELEMENT_AT": F.element_at, "ARRAY_CONTAINS": F.array_contains,
        "EXPLODE": F.explode, "POSEXPLODE": F.posexplode,
        "MAP": F.create_map, "MAP_FROM_ARRAYS": F.map_from_arrays,
        "MAP_KEYS": F.map_keys, "MAP_VALUES": F.map_values,
        "MAP_CONTAINS_KEY": F.map_contains_key,
    }


class _LazyFunctionTable:
    def __init__(self):
        self._table = None

    def __contains__(self, name):
        if self._table is None:
            self._table = _composed_functions()
        return name in self._table

    def __getitem__(self, name):
        if self._table is None:
            self._table = _composed_functions()
        return self._table[name]


_COMPOSED_FUNCTIONS = _LazyFunctionTable()


def _extension_function(name: str):
    """Builder for a session-injected function, or None."""
    from spark_tpu.api.session import SparkSession

    sess = SparkSession._active
    if sess is None:
        return None
    return sess.extensions.function(name)


def parse_sql(query: str, catalog=None) -> L.LogicalPlan:
    """Parse a full statement: SELECT query, CREATE/DROP VIEW."""
    toks = tokenize(query)
    p = _StmtParser(toks, 0, catalog if catalog is not None else _NoCatalog())

    if p.at_keyword("CREATE"):
        p.next()
        p.accept("OR")
        p.accept("REPLACE")
        p.accept("TEMP")
        p.accept("TEMPORARY")
        p.expect("VIEW")
        name = p.next().value
        p.expect("AS")
        plan = p.parse_query_body()
        catalog._register_view(name, plan)
        return L.Range(0, 0, 1, "__ok")  # DDL: empty result
    if p.at_keyword("DROP"):
        p.next()
        p.expect("VIEW")
        name = p.next().value
        catalog.dropTempView(name)
        return L.Range(0, 0, 1, "__ok")

    plan = p.parse_query_body()
    t = p.peek()
    if not (t.kind == "eof" or (t.kind == "op" and t.value == ";")):
        raise SQLParseError(f"trailing input at {t.pos}: {t.value!r}")
    from spark_tpu.plan.subquery import rewrite_subqueries

    return rewrite_subqueries(plan)


def _schema_resolver(schema) -> Resolver:
    def resolve(qual: Optional[str], name: str) -> E.Expression:
        if schema is not None and name not in schema:
            for n in schema.names:
                if n.lower() == name.lower():
                    return E.Col(n)
        return E.Col(name)

    return resolve


def parse_expression(text: str, schema=None) -> E.Expression:
    """Parse a standalone SQL expression (df.filter("..."), F.expr)."""
    toks = tokenize(text)
    ep = _ExprParser(toks, 0, _schema_resolver(schema))
    e = ep.parse()
    t = ep.peek()
    if t.kind != "eof":
        raise SQLParseError(f"trailing input at {t.pos}: {t.value!r}")
    return e


def parse_projection(text: str, schema=None) -> E.Expression:
    """Parse 'expr [AS alias]' (df.selectExpr)."""
    toks = tokenize(text)
    ep = _ExprParser(toks, 0, _schema_resolver(schema))
    e = ep.parse()
    t = ep.peek()
    if t.kind == "id" and t.upper == "AS":
        ep.next()
        alias = ep.next().value
        e = E.Alias(e, alias)
        t = ep.peek()
    elif t.kind in ("id", "qid") and t.upper not in _RESERVED_STOP:
        ep.next()
        e = E.Alias(e, t.value)
        t = ep.peek()
    if t.kind != "eof":
        raise SQLParseError(f"trailing input at {t.pos}: {t.value!r}")
    return e

"""SQL front-end: tokenizer, parser, DDL schema strings.

The analogue of the reference's ANTLR grammar + AstBuilder (reference:
sql/catalyst/src/main/antlr4/.../SqlBaseParser.g4:1 — 1,819 lines —
and parser/AstBuilder.scala), hand-written as a Pratt/recursive-descent
parser sized to the dialect the engine executes (TPC-H and the DataFrame
feature set).
"""

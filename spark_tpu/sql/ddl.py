"""DDL schema strings: ``"a INT, b STRING NOT NULL"`` -> Schema
(reference: sql/catalyst/.../parser/ParserInterface.parseTableSchema +
DataType.fromDDL)."""

from __future__ import annotations

from spark_tpu import types as T
from spark_tpu.types import Field, Schema

_TYPE_NAMES = {
    "boolean": T.BOOLEAN, "bool": T.BOOLEAN,
    "byte": T.INT8, "tinyint": T.INT8,
    "short": T.INT16, "smallint": T.INT16,
    "int": T.INT32, "integer": T.INT32,
    "long": T.INT64, "bigint": T.INT64,
    "float": T.FLOAT32, "real": T.FLOAT32,
    "double": T.FLOAT64,
    "string": T.STRING, "varchar": T.STRING, "char": T.STRING,
    "text": T.STRING,
    "date": T.DATE,
    "timestamp": T.TIMESTAMP,
}


def parse_type(s: str) -> T.DataType:
    s = s.strip().lower()
    if s.startswith("decimal") or s.startswith("numeric"):
        if "(" in s:
            inner = s[s.index("(") + 1:s.rindex(")")]
            parts = [p.strip() for p in inner.split(",")]
            p = int(parts[0])
            sc = int(parts[1]) if len(parts) > 1 else 0
            if p > T.DecimalType.MAX_PRECISION:
                raise NotImplementedError(
                    f"decimal({p},{sc}) exceeds the engine's "
                    f"{T.DecimalType.MAX_PRECISION}-digit (int64) cap")
            return T.DecimalType(p, sc)
        return T.DecimalType(10, 0)
    if "(" in s:  # varchar(32), char(1)
        s = s[:s.index("(")]
    if s in _TYPE_NAMES:
        return _TYPE_NAMES[s]
    raise ValueError(f"unknown SQL type {s!r}")


def parse_ddl_schema(ddl: str) -> Schema:
    """Parse ``name TYPE [NOT NULL], ...`` (paren-aware split so
    decimal(12,2) commas don't break fields)."""
    fields = []
    depth = 0
    cur = []
    parts = []
    for ch in ddl:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur))
    for part in parts:
        toks = part.strip().split()
        if len(toks) < 2:
            raise ValueError(f"bad DDL field {part!r}")
        name = toks[0].strip("`\"")
        nullable = True
        if len(toks) >= 4 and toks[-2].lower() == "not" \
                and toks[-1].lower() == "null":
            nullable = False
            toks = toks[:-2]
        dtype = parse_type(" ".join(toks[1:]))
        fields.append(Field(name, dtype, nullable=nullable))
    return Schema(tuple(fields))

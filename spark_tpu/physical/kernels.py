"""Device kernels: the Tungsten tier, rebuilt for XLA.

The reference's native-equivalent execution machinery — RadixSort.java:25,
UnsafeExternalSorter.java, BytesToBytesMap.java:67 (hash aggregation),
HashedRelation.scala (join builds) — is pointer-chasing JVM/off-heap
code. None of that survives contact with a TPU. These kernels re-express
the same operations as dense, static-shape XLA programs:

- sort        -> chained stable argsorts (XLA variadic sort on device)
- hash-agg    -> segment reductions over group ids; group ids come either
                 from mixed-radix dictionary codes (trace-time cardinality,
                 no sort, no sync) or from sort + change-flag cumsum
- hash-join   -> sort the build side once, then two `searchsorted`s give
                 every probe row its contiguous match range; expansion to
                 match pairs is a vectorized gather (no pointers, no probing)

Everything is mask-carrying: dead rows ride along and are neutralized per
reduction, which keeps shapes static under jit.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SortKey(NamedTuple):
    data: jnp.ndarray
    validity: Optional[jnp.ndarray]  # None = all valid
    ascending: bool = True
    nulls_first: bool = True


def _searchsorted_sort_threshold() -> int:
    """spark.tpu.kernels.searchsortedSortThreshold, from the active
    session's conf when one exists (registry default otherwise). Read
    at trace time; the choice only affects speed, never results, so a
    cached trace with a stale threshold stays correct."""
    from spark_tpu import conf as CF

    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        if sess is not None:
            return int(sess.conf.get(CF.SEARCHSORTED_SORT_THRESHOLD))
    except Exception:
        pass
    return int(CF.SEARCHSORTED_SORT_THRESHOLD.default)


def searchsorted(a: jnp.ndarray, v: jnp.ndarray,
                 side: str = "left") -> jnp.ndarray:
    """Size-aware searchsorted. 'scan' (binary search) costs ~log2(a)
    serialized gather rounds over v — linear in v, nearly free for small
    v but catastrophic for large v (measured v5e: a=1.45M/v=1.2M scan
    564 ms vs sort 27 ms). 'sort' co-sorts the concatenation — linear in
    a+v, so it overpays when v << a (a=6M/v=10k: sort 63 ms vs scan
    1.9 ms). The measured crossover (v * threshold ~ a) sat near 50 on
    v5e and is tunable per deployment via
    spark.tpu.kernels.searchsortedSortThreshold."""
    threshold = _searchsorted_sort_threshold()
    method = ("scan" if v.size < 4096 or v.size * threshold <= a.size
              else "sort")
    return jnp.searchsorted(a, v, side=side, method=method)


def lexsort_permutation(keys: Sequence[SortKey], row_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable lexicographic sort permutation. Live rows first; within the
    live region rows are ordered by ``keys`` (most significant first) with
    SQL null placement. Replaces RadixSort.java:25 / TimSort — XLA's sort
    is already a tuned parallel sort, we only arrange comparators.
    """
    n = row_mask.shape[0]
    perm = jnp.arange(n)
    for key in reversed(list(keys)):
        d = key.data[perm]
        if key.validity is not None:
            # canonicalize NULL rows' payload BEFORE the data sort:
            # sorting by garbage-under-null would scramble the
            # less-significant key order established by earlier passes
            # (all nulls are equal; their relative order must be
            # whatever the previous keys made it)
            v = key.validity[perm]
            d = jnp.where(v, d, jnp.zeros((), d.dtype))
        idx = jnp.argsort(d, stable=True, descending=not key.ascending)
        perm = perm[idx]
        if key.validity is not None:
            v = key.validity[perm]
            # nulls_first: invalid(False) first -> ascending sort on bool
            idx = jnp.argsort(v, stable=True, descending=not key.nulls_first)
            perm = perm[idx]
    live = row_mask[perm]
    idx = jnp.argsort(~live, stable=True)  # live rows (False) first
    return perm[idx]


def compaction_permutation(row_mask: jnp.ndarray) -> jnp.ndarray:
    """Permutation moving live rows to the front, preserving order."""
    return jnp.argsort(~row_mask, stable=True)


def group_ids_from_sorted(
    sorted_keys: Sequence[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
    sorted_mask: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Given key columns already sorted (live rows first), return
    (segment_ids, num_groups). Equal adjacent keys (null==null) share a
    segment; dead rows get the last segment id."""
    n = sorted_mask.shape[0]
    change = jnp.zeros((n,), dtype=jnp.bool_)
    for data, validity in sorted_keys:
        neq = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), data[1:] != data[:-1]])
        if validity is not None:
            vneq = jnp.concatenate(
                [jnp.zeros((1,), jnp.bool_), validity[1:] != validity[:-1]])
            # both-null rows compare equal regardless of payload
            both_null = jnp.concatenate(
                [jnp.zeros((1,), jnp.bool_), (~validity[1:]) & (~validity[:-1])])
            neq = (neq & ~both_null) | vneq
        change = change | neq
    change = change & sorted_mask
    seg = jnp.cumsum(change.astype(jnp.int32))
    num_groups = jnp.where(sorted_mask.any(), seg[-1] + 1, 0)
    return seg, num_groups


# ---- segment aggregation ----------------------------------------------------
#
# TPU reality check (measured on v5e): XLA scatter-add (jax.ops.segment_sum)
# costs ~100 ms/M rows regardless of dtype, while dense masked reductions,
# cumsum, and associative scans are bandwidth-bound (~free). Strategy:
#   - K == 1: plain reduction
#   - K small (<= _MASKED_SEG_LIMIT): K masked dense reductions (XLA fuses
#     the data reads; cost is K passes of pure bandwidth)
#   - monotone seg ids (sort-based aggregation, where rows are already
#     sorted by key): inclusive cumsum + searchsorted segment boundaries
#   - otherwise: scatter-add fallback
# The reference hits the same fork as hash-agg vs sort-agg
# (TungstenAggregationIterator.scala:82 switchToSortBasedAggregation).

_MASKED_SEG_LIMIT = 64


def _masked_reduce(data, seg, mask, num_segments: int, red, init):
    cols = []
    for k in range(num_segments):
        sel = mask & (seg == k)
        cols.append(red(jnp.where(sel, data, init)))
    return jnp.stack(cols)


def seg_bounds(seg: jnp.ndarray, num_segments: int):
    """First/last row positions per segment for MONOTONE seg ids."""
    ks = jnp.arange(num_segments, dtype=seg.dtype)
    starts = searchsorted(seg, ks, side="left")
    ends = searchsorted(seg, ks, side="right") - 1
    return starts, ends


def _sorted_seg_sum(masked, seg, num_segments: int):
    csum = jnp.cumsum(masked, dtype=masked.dtype)
    starts, ends = seg_bounds(seg, num_segments)
    n = masked.shape[0]
    e = jnp.clip(ends, 0, n - 1)
    s = jnp.clip(starts, 0, n - 1)
    total = csum[e] - csum[s] + masked[s]
    return jnp.where(ends >= starts, total, jnp.zeros((), masked.dtype))


def _seg_scan(seg, x, combine):
    """Segmented inclusive scan (resets at seg changes); seg monotone."""

    def op(a, b):
        sa, va = a
        sb, vb = b
        return sb, jnp.where(sa == sb, combine(va, vb), vb)

    _, out = jax.lax.associative_scan(op, (seg, x))
    return out


def _sorted_seg_red(masked, seg, num_segments: int, combine):
    run = _seg_scan(seg, masked, combine)
    _, ends = seg_bounds(seg, num_segments)
    return run[jnp.clip(ends, 0, masked.shape[0] - 1)]


def seg_sum(data, seg, mask, num_segments: int, sorted_seg: bool = False):
    if data.dtype == jnp.int64:
        # int64 is EMULATED on TPU (no native 64-bit vector ALU) — a
        # 6M-row int64 masked reduction measured ~12x slower than f64.
        # Decompose into three 21-bit limbs (arithmetic-shift top limb
        # keeps two's complement identity), sum each EXACTLY in native
        # f64 (limb partial sums stay under 2^53 up to ~4B rows), then
        # recombine; int64 wraparound makes the recombination correct
        # whenever the true total fits 64 bits. Exactness is what the
        # scaled-decimal Sum path (Decimal.scala peer) requires.
        m21 = (1 << 21) - 1
        parts = []
        for sh in (0, 21, 42):
            limb = (data >> sh) & m21 if sh < 42 else data >> 42
            parts.append(seg_sum(limb.astype(jnp.float64), seg, mask,
                                 num_segments, sorted_seg))
        return (parts[0].astype(jnp.int64)
                + (parts[1].astype(jnp.int64) << 21)
                + (parts[2].astype(jnp.int64) << 42))
    zero = jnp.zeros((), dtype=data.dtype)
    masked = jnp.where(mask, data, zero)
    if num_segments == 1:
        # global aggregate: a plain reduction beats a 1-segment scatter-add
        # (this is the AggregateBenchmark 'agg w/o group' hot path)
        return jnp.sum(masked)[None]
    if jnp.issubdtype(data.dtype, jnp.floating):
        # float addition rounds per combination-tree shape, and every
        # tree-structured reduction here (cumsum difference, masked
        # jnp.sum) takes its shape from the PADDED array length — so a
        # segment's float sum would come out bit-different between the
        # static and the AQE capacity-compacted layouts of the same
        # rows. XLA scatter-add applies updates in row order: the sum
        # depends only on the segment's own rows, byte-stable across
        # layouts (int/decimal sums are exact and keep the fast paths).
        return jax.ops.segment_sum(masked, seg, num_segments=num_segments)
    if num_segments <= _MASKED_SEG_LIMIT:
        return _masked_reduce(data, seg, mask, num_segments, jnp.sum, zero)
    if not sorted_seg:
        # 64 < K <= 1024, f32, TPU: one-pass Pallas streaming aggregate
        # (measured 2.5-15x over scatter; see ops/pallas_agg.py table)
        from spark_tpu.ops import maybe_pallas_seg_sum

        out = maybe_pallas_seg_sum(data, seg, mask, num_segments)
        if out is not None:
            return out
    if sorted_seg:
        return _sorted_seg_sum(masked, seg, num_segments)
    return jax.ops.segment_sum(masked, seg, num_segments=num_segments)


def seg_count(seg, mask, num_segments: int, sorted_seg: bool = False):
    ones = mask.astype(jnp.int64)
    if num_segments == 1:
        return jnp.sum(ones)[None]
    if num_segments <= _MASKED_SEG_LIMIT:
        return _masked_reduce(ones, seg, mask, num_segments, jnp.sum,
                              jnp.zeros((), jnp.int64))
    if not sorted_seg:
        from spark_tpu.ops import maybe_pallas_seg_count

        out = maybe_pallas_seg_count(seg, mask, num_segments)
        if out is not None:
            return out
    if sorted_seg:
        return _sorted_seg_sum(ones, seg, num_segments)
    return jax.ops.segment_sum(ones, seg, num_segments=num_segments)


def seg_min(data, seg, mask, num_segments: int, sorted_seg: bool = False):
    big = _pos_sentinel(data.dtype)
    masked = jnp.where(mask, data, big)
    if num_segments == 1:
        return jnp.min(masked)[None]
    if num_segments <= _MASKED_SEG_LIMIT:
        return _masked_reduce(data, seg, mask, num_segments, jnp.min, big)
    if not sorted_seg:
        # same measured selection table as seg_sum: 64 < K <= 1024 f32
        # goes through the one-pass Pallas streaming reduction
        from spark_tpu.ops import maybe_pallas_seg_min

        out = maybe_pallas_seg_min(data, seg, mask, num_segments)
        if out is not None:
            return out
    if sorted_seg:
        return _sorted_seg_red(masked, seg, num_segments, jnp.minimum)
    return jax.ops.segment_min(masked, seg, num_segments=num_segments)


def seg_max(data, seg, mask, num_segments: int, sorted_seg: bool = False):
    small = _neg_sentinel(data.dtype)
    masked = jnp.where(mask, data, small)
    if num_segments == 1:
        return jnp.max(masked)[None]
    if num_segments <= _MASKED_SEG_LIMIT:
        return _masked_reduce(data, seg, mask, num_segments, jnp.max, small)
    if not sorted_seg:
        from spark_tpu.ops import maybe_pallas_seg_max

        out = maybe_pallas_seg_max(data, seg, mask, num_segments)
        if out is not None:
            return out
    if sorted_seg:
        return _sorted_seg_red(masked, seg, num_segments, jnp.maximum)
    return jax.ops.segment_max(masked, seg, num_segments=num_segments)


def seg_first(data, seg, mask, num_segments: int, capacity: int,
              sorted_seg: bool = False):
    """Value of the first (by position) masked row in each segment."""
    pos = jnp.where(mask, jnp.arange(capacity), capacity)
    if sorted_seg:
        first_pos = _sorted_seg_red(pos, seg, num_segments, jnp.minimum)
        # empty segments read position `capacity`
        starts, ends = seg_bounds(seg, num_segments)
        first_pos = jnp.where(ends >= starts, first_pos, capacity)
    elif num_segments <= _MASKED_SEG_LIMIT:
        first_pos = _masked_reduce(pos, seg, mask, num_segments, jnp.min,
                                   jnp.asarray(capacity, pos.dtype))
    else:
        first_pos = jax.ops.segment_min(pos, seg, num_segments=num_segments)
    idx = jnp.clip(first_pos, 0, capacity - 1)
    return data[idx], first_pos < capacity


def _pos_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(True)
    return jnp.array(jnp.iinfo(dtype).max, dtype=dtype)


def _neg_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(False)
    return jnp.array(jnp.iinfo(dtype).min, dtype=dtype)


# ---- mixed-radix key packing ------------------------------------------------


def pack_codes(
    codes: Sequence[jnp.ndarray],
    validities: Sequence[Optional[jnp.ndarray]],
    cardinalities: Sequence[int],
) -> Tuple[jnp.ndarray, int]:
    """Combine per-column small-int codes into one dense int32/int64 group
    id with mixed-radix packing. Each column contributes (cardinality+1)
    states, the extra one encoding NULL. Replaces BytesToBytesMap lookups
    (reference: unsafe/map/BytesToBytesMap.java:497) when cardinalities
    are known at trace time — no hashing, no collisions, no probing.

    Returns (combined_ids, total_cardinality)."""
    total = 1
    combined = None
    for code, validity, card in zip(codes, validities, cardinalities):
        slot = code.astype(jnp.int64)
        if validity is not None:
            slot = jnp.where(validity, slot, card)  # NULL -> extra state
            card = card + 1
        combined = slot if combined is None else combined * card + slot
        total *= card
    assert combined is not None
    return combined, total


def unpack_code(combined: jnp.ndarray, cardinalities: Sequence[int],
                nullable: Sequence[bool]):
    """Inverse of pack_codes: combined id -> per-column (code, validity)."""
    cards = [c + (1 if nl else 0) for c, nl in zip(cardinalities, nullable)]
    out = []
    rem = combined
    for card, orig_card, nl in zip(reversed(cards),
                                   reversed(list(cardinalities)),
                                   reversed(list(nullable))):
        slot = rem % card
        rem = rem // card
        if nl:
            valid = slot < orig_card
            code = jnp.where(valid, slot, 0)
            out.append((code, valid))
        else:
            out.append((slot, None))
    return list(reversed(out))


def distinct_first_mask(data: jnp.ndarray, seg: jnp.ndarray,
                        ok: jnp.ndarray) -> jnp.ndarray:
    """True for the first ok row of each (segment, value) pair.

    DISTINCT-aggregate core (reference rewrite:
    sql/catalyst/.../optimizer/RewriteDistinctAggregates.scala:1 plans a
    two-level Expand+aggregate; here dedup is a device-local sort +
    change-flag scatter, static-shape and jittable): sort rows by
    (segment, value) with dead rows pushed to the back, mark value-group
    heads, scatter the flags back to original row positions. ANDing the
    result into an aggregate's ok-mask makes sum/count/avg see each value
    once per group. Floats compare by canonicalized bit pattern so that
    NaN == NaN for DISTINCT (Spark's NaN normalization,
    NormalizeFloatingNumbers.scala) — float equality would count every
    NaN as a fresh value."""
    n = data.shape[0]
    if jnp.issubdtype(data.dtype, jnp.floating):
        canon = jnp.where(jnp.isnan(data), jnp.nan, data)
        canon = jnp.where(canon == 0.0, 0.0, canon)  # -0.0 -> +0.0
        width = jnp.uint32 if data.dtype == jnp.float32 else jnp.uint64
        data = jax.lax.bitcast_convert_type(canon, width)
    keys = [SortKey(seg, None, True, True), SortKey(data, None, True, True)]
    perm = lexsort_permutation(keys, ok)
    sseg = seg[perm]
    sval = data[perm]
    sok = ok[perm]
    head = jnp.concatenate([
        jnp.ones((1,), jnp.bool_),
        (sseg[1:] != sseg[:-1]) | (sval[1:] != sval[:-1])])
    head = head & sok
    return jnp.zeros((n,), jnp.bool_).at[perm].set(head)


# ---- join ------------------------------------------------------------------


class JoinRanges(NamedTuple):
    """Per-probe-row contiguous match range in the sorted build side."""

    build_perm: jnp.ndarray   # sort permutation of the build side
    lo: jnp.ndarray           # int64[probe_cap]
    hi: jnp.ndarray           # int64[probe_cap]

    @property
    def counts(self) -> jnp.ndarray:
        return self.hi - self.lo


def build_join_ranges(
    build_key: jnp.ndarray,
    build_ok: jnp.ndarray,   # live AND key-valid
    probe_key: jnp.ndarray,
    probe_ok: jnp.ndarray,
) -> JoinRanges:
    """Sorted-build equi-join core (replaces HashedRelation.scala /
    LongToUnsafeRowMap:535): sort build keys with dead/null rows pushed to
    +inf, then two binary searches per probe row give its match range.
    O((B+P) log B) on device, fully vectorized. Expressed through
    make_join_index + ranges_from_index so the live path and the
    cached-index path share ONE sentinel-handling implementation."""
    perm, skey, _, _ = make_join_index(build_key, build_ok, None)
    return ranges_from_index(perm, skey, None, None, probe_key, probe_ok)


#: dense lo/cnt lookup tables are built when the packed key domain is at
#: most this many entries (int32 x2 -> 64 MB @ 8M; orderkey at SF1 is 6M)
JOIN_TABLE_MAX = 1 << 23


def make_join_index(build_key: jnp.ndarray, build_ok: jnp.ndarray,
                    domain: Optional[int]):
    """Precompute the reusable part of a sorted-build join: the build
    permutation, the sorted (sentinel-masked) key, and — when the packed
    key domain is small enough — dense lo/cnt lookup tables over the
    whole domain. Recorded once on a blocking run and replayed as jit
    ARGUMENTS on later executions (same justification as _JOIN_STATS:
    immutable leaves => deterministic), so steady-state joins skip the
    argsort + searchsorted entirely: probing a dense table is a single
    int32 gather at probe size (measured v5e: 5-19 ms where the co-sort
    searchsorted costs 19-63 ms per side; reference analogue: the
    reusable LongToUnsafeRowMap build, HashedRelation.scala:535).

    Returns (perm int32[bcap], sorted_key[bcap], lo_table|None,
    cnt_table|None) device arrays."""
    sentinel = _pos_sentinel(build_key.dtype)
    masked = jnp.where(build_ok, build_key, sentinel)
    perm = jnp.argsort(masked, stable=True)
    skey = masked[perm]
    lo_t = cnt_t = None
    if domain is not None and 0 < domain <= JOIN_TABLE_MAX:
        vals = jnp.arange(domain, dtype=build_key.dtype)
        lo = searchsorted(skey, vals, "left")
        hi = searchsorted(skey, vals, "right")
        lo_t = lo.astype(jnp.int32)
        cnt_t = (hi - lo).astype(jnp.int32)
    return perm.astype(jnp.int32), skey, lo_t, cnt_t


def ranges_from_index(perm: jnp.ndarray, sorted_key: jnp.ndarray,
                      lo_table: Optional[jnp.ndarray],
                      cnt_table: Optional[jnp.ndarray],
                      probe_key: jnp.ndarray,
                      probe_ok: jnp.ndarray) -> JoinRanges:
    """build_join_ranges against a precomputed make_join_index. Dead
    build rows carry the +inf sentinel key, so they sit past every dense
    table entry / real probe key and never match."""
    if lo_table is not None:
        domain = lo_table.shape[0]
        k = jnp.clip(probe_key, 0, domain - 1)
        ok = probe_ok & (probe_key >= 0) & (probe_key < domain)
        lo = jnp.where(ok, lo_table[k].astype(jnp.int64), 0)
        hi = jnp.where(ok, lo + cnt_table[k].astype(jnp.int64), 0)
        return JoinRanges(perm, lo, hi)
    sentinel = _pos_sentinel(sorted_key.dtype)
    lo = searchsorted(sorted_key, probe_key, side="left")
    hi = searchsorted(sorted_key, probe_key, side="right")
    ok = probe_ok & (probe_key != sentinel)
    lo = jnp.where(ok, lo, 0)
    hi = jnp.where(ok, hi, 0)
    return JoinRanges(perm, lo, hi)


def expand_join_pairs(ranges: JoinRanges, total: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Materialize (probe_idx, build_idx, pair_mask) for all match pairs.
    ``total`` is the static output capacity (host-synced count, bucketed).
    Pair j belongs to the probe row p whose exclusive-offset range covers
    j; its build index is the j-offsets[p]'th sorted match."""
    counts = ranges.counts
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix
    grand_total = offsets[-1] + counts[-1]
    j = jnp.arange(total)
    p = searchsorted(offsets, j, side="right") - 1
    p = jnp.clip(p, 0, counts.shape[0] - 1)
    k = j - offsets[p]
    build_sorted_pos = ranges.lo[p] + k
    build_idx = ranges.build_perm[jnp.clip(build_sorted_pos, 0,
                                           ranges.build_perm.shape[0] - 1)]
    pair_mask = j < grand_total
    return p, build_idx, pair_mask


def range_compress_keys(
    keys: List[Tuple[np.ndarray, Optional[np.ndarray]]],
    mins: List[int],
    ranges: List[int],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pack multiple integer join keys into one int64 via range
    compression (host supplies per-key min/range from lightweight stats).
    Returns (combined_key, all_valid_mask)."""
    combined = jnp.zeros(keys[0][0].shape, dtype=jnp.int64)
    valid = None
    for (data, validity), mn, rg in zip(keys, mins, ranges):
        slot = (data.astype(jnp.int64) - mn)
        slot = jnp.clip(slot, 0, rg - 1)
        combined = combined * rg + slot
        if validity is not None:
            valid = validity if valid is None else (valid & validity)
    if valid is None:
        valid = jnp.ones(combined.shape, dtype=jnp.bool_)
    return combined, valid


# ---- hashing / key encoding (partitioning support) --------------------------


def hash64(x: jnp.ndarray) -> jnp.ndarray:
    """Deterministic 64-bit avalanche mix (splitmix64/xxh64 finalizer
    shape). Role of the reference's Murmur3/XXH64 partitioning hashes
    (common/unsafe hash/, catalyst XXH64.java) — used to route rows to
    mesh devices; must be identical on every device."""
    h = x.astype(jnp.uint64)
    h = (h ^ (h >> 33)) * jnp.uint64(0xFF51AFD7ED558CCD)
    h = (h ^ (h >> 33)) * jnp.uint64(0xC4CEB9FE1A85EC53)
    h = h ^ (h >> 33)
    return h


def hash_combine(h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Fold another column into a running row hash."""
    return hash64(h ^ (x.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)))


def orderable_int64(
    data: jnp.ndarray,
    validity: Optional[jnp.ndarray],
    ascending: bool = True,
    nulls_first: bool = True,
    rank_table: Optional[np.ndarray] = None,
) -> jnp.ndarray:
    """Encode a sort key column as int64 such that plain integer order ==
    the SQL sort order (direction + null placement). Floats use the IEEE754
    sign-flip bit trick; dictionary-coded strings go through a rank table.
    This is the analogue of Spark's sort-key *prefix* encoding
    (core/.../unsafe/sort/PrefixComparators.java) — but here the whole key
    fits the prefix, because strings are dictionary ranks."""
    if rank_table is not None:
        y = jnp.asarray(rank_table, dtype=jnp.int64)[data]
    elif jnp.issubdtype(data.dtype, jnp.floating):
        bits = jax.lax.bitcast_convert_type(
            data.astype(jnp.float64), jnp.uint64)
        sign = (bits >> 63) == 1
        u = jnp.where(sign, ~bits, bits | jnp.uint64(0x8000000000000000))
        y = (u ^ jnp.uint64(0x8000000000000000)).astype(jnp.int64)
    else:
        y = data.astype(jnp.int64)
    if not ascending:
        y = ~y  # bitwise-not reverses integer order without overflow
    if validity is not None:
        imin = jnp.iinfo(jnp.int64).min
        imax = jnp.iinfo(jnp.int64).max
        y = jnp.where(validity, y, imin if nulls_first else imax)
    return y


# ---- misc ------------------------------------------------------------------


def limit_mask(row_mask: jnp.ndarray, n: int, offset: int = 0) -> jnp.ndarray:
    """Keep only live rows with live-rank in [offset, offset+n)."""
    rank = jnp.cumsum(row_mask.astype(jnp.int64)) - 1
    return row_mask & (rank >= offset) & (rank < offset + n)


def take_permutation(data: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    return data[perm]


@partial(jax.jit, static_argnums=())
def count_live(row_mask: jnp.ndarray) -> jnp.ndarray:
    return row_mask.sum(dtype=jnp.int64)


def bucket(n: int, multiple: int = 1024) -> int:
    """Round up to a capacity bucket (jit-cache friendliness)."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple

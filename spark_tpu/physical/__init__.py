from spark_tpu.physical import kernels, operators, planner  # noqa: F401

"""Logical -> physical planning and the stage-fusing executor.

Planning mirrors SparkPlanner/SparkStrategies (reference:
sql/core/.../SparkPlanner.scala:28, SparkStrategies.scala Aggregation:522
JoinSelection:172 BasicOperators:750) collapsed into one pass — there is
a single physical choice per logical operator, with strategy decisions
(direct vs sort aggregation) deferred to trace-time metadata.

Execution replaces the whole SparkPlan.execute -> RDD -> DAGScheduler
machinery (reference: SparkPlan.scala:191, QueryExecution.scala:168):
maximal *traceable* subtrees are fused into one jitted XLA program (the
WholeStageCodegenExec.scala:627 analogue — CollapseCodegenStages:882
becomes "walk until a blocking operator"), blocking operators run
eagerly between stages with host syncs for output sizing (the AQE
stage-boundary analogue, reference: AdaptiveSparkPlanExec.scala:247).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import numpy as np

from spark_tpu import conf as CF
from spark_tpu.columnar.batch import Batch
from spark_tpu.expr import expressions as E
from spark_tpu.physical import kernels as K
from spark_tpu.physical import operators as P
from spark_tpu.plan import logical as L


def plan_physical(plan: L.LogicalPlan) -> P.PhysicalPlan:
    if isinstance(plan, L.Relation):
        return P.BatchScanExec(plan.batch)
    if isinstance(plan, L.Range):
        return P.RangeExec(plan.start, plan.end, plan.step, plan.col_name)
    if isinstance(plan, L.UnresolvedScan):
        return P.BatchScanExec(plan.source.read(plan.columns, plan.filters))
    if isinstance(plan, L.Project):
        return P.ProjectExec(plan.exprs, plan_physical(plan.child))
    if isinstance(plan, L.Filter):
        return P.FilterExec(plan.condition, plan_physical(plan.child))
    if isinstance(plan, L.Aggregate):
        return P.HashAggregateExec(plan.groupings, plan.aggregates,
                                   plan_physical(plan.child))
    if isinstance(plan, L.Sort):
        return P.SortExec(plan.orders, plan_physical(plan.child))
    if isinstance(plan, L.Limit):
        return P.LimitExec(plan.n, plan_physical(plan.child), plan.offset)
    if isinstance(plan, L.Distinct):
        cols = tuple(E.Col(n) for n in plan.schema.names)
        return P.HashAggregateExec(cols, cols, plan_physical(plan.child))
    if isinstance(plan, L.SubqueryAlias):
        return plan_physical(plan.child)
    if isinstance(plan, L.Repartition):
        # single-device: a no-op; the mesh executor re-plans it as an
        # exchange (parallel/exchange.py)
        return plan_physical(plan.child)
    if isinstance(plan, L.Sample):
        return P.SampleExec(plan.fraction, plan.seed,
                            plan_physical(plan.child),)
    if isinstance(plan, L.Window):
        from spark_tpu.physical.window import WindowExec

        return WindowExec(plan.window_exprs, plan_physical(plan.child))
    if isinstance(plan, L.Generate):
        return P.GenerateExec(plan.generator, plan.out_name,
                              plan.pos_name, plan_physical(plan.child))
    if isinstance(plan, L.Expand):
        return P.ExpandExec(plan.projections, plan.names,
                            plan_physical(plan.child))
    if isinstance(plan, L.Join):
        return P.JoinExec(plan_physical(plan.left), plan_physical(plan.right),
                          plan.how, plan.left_keys, plan.right_keys,
                          plan.condition)
    if isinstance(plan, L.Union):
        return P.UnionExec(plan_physical(plan.left), plan_physical(plan.right))
    raise NotImplementedError(f"no physical plan for {type(plan).__name__}")


# ---- stage-fused execution --------------------------------------------------

from spark_tpu.storage.lru import LruDict  # noqa: E402

#: bounded: spark.tpu.jit.stageCacheEntries (LRU beyond the cap; an
#: evicted plan recompiles on next use)
_STAGE_CACHE = LruDict("fused", CF.JIT_STAGE_CACHE_ENTRIES)


def _fully_traceable(plan: P.PhysicalPlan) -> bool:
    if isinstance(plan, P.BatchScanExec):
        return True
    return (plan.traceable and not plan.has_blocking_exprs()
            and all(_fully_traceable(c) for c in plan.children()))


def _collect_scans(plan: P.PhysicalPlan, out: List[P.BatchScanExec]) -> None:
    if isinstance(plan, P.BatchScanExec):
        out.append(plan)
        return
    for c in plan.children():
        _collect_scans(c, out)


@dataclass(eq=False)
class _ScanSlot(P.PhysicalPlan):
    """Leaf placeholder in cached stage closures: carries only the scan
    schema so cached jit functions never pin leaf device buffers."""

    scan_schema: "object"
    traceable = True

    @property
    def schema(self):
        return self.scan_schema


def _strip_leaf_data(plan: P.PhysicalPlan) -> P.PhysicalPlan:
    if isinstance(plan, P.BatchScanExec):
        return _ScanSlot(plan.batch.schema)
    fields = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        fields[f.name] = _strip_leaf_data(v) if isinstance(
            v, P.PhysicalPlan) else v
    return dataclasses.replace(plan, **fields)


def _bind_adaptive(plan: P.PhysicalPlan) -> None:
    """Attach recorded runtime stats to join nodes (the re-optimization
    step of AQE, reference: AdaptiveSparkPlanExec.getFinalPhysicalPlan:247
    — here 'between executions' instead of 'between stages'). A join
    whose previous run on these exact leaf arrays proved a unique build
    side becomes traceable and fuses."""
    for c in plan.children():
        _bind_adaptive(c)
    if isinstance(plan, P.JoinExec) and plan.how in (
            "inner", "left", "left_semi", "left_anti") and plan.left_keys:
        sk = plan.stats_key()
        plan.adaptive = P._JOIN_STATS.get(sk)
        plan.index_scan = plan.table_scan = None
        plan.index_orient = None
        if plan.adaptive is not None:
            idx = P._JOIN_INDEX.get(sk)
            if idx is not None:
                orient, ib, tb = idx
                plan.index_scan = P.BatchScanExec(ib, aux=True)
                plan.table_scan = (P.BatchScanExec(tb, aux=True)
                                   if tb is not None else None)
                plan.index_orient = orient
    elif isinstance(plan, P.HashAggregateExec) and plan.groupings \
            and not plan._static_direct_ok():
        plan.adaptive = P._AGG_STATS.get(plan.stats_key())
    elif isinstance(plan, P.GenerateExec):
        plan.adaptive = P._GEN_STATS.get(plan.stats_key())


def _adaptive_snapshot(plan: P.PhysicalPlan) -> tuple:
    """Adaptive state of every join in tree order — part of the fused
    stage cache key (plan_key alone is stable across stats changes)."""
    out = []

    def go(p: P.PhysicalPlan) -> None:
        if isinstance(p, P.JoinExec):
            # index presence/shape changes the traced program but is
            # deliberately excluded from plan_key (stats identity)
            out.append((p.adaptive, p.index_orient,
                        None if p.index_scan is None
                        else p.index_scan.plan_key(),
                        None if p.table_scan is None
                        else p.table_scan.plan_key()))
        elif isinstance(p, (P.HashAggregateExec, P.GenerateExec)):
            out.append(p.adaptive)
        elif isinstance(p, P.CompactExec):
            # plan_key is transparent for stats stability; the snapshot
            # carries the compaction so stage programs don't collide
            out.append(("compact", p.cap))
        for c in p.children():
            go(c)

    go(plan)
    return tuple(out)


def _stable_adaptive_snapshot(plan: P.PhysicalPlan) -> tuple:
    """_adaptive_snapshot for the cross-session executable store:
    identical structure, but the embedded index/table scan identities
    use the store's content-digest keys instead of plan_key() (whose
    hash(dicts) component is salted per process). Only computed on the
    fresh-stage-entry path."""
    from spark_tpu.compile.store import stable_plan_key

    out = []

    def go(p: P.PhysicalPlan) -> None:
        if isinstance(p, P.JoinExec):
            out.append((p.adaptive, p.index_orient,
                        None if p.index_scan is None
                        else stable_plan_key(p.index_scan),
                        None if p.table_scan is None
                        else stable_plan_key(p.table_scan)))
        elif isinstance(p, (P.HashAggregateExec, P.GenerateExec)):
            out.append(p.adaptive)
        elif isinstance(p, P.CompactExec):
            out.append(("compact", p.cap))
        for c in p.children():
            go(c)

    go(plan)
    return tuple(out)


def _run_fused(plan: P.PhysicalPlan) -> Batch:
    """Compile a maximal traceable subtree to one XLA program and run it.
    The jit cache is keyed on plan structure + leaf shapes/dictionaries
    (analogue of CodeGenerator.compile's generated-class cache,
    reference: codegen/CodeGenerator.scala:1442). Cached closures hold a
    leaf-stripped plan skeleton — leaf batch data arrives as arguments."""
    scans: List[P.BatchScanExec] = []
    _collect_scans(plan, scans)
    key = (plan.plan_key(), _adaptive_snapshot(plan))
    entry = _STAGE_CACHE.get(key)
    fresh = entry is None
    if fresh:
        schema_box: dict = {}
        skeleton = _strip_leaf_data(plan)

        def stage_fn(leaf_datas):
            it = iter(leaf_datas)

            def go(p: P.PhysicalPlan) -> P.Pipe:
                if isinstance(p, _ScanSlot):
                    return P.Pipe.from_batch_data(p.scan_schema, next(it))
                pipes = [go(c) for c in p.children()]
                return p.trace(pipes)

            batch = go(skeleton).to_batch()
            schema_box["schema"] = batch.schema
            return batch.data

        # the stored callable consults the cross-session executable
        # store when the compile service is active; otherwise this is
        # exactly jax.jit(stage_fn)
        from spark_tpu.compile import build_stage_callable

        entry = (build_stage_callable(
            "fused", plan, stage_fn,
            tuple(s.batch.data for s in scans), schema_box,
            extra=_stable_adaptive_snapshot(plan)), schema_box)
        _STAGE_CACHE[key] = entry
    jitted, schema_box = entry
    if fresh:
        # first call traces + XLA-compiles (or loads from the
        # persistent disk cache — metrics.compile_cache_stats says
        # which); timing it makes warmup attributable
        import time

        from spark_tpu import metrics

        t0 = time.perf_counter()
        data = jitted(tuple(s.batch.data for s in scans))
        metrics.record("stage_compile", node=plan.node_string(),
                       ms=round((time.perf_counter() - t0) * 1e3, 2))
    else:
        data = jitted(tuple(s.batch.data for s in scans))
    return Batch(schema_box["schema"], data)


#: Observed inter-stage compaction capacities per (plan, leaf-ids):
#: 0 = "compaction not worthwhile here". Replayed as explicit
#: CompactExec nodes (see _replay_compactions) so fully-traced
#: re-executions see EXACTLY the same arrays the blocking run fed to
#: downstream operators — required for _JOIN_INDEX position validity,
#: and it keeps the traced pipeline at the shrunken capacity (AQE
#: coalescing, reference: CoalesceShufflePartitions.scala).
_COMPACT_STATS = P._AdaptiveStatsCache()


def _capacity_bucket() -> int:
    """Compaction capacities round up to
    spark.tpu.adaptive.capacityBucket (active-session conf; registry
    default 1024 reproduces the historical hard-coded multiple) — the
    same bucket adaptive exchanges use, so single-device and
    distributed re-traces share one small set of capacities and the
    jit stage caches stay hot."""
    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        if sess is not None:
            return max(1, int(sess.conf.get(CF.ADAPTIVE_CAPACITY_BUCKET)))
    except Exception:
        pass
    return max(1, int(CF.ADAPTIVE_CAPACITY_BUCKET.default))


def _compact_to(batch: Batch, new_cap: int) -> Batch:
    """Route through CompactExec so the blocking-run compaction and the
    traced replay are structurally the SAME code — _JOIN_INDEX position
    validity depends on them producing bit-identical layouts."""
    node = P.CompactExec(P.BatchScanExec(batch), new_cap)
    return node.execute_blocking([batch])


def _maybe_compact(batch: Batch, child: P.PhysicalPlan) -> Batch:
    """Shrink sparse batches between stages so capacities don't cascade
    (the reference's equivalent pressure valve is AQE partition
    coalescing, CoalesceShufflePartitions.scala). The decision is
    recorded per (plan, leaves) and replayed inside later traced
    executions — see _COMPACT_STATS."""
    cap = batch.capacity
    if cap <= 4096 or isinstance(child, P.BatchScanExec):
        return batch
    sk = child.stats_key()
    new_cap = _COMPACT_STATS.get(sk)
    if new_cap is None:
        if not P.stats_recording():
            return batch  # single-shot plan: skip the sizing sync
        live = int(np.asarray(batch.data.row_mask).sum())  # host sync
        new_cap = K.bucket(live, _capacity_bucket()) \
            if live * 4 <= cap else 0
        _COMPACT_STATS.put(sk, new_cap)
    if not new_cap or new_cap >= cap:
        return batch
    return _compact_to(batch, new_cap)


def _replay_compactions(plan: P.PhysicalPlan) -> P.PhysicalPlan:
    """Insert explicit CompactExec nodes where blocking runs compacted,
    so fused traces reproduce the identical intermediate arrays."""
    if isinstance(plan, P.BatchScanExec):
        return plan
    fields = {}
    changed = False
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, P.PhysicalPlan) and not isinstance(
                v, P.BatchScanExec):
            nv = _replay_compactions(v)
            cap = _COMPACT_STATS.get(nv.stats_key())
            if cap:
                nv = P.CompactExec(nv, cap)
            if nv is not v:
                changed = True
            fields[f.name] = nv
        else:
            fields[f.name] = v
    return dataclasses.replace(plan, **fields) if changed else plan


#: Observed live output rows per (plan, leaf-array-ids): re-executions
#: compact the result to bucket(live) ON DEVICE before the host fetch
#: (see P.CompactExec). Sound for the same reason join/agg stats replay
#: is: same immutable leaves + same plan => same live count.
_OUTPUT_STATS = P._AdaptiveStatsCache()


def execute(plan: P.PhysicalPlan) -> Batch:
    """Run a physical plan: fuse what we can, block where we must."""
    plan = _replay_compactions(plan)
    _bind_adaptive(plan)
    sk = plan.stats_key()
    cap = _OUTPUT_STATS.get(sk)
    if cap is not None:
        return _execute(P.CompactExec(plan, cap))
    batch = _execute(plan)
    if P.stats_recording():
        live = int(np.asarray(batch.data.row_mask).sum())  # 1st run only
        _OUTPUT_STATS.put(sk, K.bucket(live, _capacity_bucket()))
    return batch


def _execute(plan: P.PhysicalPlan) -> Batch:
    from spark_tpu import metrics, trace

    if isinstance(plan, P.BatchScanExec):
        return plan.batch
    if _fully_traceable(plan):
        with trace.span("stage.run", op="fused"), \
                metrics.stage_timer("fused", node=plan.node_string()):
            return _run_fused(plan)
    child_batches = []
    for c in plan.children():
        b = _execute(c)
        child_batches.append(_maybe_compact(b, c))
    with trace.span("stage.run", op=type(plan).__name__), \
            metrics.stage_timer("blocking", node=plan.node_string(),
                                cap_in=[b.capacity
                                        for b in child_batches]):
        return plan.execute_blocking(child_batches)


def execute_logical(plan: L.LogicalPlan, optimize: bool = True) -> Batch:
    from spark_tpu.plan.optimizer import optimize as opt

    lp = opt(plan) if optimize else plan
    return execute(plan_physical(lp))

"""Physical operators.

Analogue of the reference's SparkPlan operator tier (reference:
sql/core/.../execution/basicPhysicalOperators.scala ProjectExec:42
FilterExec:216 RangeExec:412, aggregate/HashAggregateExec.scala:47,
SortExec.scala:40, joins/ShuffledHashJoinExec.scala:38 +
HashedRelation.scala, limit.scala) — re-architected for XLA:

- Operators are either **traceable** (pure static-shape functions that
  compose into one jitted XLA program — the whole-stage-codegen analogue,
  reference WholeStageCodegenExec.scala:627, with XLA playing Janino) or
  **blocking** (need a host sync to size their output: general hash
  aggregation, joins). The executor fuses maximal traceable subtrees.
- A pipeline carries ``(cols: {name: TV}, row_mask)``; filters flip mask
  bits, projections rebuild the dict — shapes never change mid-stage.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.columnar.batch import Batch, BatchData, ColumnData
from spark_tpu.expr import compiler as C
from spark_tpu.expr import expressions as E
from spark_tpu.expr.compiler import TV, Env
from spark_tpu.physical import kernels as K
from spark_tpu.types import Field, Schema


class Pipe:
    """Trace-time pipeline state flowing through fused operators.

    ``rows_bound``, when set, is a static upper bound on the TOTAL live
    rows across the whole mesh — tighter than ``d * capacity`` when the
    pipe was padded to a worst-case shape (fused spans pad their output
    to the capacity-ladder worst while carrying far fewer live rows).
    Chained fused spans use it to size their ladder from real row
    counts instead of the upstream padding, which is what keeps a
    k-span chain's buffers at O(total rows) rather than O(d^k * rows).
    Row-preserving operators (Project, Filter) thread it through; any
    operator that can grow row counts simply drops it, which is always
    safe (consumers fall back to d * capacity)."""

    __slots__ = ("cols", "mask", "order", "rows_bound")

    def __init__(self, cols: Dict[str, TV], mask: jnp.ndarray,
                 order: Sequence[str],
                 rows_bound: Optional[int] = None):
        self.cols = cols
        self.mask = mask
        self.order = list(order)
        self.rows_bound = rows_bound

    @property
    def capacity(self) -> int:
        return int(self.mask.shape[0])

    def env(self) -> Env:
        return Env(self.cols, self.capacity, self.mask)

    @classmethod
    def from_batch_data(cls, schema: Schema, data: BatchData) -> "Pipe":
        cols = {}
        for f, cd in zip(schema.fields, data.columns):
            d = cd.data
            want = C._jnp_dtype(f.dtype)
            if d.ndim == 1 and d.dtype != want \
                    and jnp.issubdtype(d.dtype, jnp.integer) \
                    and jnp.issubdtype(want, jnp.integer):
                # transfer-narrowed column (batch.from_numpy
                # narrow_transfer): widen back ON DEVICE at trace entry
                d = d.astype(want)
            cols[f.name] = TV(d, cd.validity, f.dtype, f.dictionary)
        return cls(cols, data.row_mask, schema.names)

    def to_batch(self) -> Batch:
        fields = []
        cds = []
        for name in self.order:
            tv = self.cols[name]
            fields.append(Field(name, tv.dtype,
                                nullable=tv.validity is not None,
                                dictionary=tv.dictionary))
            cds.append(ColumnData(tv.data, tv.validity))
        return Batch(Schema(tuple(fields)),
                     BatchData(tuple(cds), self.mask))


class PhysicalPlan:
    """Base physical operator."""

    def children(self) -> Tuple["PhysicalPlan", ...]:
        return ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    #: True when ``trace`` composes into a fused jit program.
    traceable: bool = False

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        raise NotImplementedError(f"{type(self).__name__} is not traceable")

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        """Eager execution with host syncs allowed."""
        pipes = [Pipe.from_batch_data(b.schema, b.data) for b in child_batches]
        return self.trace(pipes).to_batch()

    def stats_key(self) -> tuple:
        """Identity for adaptive runtime stats: plan structure + leaf
        array ids (jax arrays are immutable, so id-equality implies
        data-equality — stats recorded for these exact arrays can be
        replayed as static trace constants). Returns (key, arrays): the
        cache weakrefs ``arrays`` and self-evicts when any dies, so a
        recycled id can never alias a live entry.

        Memoized per plan instance: executes call this from several
        walks (_replay_compactions, _bind_adaptive, _maybe_compact) and
        each computation re-traverses the whole subtree. Plan nodes are
        rebuilt per execution and leaves are immutable, so the memo
        cannot go stale within an instance's life."""
        cached = self.__dict__.get("_stats_key_memo")
        if cached is not None:
            return cached
        scans: List["BatchScanExec"] = []

        def collect(p: PhysicalPlan) -> None:
            if isinstance(p, BatchScanExec):
                if not p.aux:  # derived data, not identity
                    scans.append(p)
                return
            for c in p.children():
                collect(c)

        collect(self)
        pins = tuple(cd.data for s in scans for cd in s.batch.data.columns)
        ids = tuple(id(a) for a in pins)
        out = ((self.plan_key(), ids), pins)
        self.__dict__["_stats_key_memo"] = out
        return out

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + self.node_string()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children()])

    def node_string(self) -> str:
        return type(self).__name__

    def plan_key(self) -> tuple:
        """Structural cache key for fused-stage jit caching."""
        return (type(self).__name__,) + tuple(
            c.plan_key() for c in self.children())

    def has_blocking_exprs(self) -> bool:
        """Any host-only expression (arrow UDF) in THIS node's fields —
        such an operator must run on the eager path regardless of its
        own traceable flag."""
        import dataclasses as _dc

        def scan(v) -> bool:
            if isinstance(v, E.Expression):
                return E.contains_blocking(v)
            if isinstance(v, tuple):
                return any(scan(x) for x in v)
            return False

        try:
            fields = _dc.fields(self)
        except TypeError:
            return False
        return any(scan(getattr(self, f.name)) for f in fields)

    def __repr__(self):
        return self.tree_string()


# ---- leaves ----------------------------------------------------------------


@dataclass(eq=False)
class BatchScanExec(PhysicalPlan):
    """Scan over an in-memory device batch (+ input port index for fused
    stages). Analogue of LocalTableScanExec / columnar scan output."""

    batch: Batch
    #: aux scans carry DERIVED device data (cached join indexes) fully
    #: determined by the real leaves — excluded from stats_key identity
    aux: bool = False
    traceable = True

    @property
    def schema(self) -> Schema:
        return self.batch.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        raise AssertionError("leaf scan is fed by the stage runner")

    def node_string(self):
        return f"BatchScan{list(self.schema.names)}"

    def plan_key(self):
        dicts = tuple(f.dictionary for f in self.batch.schema.fields)
        return ("BatchScan", self.batch.capacity,
                tuple((f.name, repr(f.dtype)) for f in self.batch.schema.fields),
                hash(dicts))


@dataclass(eq=False)
class RangeExec(PhysicalPlan):
    """On-device iota (reference: basicPhysicalOperators.scala
    RangeExec:412; RangeBenchmark 12,110 M rows/s is the number to beat —
    here the whole range is one fused XLA iota that usually never
    materializes)."""

    start: int
    end: int
    step: int
    col_name: str = "id"
    traceable = True

    @property
    def num_rows(self) -> int:
        if self.step == 0:
            return 0
        n = (self.end - self.start + self.step - (1 if self.step > 0 else -1))
        return max(0, n // self.step)

    @property
    def schema(self) -> Schema:
        return Schema((Field(self.col_name, T.INT64, nullable=False),))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        n = self.num_rows
        cap = K.bucket(n)
        ids = self.start + jnp.arange(cap, dtype=jnp.int64) * self.step
        mask = jnp.arange(cap) < n
        return Pipe({self.col_name: TV(ids, None, T.INT64, None)}, mask,
                    [self.col_name])

    def plan_key(self):
        return ("Range", self.start, self.end, self.step, self.col_name)


# ---- pipelined unary ops ----------------------------------------------------


@dataclass(eq=False)
class ProjectExec(PhysicalPlan):
    exprs: Tuple[E.Expression, ...]
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.exprs:
            dt = e.data_type(cs)
            if isinstance(dt, T.MapType):
                # maps decompose into '#keys'/'#vals' array components
                # plus their length companions (types.MapType)
                nullable = e.nullable(cs)
                for comp, el in ((T.map_keys_col(e.name), dt.key),
                                 (T.map_vals_col(e.name), dt.value)):
                    fields.append(Field(comp, T.ArrayType(el), nullable))
                    fields.append(Field(T.array_len_col(comp), T.INT32,
                                        nullable=False))
                continue
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            fields.append(Field(e.name, dt, e.nullable(cs), dictionary))
            if isinstance(dt, T.ArrayType):
                # hidden per-row length companion (types.ArrayType)
                fields.append(Field(T.array_len_col(e.name), T.INT32,
                                    nullable=False))
        return Schema(tuple(fields))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        cols = {}
        order = []

        def add_array(name, tv):
            cols[name] = tv
            order.append(name)
            ln = T.array_len_col(name)
            cols[ln] = TV(
                (tv.lengths if tv.lengths is not None
                 else jnp.full((pipe.capacity,),
                               tv.data.shape[1] if tv.data.ndim > 1
                               else 0, dtype=jnp.int32)),
                None, T.INT32, None)
            order.append(ln)

        for e in self.exprs:
            try:
                dt = e.data_type(self.child.schema)
            except Exception:
                dt = None
            if isinstance(dt, T.MapType):
                ktv, vtv = C.evaluate_map_pair(e, env)
                add_array(T.map_keys_col(e.name), ktv)
                add_array(T.map_vals_col(e.name), vtv)
                continue
            tv = C.evaluate(e, env)
            if isinstance(tv.dtype, T.ArrayType):
                add_array(e.name, tv)
                continue
            cols[e.name] = tv
            order.append(e.name)
        return Pipe(cols, pipe.mask, order, rows_bound=pipe.rows_bound)

    def node_string(self):
        return f"Project[{', '.join(str(e) for e in self.exprs)}]"

    def plan_key(self):
        return ("Project", tuple(E.expr_key(e) for e in self.exprs),
                self.child.plan_key())


@dataclass(eq=False)
class FilterExec(PhysicalPlan):
    condition: E.Expression
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        tv = C.evaluate(self.condition, pipe.env())
        keep = tv.data & tv.valid_or_true(pipe.capacity)
        return Pipe(pipe.cols, pipe.mask & keep, pipe.order,
                    rows_bound=pipe.rows_bound)

    def node_string(self):
        return f"Filter[{self.condition}]"

    def plan_key(self):
        return ("Filter", E.expr_key(self.condition), self.child.plan_key())


@dataclass(eq=False)
class SortExec(PhysicalPlan):
    """Global sort: chained stable argsorts (reference: SortExec.scala:40
    backed by UnsafeExternalSorter/RadixSort.java:25 — XLA's on-device
    sort replaces both)."""

    orders: Tuple[E.SortOrder, ...]
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        keys = []
        for o in self.orders:
            tv = C.evaluate(o.child, env)
            keys.append(K.SortKey(tv.data, tv.validity, o.ascending,
                                  o.nulls_first_resolved))
        perm = K.lexsort_permutation(keys, pipe.mask)
        cols = {
            name: TV(tv.data[perm],
                     None if tv.validity is None else tv.validity[perm],
                     tv.dtype, tv.dictionary)
            for name, tv in pipe.cols.items()
        }
        return Pipe(cols, pipe.mask[perm], pipe.order)

    def node_string(self):
        return f"Sort[{', '.join(map(str, self.orders))}]"

    def plan_key(self):
        return ("Sort",
                tuple((E.expr_key(o.child), o.ascending,
                       o.nulls_first_resolved) for o in self.orders),
                self.child.plan_key())


@dataclass(eq=False)
class LimitExec(PhysicalPlan):
    """Keep first n live rows (reference: limit.scala GlobalLimitExec)."""

    n: int
    child: PhysicalPlan
    offset: int = 0
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        return Pipe(pipe.cols, K.limit_mask(pipe.mask, self.n, self.offset),
                    pipe.order)

    def node_string(self):
        return f"Limit[{self.n}]"

    def plan_key(self):
        return ("Limit", self.n, self.offset, self.child.plan_key())


@dataclass(eq=False)
class ExpandExec(PhysicalPlan):
    """One output block per projection, stacked (reference:
    execution/ExpandExec.scala:1): capacity = child capacity x G,
    statically shaped — no sizing sync, fuses with the aggregation
    above it (the ROLLUP/CUBE path is one XLA program end to end)."""

    projections: Tuple[Tuple[E.Expression, ...], ...]
    names: Tuple[str, ...]
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @functools.cached_property
    def schema(self) -> Schema:
        from spark_tpu.plan import logical as L

        return L.Expand(self.projections, self.names,
                        _SchemaOnly(self.child.schema)).schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        n = pipe.capacity
        out_schema = self.schema
        cols: Dict[str, TV] = {}
        for i, name in enumerate(self.names):
            out_f = out_schema.fields[i]
            tvs = [C.evaluate(proj[i], env) for proj in self.projections]
            if isinstance(out_f.dtype, T.StringType):
                union, tables = C.unify_dictionaries(
                    tuple(tv.dictionary or () for tv in tvs))
                datas = [(jnp.asarray(tb)[tv.data]
                          if len(tv.dictionary or ()) else tv.data)
                         for tv, tb in zip(tvs, tables)]
                dictionary: Optional[Tuple[str, ...]] = union
            else:
                datas = [C._cast_data(tv.data, tv.dtype, out_f.dtype)
                         for tv in tvs]
                dictionary = None
            data = jnp.concatenate(datas)
            validity = None
            if any(tv.validity is not None for tv in tvs):
                validity = jnp.concatenate(
                    [tv.valid_or_true(n) for tv in tvs])
            cols[name] = TV(data, validity, out_f.dtype, dictionary)
        mask = jnp.concatenate([pipe.mask] * len(self.projections))
        return Pipe(cols, mask, list(self.names))

    def node_string(self):
        return f"Expand[{len(self.projections)} sets]"

    def plan_key(self):
        return ("Expand",
                tuple(tuple(E.expr_key(e) for e in p)
                      for p in self.projections),
                self.names, self.child.plan_key())


@dataclass(eq=False)
class GenerateExec(PhysicalPlan):
    """Sized row expansion for explode/posexplode (reference:
    execution/GenerateExec.scala:1): one output row per live array
    element, parent columns replicated by gather — the exact shape of
    the join pair expansion, so it reuses the same offsets+searchsorted
    kernel and the same adaptive capacity-replay discipline (_GEN_STATS
    records the bucketed element total for these leaves; re-executions
    trace with a static capacity, no sizing sync)."""

    generator: E.Expression  # E.Explode
    out_name: str
    pos_name: Optional[str]
    child: PhysicalPlan
    adaptive: Optional[int] = None

    def children(self):
        return (self.child,)

    @property
    def traceable(self) -> bool:  # type: ignore[override]
        return self.adaptive is not None

    @functools.cached_property
    def schema(self) -> Schema:
        from spark_tpu.plan import logical as L

        return L.Generate(self.generator, self.out_name, self.pos_name,
                          _SchemaOnly(self.child.schema)).schema

    def _expand(self, pipe: Pipe, cap: int, tv=None) -> Pipe:
        if tv is None:
            tv = C.evaluate(self.generator.child, pipe.env())
        if tv.lengths is None or tv.data.ndim != 2:
            raise NotImplementedError("explode over a non-array value")
        ok = pipe.mask & tv.valid_or_true(pipe.capacity)
        counts = jnp.where(ok, tv.lengths.astype(jnp.int64), 0)
        offsets = jnp.cumsum(counts) - counts
        total = offsets[-1] + counts[-1]
        j = jnp.arange(cap)
        p = K.searchsorted(offsets, j, side="right") - 1
        p = jnp.clip(p, 0, pipe.capacity - 1)
        k = j - offsets[p]
        out_mask = j < total
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for name in pipe.order:
            src = pipe.cols[name]
            cols[name] = TV(
                src.data[p],
                None if src.validity is None else src.validity[p],
                src.dtype, src.dictionary,
                None if src.lengths is None else src.lengths[p])
            order.append(name)
        if self.pos_name is not None:
            cols[self.pos_name] = TV(k.astype(jnp.int32), None, T.INT32,
                                     None)
            order.append(self.pos_name)
        el = jnp.take_along_axis(
            tv.data[p], jnp.clip(k, 0, tv.data.shape[1] - 1)[:, None],
            axis=1)[:, 0]
        cols[self.out_name] = TV(el, None, tv.dtype.element,
                                 tv.dictionary)
        order.append(self.out_name)
        return Pipe(cols, out_mask, order)

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        return self._expand(child_pipes[0], self.adaptive)

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        pipe = Pipe.from_batch_data(child_batches[0].schema,
                                    child_batches[0].data)
        tv = C.evaluate(self.generator.child, pipe.env())
        if tv.lengths is None:
            raise NotImplementedError("explode over a non-array value")
        ok = pipe.mask & tv.valid_or_true(pipe.capacity)
        total = int(jax.device_get(jnp.sum(
            jnp.where(ok, tv.lengths.astype(jnp.int64), 0))))
        cap = K.bucket(total)
        sk = self.stats_key()
        if sk not in _GEN_STATS:
            _GEN_STATS.put(sk, cap)
        return self._expand(pipe, cap, tv).to_batch()

    def node_string(self):
        return f"Generate[{self.generator} AS {self.out_name}]"

    def plan_key(self):
        return ("Generate", E.expr_key(self.generator), self.out_name,
                self.pos_name, self.child.plan_key())


@dataclass(eq=False)
class _SchemaOnly(PhysicalPlan):
    """Wrap a schema as a plan-shaped object for schema composition."""

    wrapped: Schema
    traceable = False

    @property
    def schema(self) -> Schema:
        return self.wrapped


@dataclass(eq=False)
class CompactExec(PhysicalPlan):
    """Gather live rows to the front and truncate to a recorded bucketed
    capacity — planned at the query root from output-size stats
    (planner._OUTPUT_STATS) so the host fetch moves ``bucket(live)``
    rows instead of the full pipeline capacity. On a tunneled TPU the
    fetch is latency- and bandwidth-bound (~120 ms + ~11 MB/s measured),
    so fetching a 10-row result at a 32k capacity dominated short
    queries. AQE-style output coalescing (reference analogue:
    CoalesceShufflePartitions.scala). Stable compaction preserves sorted
    row order."""

    child: PhysicalPlan
    cap: int
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        if self.cap >= pipe.capacity:
            return pipe
        idx = K.compaction_permutation(pipe.mask)[: self.cap]
        cols: Dict[str, TV] = {}
        for name in pipe.order:
            tv = pipe.cols[name]
            cols[name] = TV(
                tv.data[idx],
                None if tv.validity is None else tv.validity[idx],
                tv.dtype, tv.dictionary)
        return Pipe(cols, pipe.mask[idx], pipe.order)

    def node_string(self):
        return f"Compact[{self.cap}]"

    def plan_key(self):
        # TRANSPARENT: adaptive stats recorded on a blocking run (where
        # the executor compacts between stages invisibly) must still be
        # found when the replayed plan carries explicit CompactExec
        # nodes. The stage cache distinguishes compaction via
        # planner._adaptive_snapshot instead.
        return self.child.plan_key()


@dataclass(eq=False)
class SampleExec(PhysicalPlan):
    fraction: float
    seed: int
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        key = jax.random.PRNGKey(self.seed)
        u = jax.random.uniform(key, (pipe.capacity,))
        return Pipe(pipe.cols, pipe.mask & (u < self.fraction), pipe.order)

    def plan_key(self):
        return ("Sample", self.fraction, self.seed, self.child.plan_key())


@dataclass(eq=False)
class UnionExec(PhysicalPlan):
    left: PhysicalPlan
    right: PhysicalPlan
    traceable = True

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        return self.left.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        lp, rp = child_pipes
        cols = {}
        order = []
        for lname, rname in zip(lp.order, rp.order):
            lt = lp.cols[lname]
            rt = rp.cols[rname]
            out_dt = lt.dtype if type(lt.dtype) is type(rt.dtype) \
                else T.common_type(lt.dtype, rt.dtype)
            ld, rd = lt.data, rt.data
            dictionary = None
            if isinstance(out_dt, T.StringType):
                union, (tl, tr) = C.unify_dictionaries(
                    (lt.dictionary or (), rt.dictionary or ()))
                ld = jnp.asarray(tl)[lt.data] if len(lt.dictionary or ()) else lt.data
                rd = jnp.asarray(tr)[rt.data] if len(rt.dictionary or ()) else rt.data
                dictionary = union
            else:
                ld = C._cast_data(ld, lt.dtype, out_dt)
                rd = C._cast_data(rd, rt.dtype, out_dt)
            data = jnp.concatenate([ld, rd])
            if lt.validity is None and rt.validity is None:
                validity = None
            else:
                validity = jnp.concatenate([
                    lt.valid_or_true(lp.capacity), rt.valid_or_true(rp.capacity)])
            cols[lname] = TV(data, validity, out_dt, dictionary)
            order.append(lname)
        mask = jnp.concatenate([lp.mask, rp.mask])
        return Pipe(cols, mask, order)

    def plan_key(self):
        return ("Union", self.left.plan_key(), self.right.plan_key())


# ---- aggregation ------------------------------------------------------------

_DIRECT_CARDINALITY_LIMIT = 1 << 22  # packed-key segment count bound


def _agg_primitives(agg: E.AggregateExpression) -> List[str]:
    if isinstance(agg, E.Sum):
        return ["sum"]
    if isinstance(agg, E.Count):
        return ["count"]
    if isinstance(agg, E.Avg):
        return ["sum", "count"]
    if isinstance(agg, E.Min):
        return ["min"]
    if isinstance(agg, E.Max):
        return ["max"]
    if isinstance(agg, E.StddevVariance):
        return ["count", "sum", "sumsq"]
    if isinstance(agg, E.First):
        return ["first"]
    raise NotImplementedError(f"aggregate {agg!r}")


def rewrite_agg_outputs(
    groupings: Tuple[E.Expression, ...],
    aggregates: Tuple[E.Expression, ...],
) -> Tuple[Tuple[E.Expression, ...], List[E.AggregateExpression]]:
    """Rewrite output expressions so aggregate calls become __agg{i} col
    refs and grouping subtrees become __key{j} col refs; returns the
    rewritten outputs plus the distinct aggregate calls (the physical
    aggregation list). Analogue of the planner's PhysicalAggregation
    pattern (reference: planning/patterns.scala)."""
    agg_calls: List[E.AggregateExpression] = []
    agg_keys: List[tuple] = []
    grouping_keys = [E.expr_key(g) for g in groupings]

    def rewrite(e: E.Expression) -> E.Expression:
        """Top-down: a whole subtree matching a grouping / aggregate is
        replaced before descending (descending first would corrupt
        aggregate children that reference grouping columns)."""
        sk = E.expr_key(e)
        for j, gk in enumerate(grouping_keys):
            if sk == gk:
                return E.Col(f"__key{j}")
        if isinstance(e, E.AggregateExpression):
            for i, k in enumerate(agg_keys):
                if k == sk:
                    return E.Col(f"__agg{i}")
            agg_calls.append(e)
            agg_keys.append(sk)
            return E.Col(f"__agg{len(agg_calls) - 1}")
        if isinstance(e, E.Alias):
            return E.Alias(rewrite(e.child), e.alias_name)
        # generic rebuild with rewritten expression-valued fields
        new_fields = {}
        changed = False
        for fl in dataclasses.fields(e):
            v = getattr(e, fl.name)
            if isinstance(v, E.Expression):
                nv = rewrite(v)
                changed |= nv is not v
                new_fields[fl.name] = nv
            elif isinstance(v, tuple) and any(
                    isinstance(x, (E.Expression, tuple)) for x in v):
                nv_list = []
                for x in v:
                    if isinstance(x, E.Expression):
                        nx = rewrite(x)
                        changed |= nx is not x
                        nv_list.append(nx)
                    elif isinstance(x, tuple):
                        nx = tuple(rewrite(y) if isinstance(y, E.Expression)
                                   else y for y in x)
                        changed |= nx != x
                        nv_list.append(nx)
                    else:
                        nv_list.append(x)
                new_fields[fl.name] = tuple(nv_list)
            else:
                new_fields[fl.name] = v
        return dataclasses.replace(e, **new_fields) if changed else e

    outputs = []
    for e in aggregates:
        name = e.name
        ne = rewrite(e)
        if ne.name != name:
            ne = E.Alias(ne, name)
        outputs.append(ne)
    return tuple(outputs), agg_calls


def group_key_codes(key_tvs: List[TV]):
    """Small-int codes + cardinalities for direct (packed) grouping.
    Raises AssertionError when a key has no trace-time cardinality."""
    codes, validities, cards = [], [], []
    for tv in key_tvs:
        if isinstance(tv.dtype, T.BooleanType):
            codes.append(tv.data.astype(jnp.int32))
            validities.append(tv.validity)
            cards.append(2)
        elif isinstance(tv.dtype, T.StringType) and tv.dictionary is not None:
            codes.append(tv.data)
            validities.append(tv.validity)
            cards.append(max(1, len(tv.dictionary)))
        else:
            raise AssertionError(
                "direct agg path needs trace-time key cardinality")
    return codes, validities, cards


def sorted_groups(pipe: Pipe, key_tvs: List[TV]):
    """Sort rows by grouping keys and assign change-flag group ids.
    Returns (sorted_pipe, sorted_key_tvs, seg_ids, num_groups_traced)."""
    keys = [K.SortKey(tv.data, tv.validity, True, True) for tv in key_tvs]
    perm = K.lexsort_permutation(keys, pipe.mask)

    def take(tv: TV) -> TV:
        return TV(tv.data[perm],
                  None if tv.validity is None else tv.validity[perm],
                  tv.dtype, tv.dictionary)

    spipe = Pipe({name: take(tv) for name, tv in pipe.cols.items()},
                 pipe.mask[perm], pipe.order)
    sorted_keys = [take(tv) for tv in key_tvs]
    seg, ng = K.group_ids_from_sorted(
        [(tv.data, tv.validity) for tv in sorted_keys], spipe.mask)
    return spipe, sorted_keys, seg, ng


def first_group_keys(sorted_keys: List[TV], seg, mask, num_segments: int,
                     capacity: int, sorted_seg: bool = False) -> List[TV]:
    """Representative (first-row) key values per group."""
    out = []
    for tv in sorted_keys:
        data, found = K.seg_first(tv.data, seg, mask, num_segments, capacity,
                                  sorted_seg)
        if tv.validity is None:
            valid = None
        else:
            vdata, _ = K.seg_first(tv.validity, seg, mask, num_segments,
                                   capacity, sorted_seg)
            valid = vdata & found
        out.append(TV(data, valid, tv.dtype, tv.dictionary))
    return out


def _distinct_mask_cached(env: Env, child: E.Expression, tv: TV, seg,
                          ok) -> "jnp.ndarray":
    """distinct_first_mask memoized per (env, child expr): N DISTINCT
    aggregates over one column share a single (seg, value) lexsort."""
    cache = getattr(env, "_distinct_cache", None)
    if cache is None:
        cache = {}
        env._distinct_cache = cache
    key = E.expr_key(child)
    if key not in cache:
        cache[key] = K.distinct_first_mask(tv.data, seg, ok)
    return cache[key]


def decimal_sum_type(dt: "T.DecimalType") -> "T.DecimalType":
    """Sum widens decimals by 10 integral digits (Sum.scala)."""
    return T.bounded_decimal(dt.precision + 10, dt.scale)


def decimal_avg(total, cnt, dt: "T.DecimalType"):
    """Exact decimal average from a scaled-int sum and a count:
    (sum * 10^(s'-s)) / count with HALF_UP rounding, result scale s+4
    (Average.scala). Shared by the single-device and mesh paths."""
    out_dt = T.bounded_decimal(dt.precision + 4, dt.scale + 4)
    num = total * (10 ** (out_dt.scale - dt.scale))
    cc = jnp.maximum(cnt, 1)
    data = jnp.sign(num) * ((jnp.abs(num) + cc // 2) // cc)
    return data, out_dt


def _compute_agg(agg: E.AggregateExpression, env: Env, seg, mask,
                 num_segments: int, capacity: int,
                 sorted_seg: bool = False) -> TV:
    """Compute one aggregate over segments. Nulls in the input are
    excluded per SQL semantics; a group with no valid input yields NULL
    (except count). ``sorted_seg`` marks monotone segment ids (the
    sort-agg path) unlocking the cumsum-based kernels — scatter-add is
    pathologically slow on TPU (see kernels.py)."""
    if isinstance(agg, E.Count) and agg.child is None:
        cnt = K.seg_count(seg, mask, num_segments, sorted_seg)
        return TV(cnt, None, T.INT64, None)

    child = agg.child  # type: ignore[attr-defined]
    tv = C.evaluate(child, env)
    ok = mask & tv.valid_or_true(capacity)
    any_valid = K.seg_count(seg, ok, num_segments, sorted_seg) > 0
    if getattr(agg, "distinct", False):
        # DISTINCT: keep one ok row per (group, value); any_valid is
        # computed before dedup (unchanged by it anyway).
        ok = ok & _distinct_mask_cached(env, agg.child, tv, seg, ok)

    if isinstance(agg, E.Count):
        cnt = K.seg_count(seg, ok, num_segments, sorted_seg)
        return TV(cnt, None, T.INT64, None)
    if isinstance(agg, E.Sum):
        if isinstance(tv.dtype, T.DecimalType):
            # exact scaled-int64 sum (reference: Sum.scala resultType)
            s = K.seg_sum(tv.data, seg, ok, num_segments, sorted_seg)
            return TV(s, any_valid, decimal_sum_type(tv.dtype), None)
        out_dt = T.INT64 if tv.dtype.is_integral else tv.dtype
        data = tv.data.astype(C._jnp_dtype(out_dt))
        s = K.seg_sum(data, seg, ok, num_segments, sorted_seg)
        return TV(s, any_valid, out_dt, None)
    if isinstance(agg, E.Avg):
        c = K.seg_count(seg, ok, num_segments, sorted_seg)
        if isinstance(tv.dtype, T.DecimalType):
            total = K.seg_sum(tv.data, seg, ok, num_segments, sorted_seg)
            data, out_dt = decimal_avg(total, c, tv.dtype)
            return TV(data, any_valid, out_dt, None)
        s = K.seg_sum(tv.data.astype(jnp.float64), seg, ok, num_segments,
                      sorted_seg)
        data = s / jnp.maximum(c, 1)
        return TV(data, any_valid, T.FLOAT64, None)
    if isinstance(agg, E.Min):
        m = K.seg_min(tv.data, seg, ok, num_segments, sorted_seg)
        return TV(m, any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.Max):
        m = K.seg_max(tv.data, seg, ok, num_segments, sorted_seg)
        return TV(m, any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.StddevVariance):
        x = tv.data.astype(jnp.float64)
        c = K.seg_count(seg, ok, num_segments, sorted_seg).astype(jnp.float64)
        s = K.seg_sum(x, seg, ok, num_segments, sorted_seg)
        s2 = K.seg_sum(x * x, seg, ok, num_segments, sorted_seg)
        m2 = s2 - (s * s) / jnp.maximum(c, 1.0)
        m2 = jnp.maximum(m2, 0.0)
        kind = agg.kind
        denom = c - 1.0 if kind.endswith("_samp") else c
        var = m2 / jnp.maximum(denom, 1.0)
        data = jnp.sqrt(var) if kind.startswith("stddev") else var
        enough = c >= (2.0 if kind.endswith("_samp") else 1.0)
        return TV(data, any_valid & enough, T.FLOAT64, None)
    if isinstance(agg, E.First):
        use = ok if agg.ignore_nulls else mask
        data, found = K.seg_first(tv.data, seg, use, num_segments, capacity,
                                  sorted_seg)
        valid = found if tv.validity is None else (
            found & K.seg_first(tv.valid_or_true(capacity), seg, use,
                                num_segments, capacity, sorted_seg)[0])
        return TV(data, valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.Percentile):
        # EXACT per-group percentile: one (group, value) lexsort, then a
        # rank gather vectorized over all groups — same device sort
        # every blocking aggregate pays, so no reason to approximate
        # (reference: aggregate/ApproximatePercentile.scala:81)
        q = float(agg.percentage)
        perm = K.lexsort_permutation(
            [K.SortKey(seg, None, True, True),
             K.SortKey(tv.data, tv.validity, True, True)], ok)
        svals = tv.data[perm]
        cnt = K.seg_count(seg, ok, num_segments, sorted_seg)
        starts = jnp.cumsum(cnt) - cnt
        hi_cap = capacity - 1
        if agg.interpolate:
            fvals = C._cast_data(svals, tv.dtype, T.FLOAT64)
            pos = q * (cnt - 1).astype(jnp.float64)
            lo = jnp.floor(pos).astype(jnp.int64)
            hi = jnp.ceil(pos).astype(jnp.int64)
            frac = pos - lo.astype(jnp.float64)
            vlo = fvals[jnp.clip(starts + lo, 0, hi_cap)]
            vhi = fvals[jnp.clip(starts + hi, 0, hi_cap)]
            return TV(vlo + (vhi - vlo) * frac, any_valid, T.FLOAT64,
                      None)
        rank = jnp.clip(jnp.ceil(q * cnt).astype(jnp.int64) - 1, 0,
                        jnp.maximum(cnt - 1, 0))
        data = svals[jnp.clip(starts + rank, 0, hi_cap)]
        return TV(data, any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.Collect):
        import jax as _jax

        if isinstance(seg, _jax.core.Tracer):
            raise NotImplementedError(
                "collect_list/collect_set have a data-dependent output "
                "width (the largest group) — blocking execution only")
        if agg.unique:
            ok = ok & _distinct_mask_cached(env, agg.child, tv, seg, ok)
        keys = [K.SortKey(seg, None, True, True)]
        if agg.unique:
            keys.append(K.SortKey(tv.data, tv.validity, True, True))
        perm = K.lexsort_permutation(keys, ok)  # stable: keeps row order
        svals = tv.data[perm]
        cnt = K.seg_count(seg, ok, num_segments, sorted_seg)
        starts = jnp.cumsum(cnt) - cnt
        width = max(int(jnp.max(cnt)) if num_segments else 0, 1)
        idx = starts[:, None] + jnp.arange(width)[None, :]
        data2 = svals[jnp.clip(idx, 0, capacity - 1)]
        return TV(data2, None, T.ArrayType(tv.dtype), tv.dictionary,
                  cnt.astype(jnp.int32))
    raise NotImplementedError(f"aggregate {agg!r}")


@dataclass(eq=False)
class HashAggregateExec(PhysicalPlan):
    """Group-by aggregation (reference: HashAggregateExec.scala:47 +
    TungstenAggregationIterator.scala:82 over BytesToBytesMap.java).

    Two device strategies, chosen from trace-time metadata:
    - **direct**: every grouping key has trace-time cardinality (string
      dictionary / boolean) -> mixed-radix pack to dense group ids ->
      segment reductions. No sort, no sync, fully fusable.
    - **sort**: sort rows by keys, change-flag cumsum assigns group ids,
      host-sync the group count to size the output (the one 'spill to
      host control' point, analogue of the hash-map fallback-to-sort in
      ObjectHashAggregateExec).
    """

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: PhysicalPlan
    #: bound by the planner from _AGG_STATS: observed group count, which
    #: makes the sort-based path traceable with a static output capacity
    adaptive: Optional[int] = None

    def children(self):
        return (self.child,)

    @property
    def traceable(self) -> bool:  # type: ignore[override]
        if any(isinstance(a, E.Collect)
               for e in self.aggregates
               for a in E.collect_aggregates(e)):
            return False  # output width = largest group: blocking only
        return self._static_direct_ok() or self.adaptive is not None

    def _static_direct_ok(self) -> bool:
        """Can we guarantee the direct path from schema info alone?"""
        cs = self.child.schema
        total = 1
        for g in self.groupings:
            dt = g.data_type(cs)
            if isinstance(dt, T.BooleanType):
                total *= 3
            elif isinstance(dt, T.StringType):
                inner = E.strip_alias(g)
                if not (isinstance(inner, E.Col) and inner.col_name in cs
                        and cs.field(inner.col_name).dictionary is not None):
                    return False
                total *= len(cs.field(inner.col_name).dictionary) + 1
            else:
                return False
            if total > _DIRECT_CARDINALITY_LIMIT:
                return False
        return True

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.aggregates:
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            elif isinstance(inner, (E.Min, E.Max, E.First)):
                c = E.strip_alias(inner.child)
                if isinstance(c, E.Col) and c.col_name in cs:
                    dictionary = cs.field(c.col_name).dictionary
            dt = e.data_type(cs)
            fields.append(Field(e.name, dt, e.nullable(cs), dictionary))
            if isinstance(dt, T.ArrayType):
                # hidden per-row length companion (types.ArrayType)
                fields.append(Field(T.array_len_col(e.name), T.INT32,
                                    nullable=False))
        return Schema(tuple(fields))

    # -- shared epilogue ------------------------------------------------------

    def _finalize(self, key_tvs: List[TV], agg_tvs: List[TV],
                  out_mask: jnp.ndarray, num_segments: int) -> Pipe:
        outputs, _ = rewrite_agg_outputs(self.groupings, self.aggregates)
        cols = {f"__key{j}": tv for j, tv in enumerate(key_tvs)}
        cols.update({f"__agg{i}": tv for i, tv in enumerate(agg_tvs)})
        env = Env(cols, num_segments)
        out_cols = {}
        order = []
        for e in outputs:
            tv = C.evaluate(e, env)
            out_cols[e.name] = tv
            order.append(e.name)
            if isinstance(tv.dtype, T.ArrayType):
                ln = T.array_len_col(e.name)
                out_cols[ln] = TV(
                    (tv.lengths if tv.lengths is not None
                     else jnp.full((num_segments,),
                                   tv.data.shape[1] if tv.data.ndim > 1
                                   else 0, dtype=jnp.int32)),
                    None, T.INT32, None)
                order.append(ln)
        return Pipe(out_cols, out_mask, order)

    # -- direct (packed-key) path --------------------------------------------

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        if not self._static_direct_ok():
            return self._trace_sorted(pipe)
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]
        codes, validities, cards = group_key_codes(key_tvs)

        if not key_tvs:
            seg = jnp.zeros((cap,), dtype=jnp.int32)
            num_segments = 1
        else:
            seg, num_segments = K.pack_codes(codes, validities, cards)
            seg = seg.astype(jnp.int32)

        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [_compute_agg(a, env, seg, pipe.mask, num_segments, cap)
                   for a in agg_calls]

        group_present = K.seg_count(seg, pipe.mask, num_segments) > 0
        if not key_tvs:
            out_mask = jnp.ones((1,), dtype=jnp.bool_)
            out_keys: List[TV] = []
        else:
            out_mask = group_present
            nullable = [v is not None for v in validities]
            unpacked = K.unpack_code(jnp.arange(num_segments), cards, nullable)
            out_keys = []
            for (code, valid), tv in zip(unpacked, key_tvs):
                data = code.astype(C._jnp_dtype(tv.dtype))
                out_keys.append(TV(data, valid, tv.dtype, tv.dictionary))
        return self._finalize(out_keys, agg_tvs, out_mask, max(1, num_segments))

    # -- sort-based path ------------------------------------------------------

    def _trace_sorted(self, pipe: Pipe) -> Pipe:
        """Sort-based aggregation with STATIC output capacity from
        adaptive stats (the group count observed on the first, blocking
        execution of these exact leaf arrays) — no host sync, fusable."""
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]
        pipe2, sorted_keys, seg, ng = sorted_groups(pipe, key_tvs)
        num_segments = K.bucket(max(1, self.adaptive), 256)
        env2 = pipe2.env()
        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [_compute_agg(a, env2, seg, pipe2.mask, num_segments, cap,
                                sorted_seg=True)
                   for a in agg_calls]
        out_keys = first_group_keys(sorted_keys, seg, pipe2.mask,
                                    num_segments, cap, sorted_seg=True)
        out_mask = jnp.arange(num_segments) < ng  # ng stays on device
        return self._finalize(out_keys, agg_tvs, out_mask, num_segments)

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        pipe = Pipe.from_batch_data(child_batches[0].schema,
                                    child_batches[0].data)
        if self.traceable:
            return self.trace([pipe]).to_batch()
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]

        if not key_tvs:
            seg = jnp.zeros((cap,), dtype=jnp.int32)
            pipe2, n_groups = pipe, 1
            sorted_keys: List[TV] = []
        else:
            pipe2, sorted_keys, seg, ng = sorted_groups(pipe, key_tvs)
            n_groups = max(1, int(ng))  # host sync: output sizing
            _AGG_STATS.put(self.stats_key(), n_groups)

        num_segments = K.bucket(n_groups, 256)
        env2 = pipe2.env()
        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        sorted_seg = bool(key_tvs)
        agg_tvs = [_compute_agg(a, env2, seg, pipe2.mask, num_segments, cap,
                                sorted_seg=sorted_seg)
                   for a in agg_calls]
        out_keys = first_group_keys(sorted_keys, seg, pipe2.mask,
                                    num_segments, cap, sorted_seg=sorted_seg)
        out_mask = jnp.arange(num_segments) < n_groups
        return self._finalize(out_keys, agg_tvs, out_mask,
                              num_segments).to_batch()

    def node_string(self):
        return (f"HashAggregate[keys=[{', '.join(map(str, self.groupings))}], "
                f"out=[{', '.join(str(e) for e in self.aggregates)}]]")

    def plan_key(self):
        return ("HashAggregate",
                tuple(E.expr_key(g) for g in self.groupings),
                tuple(E.expr_key(a) for a in self.aggregates),
                self.child.plan_key())


# ---- join ------------------------------------------------------------------


def _hash_keys(lds, rds):
    """Hash-combine multiple key columns into one int64 per row. Shifted
    right by 2 so the max value is 2^62-1 — strictly below the int64
    sentinel build_join_ranges uses for dead rows."""
    lh = K.hash64(lds[0])
    rh = K.hash64(rds[0])
    for ld, rd in zip(lds[1:], rds[1:]):
        lh = K.hash_combine(lh, ld)
        rh = K.hash_combine(rh, rd)
    return ((lh >> jnp.uint64(2)).astype(jnp.int64),
            (rh >> jnp.uint64(2)).astype(jnp.int64))


def _verify_key_pairs(prepped, p_idx, b_idx, cap):
    """Exact key equality for hash-matched pairs."""
    ok = jnp.ones((cap,), dtype=jnp.bool_)
    for ld, rd in prepped:
        ok = ok & (ld[p_idx] == rd[b_idx])
    return ok


def _pair_names(left_names, right_names) -> List[str]:
    """Joined-pair column names (delegates to the canonical dedup)."""
    return E.dedup_pair_names(left_names, right_names)


#: Adaptive join statistics (the AQE analogue, reference:
#: adaptive/AdaptiveSparkPlanExec.scala:247): first execution of a join
#: runs the blocking path and records key-packing ranges + whether the
#: build side matched each probe row at most once. Keyed on plan
#: structure AND the identity of the leaf device arrays — jax arrays are
#: immutable, so identical ids imply identical data, making the cached
#: stats sound. With stats present, PK-FK joins become fully traceable
#: (output capacity = probe capacity) and fuse into one XLA program with
#: zero host syncs — the difference between ~6 and ~2 tunnel round trips
#: per TPC-H query.
#: Gate for adaptive-stats RECORDING (reads stay enabled). The chunked
#: out-of-HBM executor runs hundreds of single-shot plans whose leaf
#: arrays never recur; recording them costs a blocking host sync per
#: plan and floods the LRU caches with dead-weakref entries that evict
#: live queries' stats. A ContextVar, not a module global: the chunk
#: pipeline (physical/pipeline.py) runs producer threads concurrently
#: with the consumer's merge loop, and the consumer's disabled window
#: must neither leak into nor be clobbered by another thread.
import contextvars as _contextvars

_STATS_RECORDING = _contextvars.ContextVar("stats_recording",
                                           default=True)


class stats_recording_disabled:
    """Context manager: suppress adaptive-stat recording (and the host
    syncs that feed it) for single-shot plan executions."""

    def __enter__(self):
        self._token = _STATS_RECORDING.set(False)

    def __exit__(self, *exc):
        _STATS_RECORDING.reset(self._token)
        return False


def stats_recording() -> bool:
    return _STATS_RECORDING.get()


class _AdaptiveStatsCache:
    """Bounded stats cache whose keys embed id() of leaf device arrays.

    An id can be recycled after its array is garbage-collected, which
    would silently replay stale stats (wrong clip ranges -> wrong join
    results). Entries therefore hold WEAKREFS to the arrays and are
    evicted the moment any referenced array dies — no HBM is pinned, and
    a recycled id can never alias a live entry. LRU-bounded as well."""

    def __init__(self, maxsize: int = 256):
        from collections import OrderedDict

        self._data: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._maxsize = maxsize

    def _alive(self, key) -> bool:
        v = self._data.get(key)
        if v is None:
            return False
        _, refs = v
        if any(r() is None for r in refs):
            del self._data[key]
            return False
        return True

    def get(self, key_and_pins):
        key, _ = key_and_pins
        if not self._alive(key):
            return None
        self._data.move_to_end(key)
        return self._data[key][0]

    def put(self, key_and_pins, value) -> None:
        import weakref

        if not _STATS_RECORDING.get():
            return
        key, pins = key_and_pins
        try:
            refs = tuple(weakref.ref(a) for a in pins)
        except TypeError:
            return  # non-weakref-able leaf: safer to skip caching
        # sweep entries whose leaves died: they can never be hit again
        # (stats_key embeds array ids) but would otherwise pin their
        # values — for _JoinIndexCache that is real HBM — indefinitely
        dead = [k for k, (_, rs) in self._data.items()
                if any(r() is None for r in rs)]
        for k in dead:
            del self._data[k]
        self._data[key] = (value, refs)
        self._data.move_to_end(key)
        while len(self._data) > self._maxsize:
            self._data.popitem(last=False)

    def __contains__(self, key_and_pins) -> bool:
        return self._alive(key_and_pins[0])

    def __len__(self) -> int:
        return len(self._data)


_JOIN_STATS = _AdaptiveStatsCache()


class _JoinIndexCache(_AdaptiveStatsCache):
    """Join-index cache bounded by pinned DEVICE BYTES, not entry count:
    a lineitem-scale index holds ~100 MB of HBM while a dimension-table
    index is a few KB, so a count LRU either starves breadth or risks
    HBM. Values are (orient, index_batch, tables_batch|None). Note an
    evicted index is only re-recorded by a future BLOCKING run (new leaf
    arrays); until then the join still executes correctly through the
    live build_join_ranges path, just without the speedup."""

    def __init__(self, max_bytes: int = 1 << 30):
        super().__init__(maxsize=1 << 62)
        self._max_bytes = max_bytes

    @staticmethod
    def _nbytes(value) -> int:
        _, ib, tb = value
        total = 0
        for b in (ib, tb):
            if b is None:
                continue
            for cd in b.data.columns:
                total += cd.data.size * cd.data.dtype.itemsize
        return total

    def put(self, key_and_pins, value) -> None:
        super().put(key_and_pins, value)
        total = sum(self._nbytes(v) for v, _ in self._data.values())
        while total > self._max_bytes and len(self._data) > 1:
            _, (v, _) = self._data.popitem(last=False)
            total -= self._nbytes(v)


#: Cached join build indexes (kernels.make_join_index outputs, wrapped
#: as aux Batches); leaf weakrefs evict entries when their data dies.
_JOIN_INDEX = _JoinIndexCache()

#: Observed explode output capacity per (plan, leaf-ids) — same replay
#: discipline as _JOIN_STATS (GenerateExec).
_GEN_STATS = _AdaptiveStatsCache()

#: Adaptive aggregation statistics: observed group count per
#: (plan, leaf-array-ids) — lets the sort-based aggregation path trace
#: with a static output capacity on re-execution (same AQE idea as
#: _JOIN_STATS; reference: AdaptiveSparkPlanExec.scala:247).
_AGG_STATS = _AdaptiveStatsCache()


@dataclass(eq=False)
class JoinExec(PhysicalPlan):
    """Equi-join via sorted-build + searchsorted ranges (reference:
    ShuffledHashJoinExec.scala:38 / BroadcastHashJoinExec.scala:40 +
    HashedRelation.scala — rebuilt without hash tables, see
    kernels.build_join_ranges). Blocking on first execution (output
    capacity is the host-synced match count); unique-build inner/left/
    semi/anti joins become traceable once _JOIN_STATS has their packing."""

    left: PhysicalPlan
    right: PhysicalPlan
    how: str
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    condition: Optional[E.Expression] = None
    #: bound by the planner from _JOIN_STATS: tuple of per-key (mn, rg)
    adaptive: Optional[tuple] = None
    #: bound by the planner from _JOIN_INDEX: aux scans over the cached
    #: build-side sort permutation / sorted key / dense lo+cnt tables
    #: (kernels.make_join_index). Excluded from plan_key/stats_key —
    #: they are derived data; the stage cache distinguishes their
    #: presence via planner._adaptive_snapshot.
    index_scan: Optional[PhysicalPlan] = None
    table_scan: Optional[PhysicalPlan] = None
    #: orientation the cached index was built for: 'fwd' = build on the
    #: right (every path but swap), 'rev' = build on the left (swap)
    index_orient: Optional[str] = None

    @property
    def traceable(self) -> bool:
        if self.adaptive is None:
            return False
        unique_build, unique_probe = self.adaptive[1], self.adaptive[2]
        if unique_build and self.how in ("inner", "left", "left_semi",
                                         "left_anti"):
            return True
        # sides of an INNER join are symmetric: a unique probe side can
        # play the build role (output capacity = right capacity)
        if unique_probe and self.how == "inner":
            return True
        # sized expansion: the first run recorded the bucketed output
        # capacity for THESE leaf arrays, so even a many-to-many join
        # replays as one static-shape traced program (no sizing sync)
        cap = self.adaptive[3] if len(self.adaptive) > 3 else None
        if cap is not None and self.how in ("inner", "left",
                                            "left_semi", "left_anti"):
            return True
        # semi/anti membership without condition/hash sizes itself
        return (self.how in ("left_semi", "left_anti")
                and self.condition is None and self.adaptive[0] != "hash")

    def children(self):
        out = (self.left, self.right)
        if self.index_scan is not None:
            out += (self.index_scan,)
        if self.table_scan is not None:
            out += (self.table_scan,)
        return out

    def _strategy(self, unique_build: bool, unique_probe: bool,
                  sized_cap, lcap: int, rcap: int):
        """Traced-join strategy and the orientation its ranges need.
        Chosen by OUTPUT capacity (see trace()); shared with the
        blocking recorder so the cached index matches the orientation
        the next trace will pick. Returns (strat, 'fwd'|'rev')."""
        if self.how == "inner":
            cands = []
            if unique_build:
                cands.append((lcap, 0, "build"))
            if unique_probe:
                cands.append((rcap, 1, "swap"))
            if sized_cap is not None:
                cands.append((sized_cap * 2, 2, "expand"))
            if not cands:
                return None, "fwd"
            strat = min(cands)[2]
            return strat, ("rev" if strat == "swap" else "fwd")
        if unique_build:
            return "build", "fwd"
        if sized_cap is None:
            return "member", "fwd"
        return "expand", "fwd"

    def _indexed_ranges(self, build_key, build_ok, probe_key, probe_ok,
                        child_pipes: List[Pipe], want: str):
        """Join ranges via the cached index when one with the right
        orientation is bound; the live build_join_ranges otherwise."""
        if self.index_scan is not None and len(child_pipes) > 2 \
                and self.index_orient == want:
            ipipe = child_pipes[2]
            perm = ipipe.cols["perm"].data
            skey = ipipe.cols["skey"].data
            # layout guard: the index is positional, recorded against
            # the build side as the blocking run saw it (possibly
            # compacted). If the corresponding _COMPACT_STATS entry was
            # independently evicted, the traced build pipe rides at a
            # DIFFERENT capacity — replaying the index would gather
            # arbitrary rows. A recorded compaction always changes the
            # capacity, so shape equality is the invariant.
            if perm.shape[0] == build_key.shape[0]:
                lo_t = cnt_t = None
                if self.table_scan is not None and len(child_pipes) > 3:
                    tpipe = child_pipes[3]
                    lo_t = tpipe.cols["lo"].data
                    cnt_t = tpipe.cols["cnt"].data
                return K.ranges_from_index(perm, skey, lo_t, cnt_t,
                                           probe_key, probe_ok)
        return K.build_join_ranges(build_key, build_ok,
                                   probe_key, probe_ok)

    @property
    def schema(self) -> Schema:
        if self.how in ("left_semi", "left_anti"):
            return self.left.schema
        lf = list(self.left.schema.fields)
        rf = list(self.right.schema.fields)
        if self.how in ("left", "full"):
            rf = [dataclasses.replace(f, nullable=True) for f in rf]
        if self.how in ("right", "full"):
            lf = [dataclasses.replace(f, nullable=True) for f in lf]
        names = E.dedup_pair_names([f.name for f in lf],
                                   [f.name for f in rf])
        out = [dataclasses.replace(f, name=n)
               for f, n in zip(lf + rf, names)]
        return Schema(tuple(out))

    # -- key normalization ----------------------------------------------------

    def _combined_keys(self, lpipe: Pipe, rpipe: Pipe):
        """Evaluate equi-join keys on both sides and pack them into one
        int64 key per row; strings go through a unified dictionary, ints
        through range compression (host-sync min/max stats)."""
        lenv, renv = lpipe.env(), rpipe.env()
        lks = [C.evaluate(k, lenv) for k in self.left_keys]
        rks = [C.evaluate(k, renv) for k in self.right_keys]

        lcomb = jnp.zeros((lpipe.capacity,), dtype=jnp.int64)
        rcomb = jnp.zeros((rpipe.capacity,), dtype=jnp.int64)
        lvalid = jnp.ones((lpipe.capacity,), dtype=jnp.bool_)
        rvalid = jnp.ones((rpipe.capacity,), dtype=jnp.bool_)

        # phase 1: per-key data + deferred min/max stats, fetched with ONE
        # host sync for ALL int keys (each int(...) is a full blocking
        # round trip — 87 ms on a tunneled TPU, and multi-key joins paid
        # it twice per key)
        prepped = []  # (ld, rd, rg_or_None, stat_index_or_None)
        stats = []
        for lt, rt in zip(lks, rks):
            if isinstance(lt.dtype, T.StringType) or isinstance(rt.dtype, T.StringType):
                union, (tl, tr) = C.unify_dictionaries(
                    (lt.dictionary or (), rt.dictionary or ()))
                ld = jnp.asarray(tl)[lt.data] if len(lt.dictionary or ()) else lt.data
                rd = jnp.asarray(tr)[rt.data] if len(rt.dictionary or ()) else rt.data
                prepped.append((ld, rd, max(1, len(union)), None))
            else:
                ld = lt.data.astype(jnp.int64)
                rd = rt.data.astype(jnp.int64)
                lm = jnp.where(lpipe.mask & lt.valid_or_true(lpipe.capacity),
                               ld, jnp.iinfo(jnp.int64).max)
                rm = jnp.where(rpipe.mask & rt.valid_or_true(rpipe.capacity),
                               rd, jnp.iinfo(jnp.int64).max)
                lo = jnp.minimum(jnp.min(lm), jnp.min(rm))
                l_hi = jnp.where(lpipe.mask & lt.valid_or_true(lpipe.capacity),
                                 ld, jnp.iinfo(jnp.int64).min)
                r_hi = jnp.where(rpipe.mask & rt.valid_or_true(rpipe.capacity),
                                 rd, jnp.iinfo(jnp.int64).min)
                hi = jnp.maximum(jnp.max(l_hi), jnp.max(r_hi))
                prepped.append((ld, rd, None, len(stats)))
                stats.append((lo, hi))
        fetched = jax.device_get(stats) if stats else []

        total_range = 1
        packing: List[Tuple[int, int]] = []
        overflow = False
        for ld, rd, rg, si in prepped:
            mn = 0
            if rg is None:
                mn, mx = int(fetched[si][0]), int(fetched[si][1])
                if mn > mx:
                    mn, mx = 0, 0
                rg = mx - mn + 1
            if total_range * rg > (1 << 62):  # incl. single wide key
                overflow = True
                break
            lcomb = lcomb * rg + jnp.clip(ld - mn, 0, rg - 1)
            rcomb = rcomb * rg + jnp.clip(rd - mn, 0, rg - 1)
            total_range *= rg
            packing.append((mn, rg))
        if overflow:
            # exact range packing impossible (e.g. two hash-like int64
            # ids): hash-combine the keys and VERIFY pairs after
            # expansion (reference: HashedRelation.scala:208 — probe by
            # hash, confirm by key equality)
            lcomb, rcomb = _hash_keys([p[0] for p in prepped],
                                      [p[1] for p in prepped])
            packing = "hash"  # type: ignore[assignment]
        for lt, rt in zip(lks, rks):
            if lt.validity is not None:
                lvalid = lvalid & lt.validity
            if rt.validity is not None:
                rvalid = rvalid & rt.validity
        if packing != "hash":
            packing = tuple(packing)
        return lcomb, lvalid, rcomb, rvalid, packing, \
            [(p[0], p[1]) for p in prepped]

    # -- traced path (adaptive, unique-build) ---------------------------------

    def _traced_keys(self, lpipe: Pipe, rpipe: Pipe):
        """Key packing with STATIC per-key (mn, rg) from adaptive stats —
        no host syncs, so the join fuses into the surrounding program.
        Sound because the planner only binds stats recorded for these
        exact (immutable) leaf arrays. packing == 'hash' reproduces the
        hash-combined fallback; callers must then verify pairs."""
        lenv, renv = lpipe.env(), rpipe.env()
        lks = [C.evaluate(k, lenv) for k in self.left_keys]
        rks = [C.evaluate(k, renv) for k in self.right_keys]
        lcomb = jnp.zeros((lpipe.capacity,), dtype=jnp.int64)
        rcomb = jnp.zeros((rpipe.capacity,), dtype=jnp.int64)
        lvalid = jnp.ones((lpipe.capacity,), dtype=jnp.bool_)
        rvalid = jnp.ones((rpipe.capacity,), dtype=jnp.bool_)
        packing = self.adaptive[0]
        hashed = packing == "hash"
        prepped = []
        for ki, (lt, rt) in enumerate(zip(lks, rks)):
            if isinstance(lt.dtype, T.StringType) \
                    or isinstance(rt.dtype, T.StringType):
                union, (tl, tr) = C.unify_dictionaries(
                    (lt.dictionary or (), rt.dictionary or ()))
                ld = (jnp.asarray(tl)[lt.data]
                      if len(lt.dictionary or ()) else lt.data)
                rd = (jnp.asarray(tr)[rt.data]
                      if len(rt.dictionary or ()) else rt.data)
                mn, rg = 0, max(1, len(union))
            else:
                ld = lt.data.astype(jnp.int64)
                rd = rt.data.astype(jnp.int64)
                if not hashed:
                    mn, rg = packing[ki]
            prepped.append((ld, rd))
            if not hashed:
                if isinstance(lt.dtype, T.StringType) \
                        or isinstance(rt.dtype, T.StringType):
                    rg = max(rg, packing[ki][1])
                lcomb = lcomb * rg + jnp.clip(ld - mn, 0, rg - 1)
                rcomb = rcomb * rg + jnp.clip(rd - mn, 0, rg - 1)
            if lt.validity is not None:
                lvalid = lvalid & lt.validity
            if rt.validity is not None:
                rvalid = rvalid & rt.validity
        if hashed:
            lcomb, rcomb = _hash_keys([p[0] for p in prepped],
                                      [p[1] for p in prepped])
        return lcomb, lvalid, rcomb, rvalid, hashed, prepped

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        """Unique-build join as a pure gather: each probe row has at most
        one match (adaptive stats proved it), so output capacity equals
        probe capacity and no sizing sync is needed. This is the PK-FK
        fast path every TPC-H join takes after the first execution."""
        lpipe, rpipe = child_pipes[:2]
        unique_build, unique_probe = self.adaptive[1], self.adaptive[2]
        sized_cap = self.adaptive[3] if len(self.adaptive) > 3 else None
        lcomb, lvalid, rcomb, rvalid, hashed, prepped = self._traced_keys(
            lpipe, rpipe)
        # strategy choice by OUTPUT CAPACITY: every op downstream of
        # this join (further joins, aggregation, sort) runs at the
        # capacity chosen here, so a selective join must shrink the
        # pipeline even when a gather-style join is locally cheaper.
        # (Profiled: q3's swapped join emitted at lineitem's 3.05M
        # capacity and the group-by sort-aggregated 3M rows for a
        # 32k-pair join — 1.2 s of gathers/sorts for a ~250 ms query.)
        # Expansion pays an extra offsets-searchsorted + pair mask,
        # so it must be ~2x smaller to win.
        strat, _ = self._strategy(unique_build, unique_probe, sized_cap,
                                  lpipe.capacity, rpipe.capacity)
        if strat == "swap":
            return self._trace_swapped(lpipe, rpipe, lcomb, lvalid,
                                       rcomb, rvalid, hashed, prepped,
                                       child_pipes)
        if strat == "expand":
            ranges = self._indexed_ranges(rcomb, rpipe.mask & rvalid,
                                          lcomb, lpipe.mask & lvalid,
                                          child_pipes, "fwd")
            return self._pairs_pipe(lpipe, rpipe, ranges, hashed,
                                    prepped, sized_cap)
        if strat == "member":
            # semi/anti without condition/hash: membership only, no
            # expansion needed at any capacity
            ranges = self._indexed_ranges(rcomb, rpipe.mask & rvalid,
                                          lcomb, lpipe.mask & lvalid,
                                          child_pipes, "fwd")
            has = ranges.counts > 0
            keep = lpipe.mask & (has if self.how == "left_semi"
                                 else ~has)
            return Pipe(lpipe.cols, keep, lpipe.order)
        # strat == 'build': unique-build gather at probe capacity
        ranges = self._indexed_ranges(rcomb, rpipe.mask & rvalid,
                                      lcomb, lpipe.mask & lvalid,
                                      child_pipes, "fwd")
        has = ranges.counts > 0
        b_idx = ranges.build_perm[
            jnp.clip(ranges.lo, 0, rpipe.capacity - 1)]
        if hashed:
            p_idx = jnp.arange(lpipe.capacity)
            has = has & _verify_key_pairs(prepped, p_idx, b_idx,
                                          lpipe.capacity)
        if self.how in ("left_semi", "left_anti") and self.condition is None:
            keep = lpipe.mask & (has if self.how == "left_semi" else ~has)
            return Pipe(lpipe.cols, keep, lpipe.order)
        pair_names = _pair_names(lpipe.order, rpipe.order)
        n_l = len(lpipe.order)
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_name, src in zip(pair_names[:n_l], lpipe.order):
            cols[out_name] = lpipe.cols[src]
            order.append(out_name)
        for out_name, src in zip(pair_names[n_l:], rpipe.order):
            tv = rpipe.cols[src]
            validity = tv.valid_or_true(rpipe.capacity)[b_idx] & has
            cols[out_name] = TV(tv.data[b_idx], validity, tv.dtype,
                                tv.dictionary)
            order.append(out_name)
        pair_ok = lpipe.mask & has
        if self.condition is not None:
            env = Env(cols, lpipe.capacity)
            ctv = C.evaluate(self.condition, env)
            pair_ok = pair_ok & ctv.data & ctv.valid_or_true(lpipe.capacity)
        if self.how == "left_semi":
            return Pipe(lpipe.cols, pair_ok, lpipe.order)
        if self.how == "left_anti":
            return Pipe(lpipe.cols, lpipe.mask & ~pair_ok, lpipe.order)
        if self.how == "inner":
            return Pipe(cols, pair_ok, order)
        # left outer: keep every live left row, NULL right side where the
        # (condition-passing) match is absent
        for out_name in pair_names[n_l:]:
            tv = cols[out_name]
            validity = tv.valid_or_true(lpipe.capacity) & pair_ok
            cols[out_name] = TV(tv.data, validity, tv.dtype, tv.dictionary)
        return Pipe(cols, lpipe.mask, order)

    def _trace_swapped(self, lpipe: Pipe, rpipe: Pipe, lcomb, lvalid,
                       rcomb, rvalid, hashed=False, prepped=(),
                       child_pipes=()) -> Pipe:
        """Inner join with a unique LEFT side: build on the left, stream
        the right; each right row gathers its single left match."""
        ranges = self._indexed_ranges(lcomb, lpipe.mask & lvalid,
                                      rcomb, rpipe.mask & rvalid,
                                      list(child_pipes), "rev")
        has = ranges.counts > 0
        l_idx = ranges.build_perm[
            jnp.clip(ranges.lo, 0, lpipe.capacity - 1)]
        if hashed:
            # verify with sides swapped: left is the build being gathered
            swapped = [(rd, ld) for ld, rd in prepped]
            has = has & _verify_key_pairs(
                swapped, jnp.arange(rpipe.capacity), l_idx,
                rpipe.capacity)
        pair_names = _pair_names(lpipe.order, rpipe.order)
        n_l = len(lpipe.order)
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_name, src in zip(pair_names[:n_l], lpipe.order):
            tv = lpipe.cols[src]
            validity = tv.valid_or_true(lpipe.capacity)[l_idx] & has
            cols[out_name] = TV(tv.data[l_idx], validity, tv.dtype,
                                tv.dictionary)
            order.append(out_name)
        for out_name, src in zip(pair_names[n_l:], rpipe.order):
            cols[out_name] = rpipe.cols[src]
            order.append(out_name)
        pair_ok = rpipe.mask & has
        if self.condition is not None:
            env = Env(cols, rpipe.capacity)
            ctv = C.evaluate(self.condition, env)
            pair_ok = pair_ok & ctv.data & ctv.valid_or_true(rpipe.capacity)
        return Pipe(cols, pair_ok, order)

    def _record_index(self, sk, orient: str, build_key, build_ok,
                      packing) -> None:
        """Build and cache the reusable join index (perm + sorted key
        [+ dense lo/cnt tables]) for these leaves. One-time device work
        on the blocking run; later traces consume it as jit arguments
        via aux BatchScanExec children.

        The index is POSITIONAL, so the build side's row layout must be
        identical between the blocking run that recorded it and the
        traced run that replays it. Joins and adaptive aggregations emit
        different layouts on their blocking vs traced paths (expansion
        order vs gather order), so a build subtree containing one is
        skipped — the trace falls back to live build_join_ranges."""
        build_side = self.left if orient == "rev" else self.right

        def layout_stable(p: PhysicalPlan) -> bool:
            if isinstance(p, (JoinExec, HashAggregateExec)):
                return False
            return all(layout_stable(c) for c in p.children())

        if not layout_stable(build_side):
            return
        domain = None
        if packing != "hash":
            domain = 1
            for _, rg in packing:
                domain *= rg
        perm, skey, lo_t, cnt_t = K.make_join_index(
            build_key, build_ok, domain)

        def aux_batch(named):
            fields = tuple(
                Field(name, T.INT32 if a.dtype == jnp.int32 else T.INT64,
                      nullable=False)
                for name, a in named)
            cols = tuple(ColumnData(a, None) for _, a in named)
            mask = jnp.ones((named[0][1].shape[0],), dtype=jnp.bool_)
            return Batch(Schema(fields), BatchData(cols, mask))

        ib = aux_batch((("perm", perm), ("skey", skey)))
        tb = (aux_batch((("lo", lo_t), ("cnt", cnt_t)))
              if lo_t is not None else None)
        _JOIN_INDEX.put(sk, (orient, ib, tb))

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        lpipe = Pipe.from_batch_data(child_batches[0].schema,
                                     child_batches[0].data)
        rpipe = Pipe.from_batch_data(child_batches[1].schema,
                                     child_batches[1].data)
        how = self.how

        if how == "cross" and self.condition is None:
            return self._cross(lpipe, rpipe)
        if not self.left_keys:
            # condition-only join: chunked nested loop instead of
            # materializing all L*R pairs at once (reference:
            # BroadcastNestedLoopJoinExec; VERDICT r2 weak #4 — q19-class
            # plans used to OOM/hang here)
            return self._nested_loop(lpipe, rpipe, how)

        lkey, lvalid, rkey, rvalid, packing, prepped = self._combined_keys(
            lpipe, rpipe)
        hashed = packing == "hash"
        # probe = left, build = right (left-side row order is preserved,
        # matching streamed-side semantics)
        ranges = K.build_join_ranges(rkey, rpipe.mask & rvalid,
                                     lkey, lpipe.mask & lvalid)

        adaptive_how = how in ("inner", "left", "left_semi", "left_anti")
        sk = self.stats_key() if adaptive_how else None
        record = adaptive_how and sk not in _JOIN_STATS

        if how in ("left_semi", "left_anti") and self.condition is None \
                and not hashed:
            if record:
                maxc = int(jax.device_get(ranges.counts.max()))
                _JOIN_STATS.put(sk, (packing, maxc <= 1, False, None))
                self._record_index(sk, "fwd", rkey,
                                   rpipe.mask & rvalid, packing)
            has_match = ranges.counts > 0
            keep = lpipe.mask & (has_match if how == "left_semi"
                                 else ~has_match)
            return Pipe(lpipe.cols, keep, lpipe.order).to_batch()

        # host sync: output sizing (+ on the FIRST run, max matches per
        # probe row AND per build row — either direction being unique
        # makes this join traceable next execution, swapped roles for a
        # unique probe). The BUCKETED capacity is recorded too: stats are
        # keyed on the exact leaf arrays, so the match count is
        # deterministic and re-executions can run the general expansion
        # fully traced with a static capacity — no host sync, no
        # blocking stage, even for many-to-many joins.
        if record:
            rev = K.build_join_ranges(lkey, lpipe.mask & lvalid,
                                      rkey, rpipe.mask & rvalid)
            total, maxc, maxb = (int(v) for v in jax.device_get(
                (ranges.counts.sum(), ranges.counts.max(),
                 rev.counts.max())))
            cap = K.bucket(total)
            # negative uniqueness results cached too; the capacity makes
            # the sized-expansion trace available regardless
            _JOIN_STATS.put(sk, (packing, maxc <= 1, maxb <= 1, cap))
            # cache the build index for the orientation the NEXT traced
            # execution will pick, so it skips the argsort + searchsorted
            _, orient = self._strategy(maxc <= 1, maxb <= 1, cap,
                                       lpipe.capacity, rpipe.capacity)
            if orient == "rev":
                self._record_index(sk, "rev", lkey,
                                   lpipe.mask & lvalid, packing)
            else:
                self._record_index(sk, "fwd", rkey,
                                   rpipe.mask & rvalid, packing)
        else:
            st = _JOIN_STATS.get(sk) if sk is not None else None
            if st is not None and len(st) > 3 and st[3] is not None:
                cap = st[3]  # deterministic for these leaves: no sync
            else:
                total = int(ranges.counts.sum())  # host sync: sizing
                cap = K.bucket(total)
        return self._pairs_pipe(lpipe, rpipe, ranges, hashed, prepped,
                                cap).to_batch()

    def _pairs_pipe(self, lpipe: Pipe, rpipe: Pipe, ranges, hashed,
                    prepped, cap: int) -> Pipe:
        """General match expansion at a STATIC capacity — pure jnp, so
        it runs identically as the blocking tail and as the fused
        sized-expansion trace."""
        how = self.how
        p_idx, b_idx, pair_mask = K.expand_join_pairs(ranges, cap)

        # The pair environment always carries BOTH sides (with '#2'
        # dedup names) so semi/anti join conditions can reference the
        # inner relation; the output schema narrows afterwards.
        pair_names = _pair_names(lpipe.order, rpipe.order)
        lnames = list(lpipe.order)
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_name, src_name in zip(pair_names[:len(lnames)], lnames):
            tv = lpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)
        for out_name, src_name in zip(pair_names[len(lnames):],
                                      rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)

        pair_ok = pair_mask
        if hashed:
            # hash probe: confirm candidate pairs by exact key equality
            pair_ok = pair_ok & _verify_key_pairs(prepped, p_idx, b_idx,
                                                  cap)
        if self.condition is not None:
            env = Env(cols, cap)
            ctv = C.evaluate(self.condition, env)
            pair_ok = pair_ok & ctv.data & ctv.valid_or_true(cap)

        if how == "inner":
            return Pipe(cols, pair_ok, order)

        # matched flags must be computed on the ORIGINAL pair arrays,
        # before any unmatched-row appends change the capacity
        matched = K.seg_count(p_idx, pair_ok, lpipe.capacity) > 0
        matched_b = (K.seg_count(b_idx, pair_ok, rpipe.capacity) > 0
                     if how in ("right", "full") else None)
        if how == "left_semi":
            return Pipe(lpipe.cols, lpipe.mask & matched, lpipe.order)
        if how == "left_anti":
            return Pipe(lpipe.cols, lpipe.mask & ~matched, lpipe.order)

        if how in ("left", "full"):
            out = append_unmatched_left(cols, pair_ok, order, lpipe, matched)
            cols, pair_ok, order, cap = out
        if how in ("right", "full"):
            out = append_unmatched_right(
                cols, pair_ok, order, lpipe, rpipe, matched_b)
            cols, pair_ok, order, cap = out
        return Pipe(cols, pair_ok, order)

    def _nested_loop(self, lpipe: Pipe, rpipe: Pipe, how: str) -> Batch:
        """Condition-only join evaluated in fixed-size left-chunks of
        bounded pair count. Fixed chunk shapes mean one XLA dispatch
        compile serves every chunk; surviving pair indices are pulled to
        host per chunk (this is the blocking path) and gathered once at
        the end."""
        lcap = lpipe.capacity
        rcap = rpipe.capacity
        rn = int(np.asarray(rpipe.mask).sum())  # host sync: build size
        rperm = K.compaction_permutation(rpipe.mask)
        pair_names = _pair_names(lpipe.order, rpipe.order)
        lnames = list(lpipe.order)

        def gather_pairs(p_idx, b_idx) -> Tuple[Dict[str, TV], List[str]]:
            cols: Dict[str, TV] = {}
            order: List[str] = []
            for out_name, src_name in zip(pair_names[:len(lnames)], lnames):
                tv = lpipe.cols[src_name]
                cols[out_name] = TV(
                    tv.data[p_idx],
                    None if tv.validity is None else tv.validity[p_idx],
                    tv.dtype, tv.dictionary)
                order.append(out_name)
            for out_name, src_name in zip(pair_names[len(lnames):],
                                          rpipe.order):
                tv = rpipe.cols[src_name]
                cols[out_name] = TV(
                    tv.data[b_idx],
                    None if tv.validity is None else tv.validity[b_idx],
                    tv.dtype, tv.dictionary)
                order.append(out_name)
            return cols, order

        matched_l = np.zeros(lcap, dtype=bool)
        matched_r = np.zeros(rcap, dtype=bool)
        keep_p: List[np.ndarray] = []
        keep_b: List[np.ndarray] = []
        if rn > 0:
            budget = 1 << 22  # pairs per chunk (~32 MB of int64 per col)
            chunk = max(1, min(lcap, budget // rn))
            j = jnp.arange(chunk * rn)
            local_p = j // rn
            b_idx = rperm[j % rn]
            for start in range(0, lcap, chunk):
                p_idx = jnp.clip(local_p + start, 0, lcap - 1)
                pair_ok = (local_p + start < lcap) & lpipe.mask[p_idx]
                if self.condition is not None:
                    cols, _ = gather_pairs(p_idx, b_idx)
                    env = Env(cols, chunk * rn)
                    ctv = C.evaluate(self.condition, env)
                    pair_ok = pair_ok & ctv.data & ctv.valid_or_true(
                        chunk * rn)
                ok = np.asarray(pair_ok)
                idx = np.nonzero(ok)[0]
                if idx.size:
                    ps = np.asarray(p_idx)[idx]
                    bs = np.asarray(b_idx)[idx]
                    matched_l[ps] = True
                    matched_r[bs] = True
                    if how not in ("left_semi", "left_anti"):
                        keep_p.append(ps)
                        keep_b.append(bs)

        ml = jnp.asarray(matched_l)
        if how == "left_semi":
            return Pipe(lpipe.cols, lpipe.mask & ml, lpipe.order).to_batch()
        if how == "left_anti":
            return Pipe(lpipe.cols, lpipe.mask & ~ml, lpipe.order).to_batch()

        all_p = (np.concatenate(keep_p) if keep_p
                 else np.zeros((0,), dtype=np.int64))
        all_b = (np.concatenate(keep_b) if keep_b
                 else np.zeros((0,), dtype=np.int64))
        total = int(all_p.shape[0])
        cap = K.bucket(total)
        pad_p = np.zeros(cap, dtype=np.int64)
        pad_b = np.zeros(cap, dtype=np.int64)
        pad_p[:total] = all_p
        pad_b[:total] = all_b
        p_idx = jnp.asarray(pad_p)
        b_idx = jnp.asarray(pad_b)
        pair_ok = jnp.arange(cap) < total
        cols, order = gather_pairs(p_idx, b_idx)

        if how in ("inner", "cross"):
            return Pipe(cols, pair_ok, order).to_batch()
        if how in ("left", "full"):
            out = append_unmatched_left(cols, pair_ok, order, lpipe, ml)
            cols, pair_ok, order, cap = out
        if how in ("right", "full"):
            out = append_unmatched_right(
                cols, pair_ok, order, lpipe, rpipe, jnp.asarray(matched_r))
            cols, pair_ok, order, cap = out
        return Pipe(cols, pair_ok, order).to_batch()

    def _cross(self, lpipe: Pipe, rpipe: Pipe) -> Batch:
        ln = int(np.asarray(lpipe.mask).sum())
        rn = int(np.asarray(rpipe.mask).sum())
        cap = K.bucket(lpipe.capacity * rn if rn else 1)
        j = jnp.arange(cap)
        rs = max(rn, 1)
        p_idx = j // rs
        # compact right side live rows first
        rperm = K.compaction_permutation(rpipe.mask)
        b_idx = rperm[j % rs]
        pair_mask = (j < lpipe.capacity * rs) & lpipe.mask[
            jnp.clip(p_idx, 0, lpipe.capacity - 1)]
        if rn == 0:  # empty side -> empty cross product
            pair_mask = jnp.zeros_like(pair_mask)
        p_idx = jnp.clip(p_idx, 0, lpipe.capacity - 1)
        out_schema = self.schema
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_f, src_name in zip(out_schema.fields[:len(lpipe.order)],
                                   lpipe.order):
            tv = lpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        for out_f, src_name in zip(out_schema.fields[len(lpipe.order):],
                                   rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        if self.condition is not None:
            env = Env(cols, cap)
            ctv = C.evaluate(self.condition, env)
            pair_mask = pair_mask & ctv.data & ctv.valid_or_true(cap)
        return Pipe(cols, pair_mask, order).to_batch()

    def node_string(self):
        ks = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys,
                                                  self.right_keys))
        return f"Join[{self.how}, ({ks}), cond={self.condition}]"

    def plan_key(self):
        return ("Join", self.how,
                tuple(E.expr_key(k) for k in self.left_keys),
                tuple(E.expr_key(k) for k in self.right_keys),
                None if self.condition is None else E.expr_key(self.condition),
                self.left.plan_key(), self.right.plan_key())


def append_unmatched_left(cols, pair_ok, order, lpipe, matched):
    """Append left rows with no (condition-passing) match; right side NULL.

    Shared by the single-device JoinExec and the mesh JoinApplyExec
    (reference contract: joins/ShuffledHashJoinExec.scala:38 fullOuterJoin
    buildSideOrFullOuterJoin — unmatched stream rows padded with nulls).
    """
    lcap = lpipe.capacity
    n_l = len(lpipe.order)
    extra_mask = lpipe.mask & ~matched
    new_cols: Dict[str, TV] = {}
    for i, name in enumerate(order):
        tv = cols[name]
        if i < n_l:
            src = lpipe.cols[lpipe.order[i]]
            data = jnp.concatenate([tv.data, src.data])
            validity = None
            if tv.validity is not None or src.validity is not None:
                validity = jnp.concatenate([
                    tv.valid_or_true(tv.data.shape[0]),
                    src.valid_or_true(lcap)])
        else:
            data = jnp.concatenate(
                [tv.data, jnp.zeros((lcap,), dtype=tv.data.dtype)])
            validity = jnp.concatenate([
                tv.valid_or_true(tv.data.shape[0]),
                jnp.zeros((lcap,), dtype=jnp.bool_)])
        new_cols[name] = TV(data, validity, tv.dtype, tv.dictionary)
    mask = jnp.concatenate([pair_ok, extra_mask])
    return new_cols, mask, order, int(mask.shape[0])


def append_unmatched_right(cols, pair_ok, order, lpipe, rpipe, matched_b):
    """Append right rows with no (condition-passing) match; left side NULL."""
    rcap = rpipe.capacity
    n_l = len(lpipe.order)
    extra_mask = rpipe.mask & ~matched_b
    new_cols: Dict[str, TV] = {}
    cur_cap = cols[order[0]].data.shape[0]
    for i, name in enumerate(order):
        tv = cols[name]
        if i < n_l:
            data = jnp.concatenate(
                [tv.data, jnp.zeros((rcap,), dtype=tv.data.dtype)])
            validity = jnp.concatenate([
                tv.valid_or_true(cur_cap),
                jnp.zeros((rcap,), dtype=jnp.bool_)])
        else:
            src = rpipe.cols[rpipe.order[i - n_l]]
            data = jnp.concatenate([tv.data, src.data])
            validity = None
            if tv.validity is not None or src.validity is not None:
                validity = jnp.concatenate([
                    tv.valid_or_true(cur_cap), src.valid_or_true(rcap)])
        new_cols[name] = TV(data, validity, tv.dtype, tv.dictionary)
    mask = jnp.concatenate([pair_ok, extra_mask])
    return new_cols, mask, order, int(mask.shape[0])

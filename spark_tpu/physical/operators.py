"""Physical operators.

Analogue of the reference's SparkPlan operator tier (reference:
sql/core/.../execution/basicPhysicalOperators.scala ProjectExec:42
FilterExec:216 RangeExec:412, aggregate/HashAggregateExec.scala:47,
SortExec.scala:40, joins/ShuffledHashJoinExec.scala:38 +
HashedRelation.scala, limit.scala) — re-architected for XLA:

- Operators are either **traceable** (pure static-shape functions that
  compose into one jitted XLA program — the whole-stage-codegen analogue,
  reference WholeStageCodegenExec.scala:627, with XLA playing Janino) or
  **blocking** (need a host sync to size their output: general hash
  aggregation, joins). The executor fuses maximal traceable subtrees.
- A pipeline carries ``(cols: {name: TV}, row_mask)``; filters flip mask
  bits, projections rebuild the dict — shapes never change mid-stage.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_tpu import types as T
from spark_tpu.columnar.batch import Batch, BatchData, ColumnData
from spark_tpu.expr import compiler as C
from spark_tpu.expr import expressions as E
from spark_tpu.expr.compiler import TV, Env
from spark_tpu.physical import kernels as K
from spark_tpu.types import Field, Schema


class Pipe:
    """Trace-time pipeline state flowing through fused operators."""

    __slots__ = ("cols", "mask", "order")

    def __init__(self, cols: Dict[str, TV], mask: jnp.ndarray,
                 order: Sequence[str]):
        self.cols = cols
        self.mask = mask
        self.order = list(order)

    @property
    def capacity(self) -> int:
        return int(self.mask.shape[0])

    def env(self) -> Env:
        return Env(self.cols, self.capacity)

    @classmethod
    def from_batch_data(cls, schema: Schema, data: BatchData) -> "Pipe":
        cols = {}
        for f, cd in zip(schema.fields, data.columns):
            cols[f.name] = TV(cd.data, cd.validity, f.dtype, f.dictionary)
        return cls(cols, data.row_mask, schema.names)

    def to_batch(self) -> Batch:
        fields = []
        cds = []
        for name in self.order:
            tv = self.cols[name]
            fields.append(Field(name, tv.dtype,
                                nullable=tv.validity is not None,
                                dictionary=tv.dictionary))
            cds.append(ColumnData(tv.data, tv.validity))
        return Batch(Schema(tuple(fields)),
                     BatchData(tuple(cds), self.mask))


class PhysicalPlan:
    """Base physical operator."""

    def children(self) -> Tuple["PhysicalPlan", ...]:
        return ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    #: True when ``trace`` composes into a fused jit program.
    traceable: bool = False

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        raise NotImplementedError(f"{type(self).__name__} is not traceable")

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        """Eager execution with host syncs allowed."""
        pipes = [Pipe.from_batch_data(b.schema, b.data) for b in child_batches]
        return self.trace(pipes).to_batch()

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + self.node_string()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children()])

    def node_string(self) -> str:
        return type(self).__name__

    def plan_key(self) -> tuple:
        """Structural cache key for fused-stage jit caching."""
        return (type(self).__name__,) + tuple(
            c.plan_key() for c in self.children())

    def __repr__(self):
        return self.tree_string()


# ---- leaves ----------------------------------------------------------------


@dataclass(eq=False)
class BatchScanExec(PhysicalPlan):
    """Scan over an in-memory device batch (+ input port index for fused
    stages). Analogue of LocalTableScanExec / columnar scan output."""

    batch: Batch
    traceable = True

    @property
    def schema(self) -> Schema:
        return self.batch.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        raise AssertionError("leaf scan is fed by the stage runner")

    def node_string(self):
        return f"BatchScan{list(self.schema.names)}"

    def plan_key(self):
        dicts = tuple(f.dictionary for f in self.batch.schema.fields)
        return ("BatchScan", self.batch.capacity,
                tuple((f.name, repr(f.dtype)) for f in self.batch.schema.fields),
                hash(dicts))


@dataclass(eq=False)
class RangeExec(PhysicalPlan):
    """On-device iota (reference: basicPhysicalOperators.scala
    RangeExec:412; RangeBenchmark 12,110 M rows/s is the number to beat —
    here the whole range is one fused XLA iota that usually never
    materializes)."""

    start: int
    end: int
    step: int
    col_name: str = "id"
    traceable = True

    @property
    def num_rows(self) -> int:
        if self.step == 0:
            return 0
        n = (self.end - self.start + self.step - (1 if self.step > 0 else -1))
        return max(0, n // self.step)

    @property
    def schema(self) -> Schema:
        return Schema((Field(self.col_name, T.INT64, nullable=False),))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        n = self.num_rows
        cap = K.bucket(n)
        ids = self.start + jnp.arange(cap, dtype=jnp.int64) * self.step
        mask = jnp.arange(cap) < n
        return Pipe({self.col_name: TV(ids, None, T.INT64, None)}, mask,
                    [self.col_name])

    def plan_key(self):
        return ("Range", self.start, self.end, self.step, self.col_name)


# ---- pipelined unary ops ----------------------------------------------------


@dataclass(eq=False)
class ProjectExec(PhysicalPlan):
    exprs: Tuple[E.Expression, ...]
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.exprs:
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            fields.append(Field(e.name, e.data_type(cs), e.nullable(cs),
                                dictionary))
        return Schema(tuple(fields))

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        cols = {}
        order = []
        for e in self.exprs:
            tv = C.evaluate(e, env)
            cols[e.name] = tv
            order.append(e.name)
        return Pipe(cols, pipe.mask, order)

    def node_string(self):
        return f"Project[{', '.join(str(e) for e in self.exprs)}]"

    def plan_key(self):
        return ("Project", tuple(E.expr_key(e) for e in self.exprs),
                self.child.plan_key())


@dataclass(eq=False)
class FilterExec(PhysicalPlan):
    condition: E.Expression
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        tv = C.evaluate(self.condition, pipe.env())
        keep = tv.data & tv.valid_or_true(pipe.capacity)
        return Pipe(pipe.cols, pipe.mask & keep, pipe.order)

    def node_string(self):
        return f"Filter[{self.condition}]"

    def plan_key(self):
        return ("Filter", E.expr_key(self.condition), self.child.plan_key())


@dataclass(eq=False)
class SortExec(PhysicalPlan):
    """Global sort: chained stable argsorts (reference: SortExec.scala:40
    backed by UnsafeExternalSorter/RadixSort.java:25 — XLA's on-device
    sort replaces both)."""

    orders: Tuple[E.SortOrder, ...]
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        keys = []
        for o in self.orders:
            tv = C.evaluate(o.child, env)
            keys.append(K.SortKey(tv.data, tv.validity, o.ascending,
                                  o.nulls_first_resolved))
        perm = K.lexsort_permutation(keys, pipe.mask)
        cols = {
            name: TV(tv.data[perm],
                     None if tv.validity is None else tv.validity[perm],
                     tv.dtype, tv.dictionary)
            for name, tv in pipe.cols.items()
        }
        return Pipe(cols, pipe.mask[perm], pipe.order)

    def node_string(self):
        return f"Sort[{', '.join(map(str, self.orders))}]"

    def plan_key(self):
        return ("Sort",
                tuple((E.expr_key(o.child), o.ascending,
                       o.nulls_first_resolved) for o in self.orders),
                self.child.plan_key())


@dataclass(eq=False)
class LimitExec(PhysicalPlan):
    """Keep first n live rows (reference: limit.scala GlobalLimitExec)."""

    n: int
    child: PhysicalPlan
    offset: int = 0
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        return Pipe(pipe.cols, K.limit_mask(pipe.mask, self.n, self.offset),
                    pipe.order)

    def node_string(self):
        return f"Limit[{self.n}]"

    def plan_key(self):
        return ("Limit", self.n, self.offset, self.child.plan_key())


@dataclass(eq=False)
class SampleExec(PhysicalPlan):
    fraction: float
    seed: int
    child: PhysicalPlan
    traceable = True

    def children(self):
        return (self.child,)

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        key = jax.random.PRNGKey(self.seed)
        u = jax.random.uniform(key, (pipe.capacity,))
        return Pipe(pipe.cols, pipe.mask & (u < self.fraction), pipe.order)

    def plan_key(self):
        return ("Sample", self.fraction, self.seed, self.child.plan_key())


@dataclass(eq=False)
class UnionExec(PhysicalPlan):
    left: PhysicalPlan
    right: PhysicalPlan
    traceable = True

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        return self.left.schema

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        lp, rp = child_pipes
        cols = {}
        order = []
        for lname, rname in zip(lp.order, rp.order):
            lt = lp.cols[lname]
            rt = rp.cols[rname]
            out_dt = lt.dtype if type(lt.dtype) is type(rt.dtype) \
                else T.common_type(lt.dtype, rt.dtype)
            ld, rd = lt.data, rt.data
            dictionary = None
            if isinstance(out_dt, T.StringType):
                union, (tl, tr) = C.unify_dictionaries(
                    (lt.dictionary or (), rt.dictionary or ()))
                ld = jnp.asarray(tl)[lt.data] if len(lt.dictionary or ()) else lt.data
                rd = jnp.asarray(tr)[rt.data] if len(rt.dictionary or ()) else rt.data
                dictionary = union
            else:
                ld = C._cast_data(ld, lt.dtype, out_dt)
                rd = C._cast_data(rd, rt.dtype, out_dt)
            data = jnp.concatenate([ld, rd])
            if lt.validity is None and rt.validity is None:
                validity = None
            else:
                validity = jnp.concatenate([
                    lt.valid_or_true(lp.capacity), rt.valid_or_true(rp.capacity)])
            cols[lname] = TV(data, validity, out_dt, dictionary)
            order.append(lname)
        mask = jnp.concatenate([lp.mask, rp.mask])
        return Pipe(cols, mask, order)

    def plan_key(self):
        return ("Union", self.left.plan_key(), self.right.plan_key())


# ---- aggregation ------------------------------------------------------------

_DIRECT_CARDINALITY_LIMIT = 1 << 22  # packed-key segment count bound


def _agg_primitives(agg: E.AggregateExpression) -> List[str]:
    if isinstance(agg, E.Sum):
        return ["sum"]
    if isinstance(agg, E.Count):
        return ["count"]
    if isinstance(agg, E.Avg):
        return ["sum", "count"]
    if isinstance(agg, E.Min):
        return ["min"]
    if isinstance(agg, E.Max):
        return ["max"]
    if isinstance(agg, E.StddevVariance):
        return ["count", "sum", "sumsq"]
    if isinstance(agg, E.First):
        return ["first"]
    raise NotImplementedError(f"aggregate {agg!r}")


def rewrite_agg_outputs(
    groupings: Tuple[E.Expression, ...],
    aggregates: Tuple[E.Expression, ...],
) -> Tuple[Tuple[E.Expression, ...], List[E.AggregateExpression]]:
    """Rewrite output expressions so aggregate calls become __agg{i} col
    refs and grouping subtrees become __key{j} col refs; returns the
    rewritten outputs plus the distinct aggregate calls (the physical
    aggregation list). Analogue of the planner's PhysicalAggregation
    pattern (reference: planning/patterns.scala)."""
    agg_calls: List[E.AggregateExpression] = []
    agg_keys: List[tuple] = []
    grouping_keys = [E.expr_key(g) for g in groupings]

    def rewrite(e: E.Expression) -> E.Expression:
        """Top-down: a whole subtree matching a grouping / aggregate is
        replaced before descending (descending first would corrupt
        aggregate children that reference grouping columns)."""
        sk = E.expr_key(e)
        for j, gk in enumerate(grouping_keys):
            if sk == gk:
                return E.Col(f"__key{j}")
        if isinstance(e, E.AggregateExpression):
            for i, k in enumerate(agg_keys):
                if k == sk:
                    return E.Col(f"__agg{i}")
            agg_calls.append(e)
            agg_keys.append(sk)
            return E.Col(f"__agg{len(agg_calls) - 1}")
        if isinstance(e, E.Alias):
            return E.Alias(rewrite(e.child), e.alias_name)
        # generic rebuild with rewritten expression-valued fields
        new_fields = {}
        changed = False
        for fl in dataclasses.fields(e):
            v = getattr(e, fl.name)
            if isinstance(v, E.Expression):
                nv = rewrite(v)
                changed |= nv is not v
                new_fields[fl.name] = nv
            elif isinstance(v, tuple) and any(
                    isinstance(x, (E.Expression, tuple)) for x in v):
                nv_list = []
                for x in v:
                    if isinstance(x, E.Expression):
                        nx = rewrite(x)
                        changed |= nx is not x
                        nv_list.append(nx)
                    elif isinstance(x, tuple):
                        nx = tuple(rewrite(y) if isinstance(y, E.Expression)
                                   else y for y in x)
                        changed |= nx != x
                        nv_list.append(nx)
                    else:
                        nv_list.append(x)
                new_fields[fl.name] = tuple(nv_list)
            else:
                new_fields[fl.name] = v
        return dataclasses.replace(e, **new_fields) if changed else e

    outputs = []
    for e in aggregates:
        name = e.name
        ne = rewrite(e)
        if ne.name != name:
            ne = E.Alias(ne, name)
        outputs.append(ne)
    return tuple(outputs), agg_calls


def group_key_codes(key_tvs: List[TV]):
    """Small-int codes + cardinalities for direct (packed) grouping.
    Raises AssertionError when a key has no trace-time cardinality."""
    codes, validities, cards = [], [], []
    for tv in key_tvs:
        if isinstance(tv.dtype, T.BooleanType):
            codes.append(tv.data.astype(jnp.int32))
            validities.append(tv.validity)
            cards.append(2)
        elif isinstance(tv.dtype, T.StringType) and tv.dictionary is not None:
            codes.append(tv.data)
            validities.append(tv.validity)
            cards.append(max(1, len(tv.dictionary)))
        else:
            raise AssertionError(
                "direct agg path needs trace-time key cardinality")
    return codes, validities, cards


def sorted_groups(pipe: Pipe, key_tvs: List[TV]):
    """Sort rows by grouping keys and assign change-flag group ids.
    Returns (sorted_pipe, sorted_key_tvs, seg_ids, num_groups_traced)."""
    keys = [K.SortKey(tv.data, tv.validity, True, True) for tv in key_tvs]
    perm = K.lexsort_permutation(keys, pipe.mask)

    def take(tv: TV) -> TV:
        return TV(tv.data[perm],
                  None if tv.validity is None else tv.validity[perm],
                  tv.dtype, tv.dictionary)

    spipe = Pipe({name: take(tv) for name, tv in pipe.cols.items()},
                 pipe.mask[perm], pipe.order)
    sorted_keys = [take(tv) for tv in key_tvs]
    seg, ng = K.group_ids_from_sorted(
        [(tv.data, tv.validity) for tv in sorted_keys], spipe.mask)
    return spipe, sorted_keys, seg, ng


def first_group_keys(sorted_keys: List[TV], seg, mask, num_segments: int,
                     capacity: int) -> List[TV]:
    """Representative (first-row) key values per group."""
    out = []
    for tv in sorted_keys:
        data, found = K.seg_first(tv.data, seg, mask, num_segments, capacity)
        if tv.validity is None:
            valid = None
        else:
            vdata, _ = K.seg_first(tv.validity, seg, mask, num_segments,
                                   capacity)
            valid = vdata & found
        out.append(TV(data, valid, tv.dtype, tv.dictionary))
    return out


def _distinct_mask_cached(env: Env, child: E.Expression, tv: TV, seg,
                          ok) -> "jnp.ndarray":
    """distinct_first_mask memoized per (env, child expr): N DISTINCT
    aggregates over one column share a single (seg, value) lexsort."""
    cache = getattr(env, "_distinct_cache", None)
    if cache is None:
        cache = {}
        env._distinct_cache = cache
    key = E.expr_key(child)
    if key not in cache:
        cache[key] = K.distinct_first_mask(tv.data, seg, ok)
    return cache[key]


def _compute_agg(agg: E.AggregateExpression, env: Env, seg, mask,
                 num_segments: int, capacity: int) -> TV:
    """Compute one aggregate over segments. Nulls in the input are
    excluded per SQL semantics; a group with no valid input yields NULL
    (except count)."""
    if isinstance(agg, E.Count) and agg.child is None:
        cnt = K.seg_count(seg, mask, num_segments)
        return TV(cnt, None, T.INT64, None)

    child = agg.child  # type: ignore[attr-defined]
    tv = C.evaluate(child, env)
    ok = mask & tv.valid_or_true(capacity)
    any_valid = K.seg_count(seg, ok, num_segments) > 0
    if getattr(agg, "distinct", False):
        # DISTINCT: keep one ok row per (group, value); any_valid is
        # computed before dedup (unchanged by it anyway).
        ok = ok & _distinct_mask_cached(env, agg.child, tv, seg, ok)

    if isinstance(agg, E.Count):
        cnt = K.seg_count(seg, ok, num_segments)
        return TV(cnt, None, T.INT64, None)
    if isinstance(agg, E.Sum):
        out_dt = T.INT64 if tv.dtype.is_integral else tv.dtype
        data = tv.data.astype(C._jnp_dtype(out_dt))
        s = K.seg_sum(data, seg, ok, num_segments)
        return TV(s, any_valid, out_dt, None)
    if isinstance(agg, E.Avg):
        s = K.seg_sum(tv.data.astype(jnp.float64), seg, ok, num_segments)
        c = K.seg_count(seg, ok, num_segments)
        data = s / jnp.maximum(c, 1)
        return TV(data, any_valid, T.FLOAT64, None)
    if isinstance(agg, E.Min):
        m = K.seg_min(tv.data, seg, ok, num_segments)
        return TV(m, any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.Max):
        m = K.seg_max(tv.data, seg, ok, num_segments)
        return TV(m, any_valid, tv.dtype, tv.dictionary)
    if isinstance(agg, E.StddevVariance):
        x = tv.data.astype(jnp.float64)
        c = K.seg_count(seg, ok, num_segments).astype(jnp.float64)
        s = K.seg_sum(x, seg, ok, num_segments)
        s2 = K.seg_sum(x * x, seg, ok, num_segments)
        m2 = s2 - (s * s) / jnp.maximum(c, 1.0)
        m2 = jnp.maximum(m2, 0.0)
        kind = agg.kind
        denom = c - 1.0 if kind.endswith("_samp") else c
        var = m2 / jnp.maximum(denom, 1.0)
        data = jnp.sqrt(var) if kind.startswith("stddev") else var
        enough = c >= (2.0 if kind.endswith("_samp") else 1.0)
        return TV(data, any_valid & enough, T.FLOAT64, None)
    if isinstance(agg, E.First):
        use = ok if agg.ignore_nulls else mask
        data, found = K.seg_first(tv.data, seg, use, num_segments, capacity)
        valid = found if tv.validity is None else (
            found & K.seg_first(tv.valid_or_true(capacity), seg, use,
                                num_segments, capacity)[0])
        return TV(data, valid, tv.dtype, tv.dictionary)
    raise NotImplementedError(f"aggregate {agg!r}")


@dataclass(eq=False)
class HashAggregateExec(PhysicalPlan):
    """Group-by aggregation (reference: HashAggregateExec.scala:47 +
    TungstenAggregationIterator.scala:82 over BytesToBytesMap.java).

    Two device strategies, chosen from trace-time metadata:
    - **direct**: every grouping key has trace-time cardinality (string
      dictionary / boolean) -> mixed-radix pack to dense group ids ->
      segment reductions. No sort, no sync, fully fusable.
    - **sort**: sort rows by keys, change-flag cumsum assigns group ids,
      host-sync the group count to size the output (the one 'spill to
      host control' point, analogue of the hash-map fallback-to-sort in
      ObjectHashAggregateExec).
    """

    groupings: Tuple[E.Expression, ...]
    aggregates: Tuple[E.Expression, ...]
    child: PhysicalPlan

    def children(self):
        return (self.child,)

    @property
    def traceable(self) -> bool:  # type: ignore[override]
        return self._static_direct_ok()

    def _static_direct_ok(self) -> bool:
        """Can we guarantee the direct path from schema info alone?"""
        cs = self.child.schema
        total = 1
        for g in self.groupings:
            dt = g.data_type(cs)
            if isinstance(dt, T.BooleanType):
                total *= 3
            elif isinstance(dt, T.StringType):
                inner = E.strip_alias(g)
                if not (isinstance(inner, E.Col) and inner.col_name in cs
                        and cs.field(inner.col_name).dictionary is not None):
                    return False
                total *= len(cs.field(inner.col_name).dictionary) + 1
            else:
                return False
            if total > _DIRECT_CARDINALITY_LIMIT:
                return False
        return True

    @property
    def schema(self) -> Schema:
        cs = self.child.schema
        fields = []
        for e in self.aggregates:
            inner = E.strip_alias(e)
            dictionary = None
            if isinstance(inner, E.Col) and inner.col_name in cs:
                dictionary = cs.field(inner.col_name).dictionary
            elif isinstance(inner, (E.Min, E.Max, E.First)):
                c = E.strip_alias(inner.child)
                if isinstance(c, E.Col) and c.col_name in cs:
                    dictionary = cs.field(c.col_name).dictionary
            fields.append(Field(e.name, e.data_type(cs), e.nullable(cs),
                                dictionary))
        return Schema(tuple(fields))

    # -- shared epilogue ------------------------------------------------------

    def _finalize(self, key_tvs: List[TV], agg_tvs: List[TV],
                  out_mask: jnp.ndarray, num_segments: int) -> Pipe:
        outputs, _ = rewrite_agg_outputs(self.groupings, self.aggregates)
        cols = {f"__key{j}": tv for j, tv in enumerate(key_tvs)}
        cols.update({f"__agg{i}": tv for i, tv in enumerate(agg_tvs)})
        env = Env(cols, num_segments)
        out_cols = {}
        order = []
        for e in outputs:
            tv = C.evaluate(e, env)
            out_cols[e.name] = tv
            order.append(e.name)
        return Pipe(out_cols, out_mask, order)

    # -- direct (packed-key) path --------------------------------------------

    def trace(self, child_pipes: List[Pipe]) -> Pipe:
        pipe = child_pipes[0]
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]
        codes, validities, cards = group_key_codes(key_tvs)

        if not key_tvs:
            seg = jnp.zeros((cap,), dtype=jnp.int32)
            num_segments = 1
        else:
            seg, num_segments = K.pack_codes(codes, validities, cards)
            seg = seg.astype(jnp.int32)

        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [_compute_agg(a, env, seg, pipe.mask, num_segments, cap)
                   for a in agg_calls]

        group_present = K.seg_count(seg, pipe.mask, num_segments) > 0
        if not key_tvs:
            out_mask = jnp.ones((1,), dtype=jnp.bool_)
            out_keys: List[TV] = []
        else:
            out_mask = group_present
            nullable = [v is not None for v in validities]
            unpacked = K.unpack_code(jnp.arange(num_segments), cards, nullable)
            out_keys = []
            for (code, valid), tv in zip(unpacked, key_tvs):
                data = code.astype(C._jnp_dtype(tv.dtype))
                out_keys.append(TV(data, valid, tv.dtype, tv.dictionary))
        return self._finalize(out_keys, agg_tvs, out_mask, max(1, num_segments))

    # -- sort-based path ------------------------------------------------------

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        pipe = Pipe.from_batch_data(child_batches[0].schema,
                                    child_batches[0].data)
        if self.traceable:
            return self.trace([pipe]).to_batch()
        env = pipe.env()
        cap = pipe.capacity
        key_tvs = [C.evaluate(g, env) for g in self.groupings]

        if not key_tvs:
            seg = jnp.zeros((cap,), dtype=jnp.int32)
            pipe2, n_groups = pipe, 1
            sorted_keys: List[TV] = []
        else:
            pipe2, sorted_keys, seg, ng = sorted_groups(pipe, key_tvs)
            n_groups = max(1, int(ng))  # host sync: output sizing

        num_segments = K.bucket(n_groups, 256)
        env2 = pipe2.env()
        _, agg_calls = rewrite_agg_outputs(self.groupings, self.aggregates)
        agg_tvs = [_compute_agg(a, env2, seg, pipe2.mask, num_segments, cap)
                   for a in agg_calls]
        out_keys = first_group_keys(sorted_keys, seg, pipe2.mask,
                                    num_segments, cap)
        out_mask = jnp.arange(num_segments) < n_groups
        return self._finalize(out_keys, agg_tvs, out_mask,
                              num_segments).to_batch()

    def node_string(self):
        return (f"HashAggregate[keys=[{', '.join(map(str, self.groupings))}], "
                f"out=[{', '.join(str(e) for e in self.aggregates)}]]")

    def plan_key(self):
        return ("HashAggregate",
                tuple(E.expr_key(g) for g in self.groupings),
                tuple(E.expr_key(a) for a in self.aggregates),
                self.child.plan_key())


# ---- join ------------------------------------------------------------------


def _pair_names(left_names, right_names) -> List[str]:
    """Joined-pair column names: left keeps its names, right duplicates
    get '#2' suffixes (must match Join.schema dedup)."""
    seen = set()
    out = []
    for n in list(left_names) + list(right_names):
        name = n
        while name in seen:
            name = name + "#2"
        seen.add(name)
        out.append(name)
    return out


@dataclass(eq=False)
class JoinExec(PhysicalPlan):
    """Equi-join via sorted-build + searchsorted ranges (reference:
    ShuffledHashJoinExec.scala:38 / BroadcastHashJoinExec.scala:40 +
    HashedRelation.scala — rebuilt without hash tables, see
    kernels.build_join_ranges). Blocking: output capacity is the
    host-synced match count, bucketed."""

    left: PhysicalPlan
    right: PhysicalPlan
    how: str
    left_keys: Tuple[E.Expression, ...]
    right_keys: Tuple[E.Expression, ...]
    condition: Optional[E.Expression] = None
    traceable = False

    def children(self):
        return (self.left, self.right)

    @property
    def schema(self) -> Schema:
        if self.how in ("left_semi", "left_anti"):
            return self.left.schema
        lf = list(self.left.schema.fields)
        rf = list(self.right.schema.fields)
        if self.how in ("left", "full"):
            rf = [dataclasses.replace(f, nullable=True) for f in rf]
        if self.how in ("right", "full"):
            lf = [dataclasses.replace(f, nullable=True) for f in lf]
        seen = set()
        out = []
        for f in lf + rf:
            name = f.name
            while name in seen:
                name = name + "#2"
            seen.add(name)
            out.append(dataclasses.replace(f, name=name))
        return Schema(tuple(out))

    # -- key normalization ----------------------------------------------------

    def _combined_keys(self, lpipe: Pipe, rpipe: Pipe):
        """Evaluate equi-join keys on both sides and pack them into one
        int64 key per row; strings go through a unified dictionary, ints
        through range compression (host-sync min/max stats)."""
        lenv, renv = lpipe.env(), rpipe.env()
        lks = [C.evaluate(k, lenv) for k in self.left_keys]
        rks = [C.evaluate(k, renv) for k in self.right_keys]

        lcomb = jnp.zeros((lpipe.capacity,), dtype=jnp.int64)
        rcomb = jnp.zeros((rpipe.capacity,), dtype=jnp.int64)
        lvalid = jnp.ones((lpipe.capacity,), dtype=jnp.bool_)
        rvalid = jnp.ones((rpipe.capacity,), dtype=jnp.bool_)
        total_range = 1
        for lt, rt in zip(lks, rks):
            if isinstance(lt.dtype, T.StringType) or isinstance(rt.dtype, T.StringType):
                union, (tl, tr) = C.unify_dictionaries(
                    (lt.dictionary or (), rt.dictionary or ()))
                ld = jnp.asarray(tl)[lt.data] if len(lt.dictionary or ()) else lt.data
                rd = jnp.asarray(tr)[rt.data] if len(rt.dictionary or ()) else rt.data
                rg = max(1, len(union))
                mn = 0
            else:
                ld = lt.data.astype(jnp.int64)
                rd = rt.data.astype(jnp.int64)
                lm = jnp.where(lpipe.mask & lt.valid_or_true(lpipe.capacity),
                               ld, jnp.iinfo(jnp.int64).max)
                rm = jnp.where(rpipe.mask & rt.valid_or_true(rpipe.capacity),
                               rd, jnp.iinfo(jnp.int64).max)
                lo = jnp.minimum(jnp.min(lm), jnp.min(rm))
                l_hi = jnp.where(lpipe.mask & lt.valid_or_true(lpipe.capacity),
                                 ld, jnp.iinfo(jnp.int64).min)
                r_hi = jnp.where(rpipe.mask & rt.valid_or_true(rpipe.capacity),
                                 rd, jnp.iinfo(jnp.int64).min)
                hi = jnp.maximum(jnp.max(l_hi), jnp.max(r_hi))
                mn = int(lo)  # host sync: key stats
                mx = int(hi)
                if mn > mx:
                    mn, mx = 0, 0
                rg = mx - mn + 1
            if total_range > 1 and total_range * rg > (1 << 62):
                raise NotImplementedError(
                    "multi-key join exceeds int64 packing range")
            lcomb = lcomb * rg + jnp.clip(ld - mn, 0, rg - 1)
            rcomb = rcomb * rg + jnp.clip(rd - mn, 0, rg - 1)
            total_range *= rg
            if lt.validity is not None:
                lvalid = lvalid & lt.validity
            if rt.validity is not None:
                rvalid = rvalid & rt.validity
        return lcomb, lvalid, rcomb, rvalid

    def execute_blocking(self, child_batches: List[Batch]) -> Batch:
        lpipe = Pipe.from_batch_data(child_batches[0].schema,
                                     child_batches[0].data)
        rpipe = Pipe.from_batch_data(child_batches[1].schema,
                                     child_batches[1].data)
        how = self.how

        if how == "cross":
            return self._cross(lpipe, rpipe)

        lkey, lvalid, rkey, rvalid = self._combined_keys(lpipe, rpipe)
        # probe = left, build = right (left-side row order is preserved,
        # matching streamed-side semantics)
        ranges = K.build_join_ranges(rkey, rpipe.mask & rvalid,
                                     lkey, lpipe.mask & lvalid)

        if how in ("left_semi", "left_anti") and self.condition is None:
            has_match = ranges.counts > 0
            keep = lpipe.mask & (has_match if how == "left_semi"
                                 else ~has_match)
            return Pipe(lpipe.cols, keep, lpipe.order).to_batch()

        total = int(ranges.counts.sum())  # host sync: output sizing
        cap = K.bucket(total)
        p_idx, b_idx, pair_mask = K.expand_join_pairs(ranges, cap)

        # The pair environment always carries BOTH sides (with '#2'
        # dedup names) so semi/anti join conditions can reference the
        # inner relation; the output schema narrows afterwards.
        pair_names = _pair_names(lpipe.order, rpipe.order)
        lnames = list(lpipe.order)
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_name, src_name in zip(pair_names[:len(lnames)], lnames):
            tv = lpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)
        for out_name, src_name in zip(pair_names[len(lnames):],
                                      rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_name)

        pair_ok = pair_mask
        if self.condition is not None:
            env = Env(cols, cap)
            ctv = C.evaluate(self.condition, env)
            pair_ok = pair_ok & ctv.data & ctv.valid_or_true(cap)

        if how == "inner":
            return Pipe(cols, pair_ok, order).to_batch()

        # matched flags must be computed on the ORIGINAL pair arrays,
        # before any unmatched-row appends change the capacity
        matched = K.seg_count(p_idx, pair_ok, lpipe.capacity) > 0
        matched_b = (K.seg_count(b_idx, pair_ok, rpipe.capacity) > 0
                     if how in ("right", "full") else None)
        if how == "left_semi":
            return Pipe(lpipe.cols, lpipe.mask & matched, lpipe.order).to_batch()
        if how == "left_anti":
            return Pipe(lpipe.cols, lpipe.mask & ~matched, lpipe.order).to_batch()

        if how in ("left", "full"):
            out = append_unmatched_left(cols, pair_ok, order, lpipe, matched)
            cols, pair_ok, order, cap = out
        if how in ("right", "full"):
            out = append_unmatched_right(
                cols, pair_ok, order, lpipe, rpipe, matched_b)
            cols, pair_ok, order, cap = out
        return Pipe(cols, pair_ok, order).to_batch()

    def _cross(self, lpipe: Pipe, rpipe: Pipe) -> Batch:
        ln = int(np.asarray(lpipe.mask).sum())
        rn = int(np.asarray(rpipe.mask).sum())
        cap = K.bucket(lpipe.capacity * rn if rn else 1)
        j = jnp.arange(cap)
        rs = max(rn, 1)
        p_idx = j // rs
        # compact right side live rows first
        rperm = K.compaction_permutation(rpipe.mask)
        b_idx = rperm[j % rs]
        pair_mask = (j < lpipe.capacity * rs) & lpipe.mask[
            jnp.clip(p_idx, 0, lpipe.capacity - 1)]
        if rn == 0:  # empty side -> empty cross product
            pair_mask = jnp.zeros_like(pair_mask)
        p_idx = jnp.clip(p_idx, 0, lpipe.capacity - 1)
        out_schema = self.schema
        cols: Dict[str, TV] = {}
        order: List[str] = []
        for out_f, src_name in zip(out_schema.fields[:len(lpipe.order)],
                                   lpipe.order):
            tv = lpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[p_idx],
                None if tv.validity is None else tv.validity[p_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        for out_f, src_name in zip(out_schema.fields[len(lpipe.order):],
                                   rpipe.order):
            tv = rpipe.cols[src_name]
            cols[out_f.name] = TV(
                tv.data[b_idx],
                None if tv.validity is None else tv.validity[b_idx],
                tv.dtype, tv.dictionary)
            order.append(out_f.name)
        if self.condition is not None:
            env = Env(cols, cap)
            ctv = C.evaluate(self.condition, env)
            pair_mask = pair_mask & ctv.data & ctv.valid_or_true(cap)
        return Pipe(cols, pair_mask, order).to_batch()

    def node_string(self):
        ks = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys,
                                                  self.right_keys))
        return f"Join[{self.how}, ({ks}), cond={self.condition}]"

    def plan_key(self):
        return ("Join", self.how,
                tuple(E.expr_key(k) for k in self.left_keys),
                tuple(E.expr_key(k) for k in self.right_keys),
                None if self.condition is None else E.expr_key(self.condition),
                self.left.plan_key(), self.right.plan_key())


def append_unmatched_left(cols, pair_ok, order, lpipe, matched):
    """Append left rows with no (condition-passing) match; right side NULL.

    Shared by the single-device JoinExec and the mesh JoinApplyExec
    (reference contract: joins/ShuffledHashJoinExec.scala:38 fullOuterJoin
    buildSideOrFullOuterJoin — unmatched stream rows padded with nulls).
    """
    lcap = lpipe.capacity
    n_l = len(lpipe.order)
    extra_mask = lpipe.mask & ~matched
    new_cols: Dict[str, TV] = {}
    for i, name in enumerate(order):
        tv = cols[name]
        if i < n_l:
            src = lpipe.cols[lpipe.order[i]]
            data = jnp.concatenate([tv.data, src.data])
            validity = None
            if tv.validity is not None or src.validity is not None:
                validity = jnp.concatenate([
                    tv.valid_or_true(tv.data.shape[0]),
                    src.valid_or_true(lcap)])
        else:
            data = jnp.concatenate(
                [tv.data, jnp.zeros((lcap,), dtype=tv.data.dtype)])
            validity = jnp.concatenate([
                tv.valid_or_true(tv.data.shape[0]),
                jnp.zeros((lcap,), dtype=jnp.bool_)])
        new_cols[name] = TV(data, validity, tv.dtype, tv.dictionary)
    mask = jnp.concatenate([pair_ok, extra_mask])
    return new_cols, mask, order, int(mask.shape[0])


def append_unmatched_right(cols, pair_ok, order, lpipe, rpipe, matched_b):
    """Append right rows with no (condition-passing) match; left side NULL."""
    rcap = rpipe.capacity
    n_l = len(lpipe.order)
    extra_mask = rpipe.mask & ~matched_b
    new_cols: Dict[str, TV] = {}
    cur_cap = cols[order[0]].data.shape[0]
    for i, name in enumerate(order):
        tv = cols[name]
        if i < n_l:
            data = jnp.concatenate(
                [tv.data, jnp.zeros((rcap,), dtype=tv.data.dtype)])
            validity = jnp.concatenate([
                tv.valid_or_true(cur_cap),
                jnp.zeros((rcap,), dtype=jnp.bool_)])
        else:
            src = rpipe.cols[rpipe.order[i - n_l]]
            data = jnp.concatenate([tv.data, src.data])
            validity = None
            if tv.validity is not None or src.validity is not None:
                validity = jnp.concatenate([
                    tv.valid_or_true(cur_cap), src.valid_or_true(rcap)])
        new_cols[name] = TV(data, validity, tv.dtype, tv.dictionary)
    mask = jnp.concatenate([pair_ok, extra_mask])
    return new_cols, mask, order, int(mask.shape[0])

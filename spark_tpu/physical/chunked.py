"""Out-of-HBM execution: chunked scan-aggregation.

A v5e chip holds ~16 GB of HBM; TPC-H SF100 lineitem alone is ~80 GB.
When an aggregation's scan would exceed the device budget
(spark.tpu.maxDeviceBatchBytes), the plan is NOT materialized: the
parquet dataset streams through host RAM in bounded chunks, each chunk's
PARTIAL aggregates run on device as an ordinary batch query, and
partials merge through the same accumulator decomposition streaming uses
(plan/incremental.AggSpec). Peak device footprint = one chunk + the
running state, independent of input size.

Reference analogue: ExternalSorter.scala:93 spill-to-disk +
TungstenAggregationIterator.scala:82 sort-merge fallback — except the
reference spills mid-operator, while here the operator is re-planned as
a merge over chunk partials (the map-side-combine shape of AggUtils).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from spark_tpu import conf as CF
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.plan.incremental import AggSpec

MAX_DEVICE_BATCH_BYTES = CF.register(
    "spark.tpu.maxDeviceBatchBytes", 5 << 30,
    "Scans whose materialized size would exceed this execute in bounded "
    "host-RAM chunks with device-side partial aggregation (out-of-HBM "
    "execution). Default assumes a 16 GB-HBM chip and ~3x working-set "
    "multiplier for sort/gather intermediates over the scan itself; "
    "chunking a resident-sized scan costs ~100x (measured SF10 q1: "
    "133 s chunked vs 0.16 s resident), so do not set this timidly.",
    int)

CHUNK_ROWS = CF.register(
    "spark.tpu.chunkRows", 1 << 21,
    "Rows per device chunk for out-of-HBM execution.", int)


def _schema_width(schema) -> int:
    """Bytes per row of the scan's (column-pruned) schema."""
    from spark_tpu.expr.compiler import _jnp_dtype

    width = 0
    for f in schema.fields:
        try:
            width += np.dtype(_jnp_dtype(f.dtype)).itemsize
        except Exception:
            width += 8
        if f.nullable:
            width += 1
    return width


def find_chunkable(plan: L.LogicalPlan, conf) -> Optional[tuple]:
    """Detect `...unary ops...(Aggregate(... over one big UnresolvedScan))`
    and return (above_chain, aggregate, scan) when the scan exceeds the
    device budget. ``above_chain`` are the unary nodes above the
    aggregate, outermost first."""
    budget = conf.get(MAX_DEVICE_BATCH_BYTES)
    above: List[L.LogicalPlan] = []
    node = plan
    while isinstance(node, (L.Project, L.Sort, L.Limit, L.Filter)) \
            and not isinstance(node, L.Aggregate):
        above.append(node)
        node = node.children()[0]
    if not isinstance(node, L.Aggregate):
        return None
    # the subtree below the aggregate must be PER-ROW only (Filter/
    # Project/alias over the scan): anything order- or set-sensitive
    # (Limit, Distinct, Window, Sample, Join, nested Aggregate) would be
    # wrongly re-applied per chunk
    def per_row_only(p: L.LogicalPlan) -> bool:
        if isinstance(p, L.UnresolvedScan):
            return True
        if isinstance(p, (L.Filter, L.Project, L.SubqueryAlias)):
            return per_row_only(p.children()[0])
        return False

    if not per_row_only(node.child):
        return None
    try:
        AggSpec(node.groupings, node.aggregates)
    except NotImplementedError:
        return None  # non-mergeable aggregate: execute directly
    scans = L.collect_nodes(node.child, L.UnresolvedScan)
    if len(scans) != 1:
        return None
    scan = scans[0]
    try:
        rows = scan.source.count_rows(scan.filters)
    except Exception:
        return None
    est = rows * _schema_width(scan.schema)
    if est <= budget:
        return None
    return above, node, scan


def execute_chunked(found: tuple, conf, run_fn) -> "object":
    """Execute a chunkable plan (``found`` from find_chunkable);
    ``run_fn(logical_plan) -> Batch`` is the engine (single-device or
    mesh). Returns the final Batch."""
    from spark_tpu import metrics
    from spark_tpu.columnar.arrow import from_arrow

    above, agg, scan = found
    spec = AggSpec(agg.groupings, agg.aggregates)
    key_aliases = tuple(E.Alias(g, n) for g, n
                        in zip(spec.groupings_exec, spec.key_names))
    chunk_rows = conf.get(CHUNK_ROWS)

    # the running merge state stays a DEVICE batch across chunks: the
    # old arrow round trip downloaded every chunk's partials through the
    # host (catastrophic on a tunneled TPU — ~77 s of fetches for SF10
    # q1) where a device-side Union+merge moves nothing until the end
    state = None  # Batch
    n_chunks = 0
    for tbl in scan.source.iter_batches(scan.columns, scan.filters,
                                        chunk_rows):
        rel = L.Relation(from_arrow(tbl))

        def splice(p: L.LogicalPlan) -> L.LogicalPlan:
            if isinstance(p, L.UnresolvedScan):
                return rel
            return p

        batch_child = agg.child.transform_up(splice)
        partial = L.Aggregate(tuple(spec.groupings_exec),
                              key_aliases + tuple(spec.partials),
                              batch_child)
        keys = tuple(E.Col(n) for n in spec.key_names)
        merge_outs = tuple(E.Alias(E.Col(n), n)
                           for n in spec.key_names) + tuple(spec.merges)
        if state is None:
            merged = L.Aggregate(keys, merge_outs, partial)
        else:
            aligned = L.Project(
                tuple(E.Col(n) for n in state.schema.names), partial)
            merged = L.Aggregate(
                keys, merge_outs, L.Union(L.Relation(state), aligned))
        # every chunk plan is single-shot (fresh leaf arrays): recording
        # adaptive/output stats would cost one blocking sync per chunk
        # and flood the LRU caches with dead entries
        from spark_tpu.physical.operators import stats_recording_disabled

        with stats_recording_disabled():
            state = run_fn(merged)
        n_chunks += 1
    metrics.record("chunked_agg", chunks=n_chunks,
                   groups=0 if state is None else state.num_valid_rows())

    if state is None:  # empty scan: run the aggregate directly
        final0: L.LogicalPlan = agg
        for node in reversed(above):
            final0 = node.with_children((final0,))
        return run_fn(final0)
    final: L.LogicalPlan = L.Project(tuple(spec.outputs),
                                     L.Relation(state))
    for node in reversed(above):
        final = node.with_children((final,))
    return run_fn(final)

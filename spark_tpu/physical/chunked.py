"""Out-of-HBM execution: chunked scans through aggregation, joins, and
top-k, plus grace-hash partitioned joins when BOTH sides exceed HBM.

A v5e chip holds ~16 GB of HBM; TPC-H SF100 lineitem alone is ~80 GB.
When a plan's scan would exceed the device budget
(spark.tpu.maxDeviceBatchBytes), the plan is NOT materialized. Three
tiers, all built on the same merge-state decomposition streaming uses
(plan/incremental.AggSpec):

1. **Streamed aggregation** (`_ChunkedAgg`, sidecars=[]): the parquet
   dataset streams through host RAM in bounded chunks; each chunk's
   PARTIAL aggregates run on device; partials merge device-side.

2. **Streamed join tree** (`_ChunkedAgg` with sidecars): one big scan
   joined against sub-budget subplans. The small join inputs
   ("sidecars") pre-materialize ONCE to device-resident Relations; big
   chunks then flow through the ORIGINAL join tree per chunk. Sound
   because each big-side row contributes to the join output
   independently when the big side is on a preserved streamed side
   (inner/cross either side; left/semi/anti left; right right) — the
   union of per-chunk join outputs IS the join output. Join-key
   membership filters from the sidecars are applied host-side to each
   chunk before it is shipped (exact semi filter below
   spark.tpu.semiFilterExactMax keys, Bloom above it — the runtime-
   filter/Bloom pushdown of InjectRuntimeFilter.scala:36, done where it
   actually pays: the host->device tunnel), and the key's min/max range
   is pushed into the parquet scan for row-group pruning.

3. **Hybrid hash join** (`_HybridHashJoinAgg`, default;
   `spark.tpu.join.hybrid.*`): both sides over budget. A planned
   single pass at ANY memory level — build staging requests a grant
   from the unified memory manager, partitions spill to host files
   beyond the granted bytes (growing from the free span first),
   overflowing buckets recursively repartition, and the final result
   is byte-identical to the static tier below. The static
   **grace-hash join** (`_GraceHashAgg`) survives as the
   hybrid-disabled path and the fallback rung when a spill seam fails
   unrecoverably: both scans hash-partition by join key into P
   host-RAM bucket sets (one streaming pass each); each bucket pair
   then joins on device as an ordinary sub-budget plan. Every key
   lands in exactly one bucket, so inner/outer/semi semantics all
   hold bucket-locally.

plus **streamed top-k** (`_ChunkedTopK`): Limit(Sort(big scan)) keeps a
running device top-(n+offset) merged per chunk.

Reference analogue: ExternalSorter.scala:93 spill-merge,
SortMergeJoinExec.scala:39 + ShuffledHashJoinExec (grace hash is the
spill-tier shape of its build), TungstenAggregationIterator.scala:82
sort-merge fallback — except the reference spills mid-operator, while
here the operator is re-planned as a merge over chunk partials (the
map-side-combine shape of AggUtils).

All three tiers stream through the asynchronous chunk pipeline
(physical/pipeline.py, ``spark.tpu.pipelineDepth``): a background
producer decodes, host-filters, and ships the next chunks while the
device merges the previous partials — chunks are always consumed in
source order, so results are byte-identical at every depth.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_tpu import conf as CF
from spark_tpu.expr import expressions as E
from spark_tpu.plan import logical as L
from spark_tpu.plan.incremental import AggSpec

MAX_DEVICE_BATCH_BYTES = CF.register(
    "spark.tpu.maxDeviceBatchBytes", 5 << 30,
    "Scans whose materialized size would exceed this execute in bounded "
    "host-RAM chunks with device-side partial aggregation (out-of-HBM "
    "execution). Default assumes a 16 GB-HBM chip and ~3x working-set "
    "multiplier for sort/gather intermediates over the scan itself; "
    "chunking a resident-sized scan costs ~100x (measured SF10 q1: "
    "133 s chunked vs 0.16 s resident), so do not set this timidly.",
    int)

CHUNK_ROWS = CF.register(
    "spark.tpu.chunkRows", 1 << 21,
    "Rows per device chunk for out-of-HBM execution.", int)

SEMI_FILTER_EXACT_MAX = CF.register(
    "spark.tpu.semiFilterExactMax", 64 << 20,
    "Chunked joins filter big-side chunks host-side by join-key "
    "membership in the materialized small side. Up to this many distinct "
    "keys the filter is EXACT (sorted array + searchsorted); above it a "
    "Bloom bitset is used instead (false positives only cost transfer). "
    "0 disables the host-side filter.", int)

GRACE_PARTITIONS_MAX = CF.register(
    "spark.tpu.gracePartitionsMax", 256,
    "Upper bound on grace-hash join partition count.", int)

JOIN_HYBRID_ENABLED = CF.register(
    "spark.tpu.join.hybrid.enabled", True,
    "Route both-sides-over-budget joins through the grant-driven "
    "dynamic hybrid hash join (_HybridHashJoinAgg): build staging is "
    "sized to bytes actually GRANTED by the unified memory manager, "
    "overflow partitions spill to host files as a planned single pass, "
    "and overflowing buckets recursively repartition instead of "
    "relying on the OOM degradation ladder. Off = the static grace-"
    "hash join (which also remains the fallback rung when a hybrid "
    "spill seam fails unrecoverably).", bool)

JOIN_HYBRID_PARTITIONS_MAX = CF.register(
    "spark.tpu.join.hybrid.partitionsMax", 256,
    "Upper bound on the hybrid hash join's top-level partition count. "
    "Buckets that still exceed the device budget (skew, or a cap this "
    "low) recursively repartition with a per-level hash salt.", int)

JOIN_HYBRID_SPILL_RETRIES = CF.register(
    "spark.tpu.join.hybrid.spillRetryAttempts", 2,
    "Bounded retries for one hybrid-join spill operation (spill-file "
    "write, spill-file read-back, recursive repartition) on a "
    "transient/deadline failure before the join falls back one rung "
    "to the static grace-hash join recomputed from source.", int)

JOIN_HYBRID_GROW_WHEN_IDLE = CF.register(
    "spark.tpu.join.hybrid.growWhenIdle", True,
    "Let the hybrid hash join grow its resident set mid-pass from the "
    "unified memory manager's FREE span (never by evicting storage) "
    "before demoting a partition to a host spill file.", bool)

# join types through which a big LEFT / RIGHT child may stream
_STREAM_LEFT = ("inner", "cross", "left", "left_semi", "left_anti")
_STREAM_RIGHT = ("inner", "cross", "right")
# join types where non-matching streamed rows can be DROPPED host-side
_FILTER_LEFT = ("inner", "left_semi")
_FILTER_RIGHT = ("inner",)


def _schema_width(schema) -> int:
    """Bytes per row of the scan's (column-pruned) schema."""
    from spark_tpu.expr.compiler import _jnp_dtype

    width = 0
    for f in schema.fields:
        try:
            width += np.dtype(_jnp_dtype(f.dtype)).itemsize
        except Exception:
            width += 8
        if f.nullable:
            width += 1
    return width


def _est_scan(scan: L.UnresolvedScan) -> Optional[int]:
    try:
        rows = scan.source.count_rows(scan.filters)
    except Exception:
        return None
    return rows * _schema_width(scan.schema)


def _contains(plan: L.LogicalPlan, target: L.LogicalPlan) -> bool:
    if plan is target:
        return True
    return any(_contains(c, target) for c in plan.children())


def _peel_above(plan: L.LogicalPlan):
    above: List[L.LogicalPlan] = []
    node = plan
    while isinstance(node, (L.Project, L.Sort, L.Limit, L.Filter)) \
            and not isinstance(node, L.Aggregate):
        above.append(node)
        node = node.children()[0]
    return above, node


@dataclasses.dataclass
class _PathJoin:
    join: L.Join
    big_on_left: bool

    @property
    def sidecar(self) -> L.LogicalPlan:
        return self.join.right if self.big_on_left else self.join.left

    @property
    def big_keys(self) -> Tuple[E.Expression, ...]:
        return self.join.left_keys if self.big_on_left \
            else self.join.right_keys

    @property
    def sidecar_keys(self) -> Tuple[E.Expression, ...]:
        return self.join.right_keys if self.big_on_left \
            else self.join.left_keys

    @property
    def can_filter(self) -> bool:
        how = self.join.how
        return how in (_FILTER_LEFT if self.big_on_left else _FILTER_RIGHT)


def _stream_path(root: L.LogicalPlan,
                 big: L.UnresolvedScan) -> Optional[List[_PathJoin]]:
    """Validate that every node between ``root`` and the big scan is
    either per-row (Filter/Project/SubqueryAlias) or a join the big side
    may stream through; return the joins on the path (outermost first),
    or None when the shape is inadmissible."""
    out: List[_PathJoin] = []
    node = root
    while node is not big:
        if isinstance(node, (L.Filter, L.Project, L.SubqueryAlias)):
            node = node.children()[0]
            continue
        if isinstance(node, L.Join):
            in_left = _contains(node.left, big)
            in_right = _contains(node.right, big)
            if in_left == in_right:  # both (self-join) or neither
                return None
            how = node.how
            if in_left and how in _STREAM_LEFT:
                out.append(_PathJoin(node, True))
                node = node.left
                continue
            if in_right and how in _STREAM_RIGHT:
                out.append(_PathJoin(node, False))
                node = node.right
                continue
            return None
        return None
    return out


def _resolve_to_scan_col(expr: E.Expression, root: L.LogicalPlan,
                         big: L.UnresolvedScan) -> Optional[str]:
    """Trace a join-key expression from ``root``'s output schema down
    the streamed path to a direct column of the big scan (through
    Project aliases and join-output renames); None when it is computed
    or lands outside the scan."""
    expr = E.strip_alias(expr)
    node = root
    while node is not big:
        if not isinstance(expr, E.Col):
            return None
        name = expr.col_name
        if isinstance(node, (L.Filter, L.SubqueryAlias)):
            node = node.children()[0]
            continue
        if isinstance(node, L.Project):
            for e in node.exprs:
                if isinstance(e, E.Alias) and e.alias_name == name:
                    expr = E.strip_alias(e.child)
                    break
                if isinstance(e, E.Col) and e.col_name == name:
                    break
            else:
                return None
            node = node.children()[0]
            continue
        if isinstance(node, L.Join):
            big_on_left = _contains(node.left, big)
            out_names = list(node.schema.names)
            if name not in out_names:
                return None
            pos = out_names.index(name)
            ln = list(node.left.schema.names)
            if big_on_left:
                if pos >= len(ln):
                    return None
                expr = E.Col(ln[pos])
                node = node.left
            else:
                if pos < len(ln):
                    return None
                rn = list(node.right.schema.names)
                expr = E.Col(rn[pos - len(ln)])
                node = node.right
            continue
        return None
    if isinstance(expr, E.Col) and expr.col_name in big.schema.names:
        return expr.col_name
    return None


class _MergeState:
    """Running device-side merge of per-chunk partial batches: the state
    stays a DEVICE batch across chunks (an arrow round trip would
    download every chunk's partials through the host — catastrophic on a
    tunneled TPU: ~77 s of fetches for SF10 q1)."""

    def __init__(self, merge_plan_fn, run_fn):
        self._merge_plan_fn = merge_plan_fn  # (state_rel|None, partial_plan) -> plan
        self._run = run_fn
        self.batch = None
        self.chunks = 0

    def feed(self, partial_plan: L.LogicalPlan) -> None:
        from spark_tpu.physical.operators import stats_recording_disabled

        state_rel = None if self.batch is None else L.Relation(self.batch)
        plan = self._merge_plan_fn(state_rel, partial_plan)
        # every chunk plan is single-shot (fresh leaf arrays): recording
        # adaptive/output stats would cost one blocking sync per chunk
        # and flood the LRU caches with dead entries
        with stats_recording_disabled():
            self.batch = self._run(plan)
        self.chunks += 1


def _merge_plan_for(spec: AggSpec):
    """The device merge step shared by every chunked tier: re-aggregate
    the union of the running state and one chunk's partials."""
    keys = tuple(E.Col(n) for n in spec.key_names)
    merge_outs = tuple(E.Alias(E.Col(n), n)
                       for n in spec.key_names) + tuple(spec.merges)

    def merge_plan(state_rel, partial):
        if state_rel is None:
            return L.Aggregate(keys, merge_outs, partial)
        aligned = L.Project(
            tuple(E.Col(n) for n in state_rel.schema.names), partial)
        return L.Aggregate(keys, merge_outs,
                           L.Union(state_rel, aligned))

    return merge_plan


def _int_key_values(batch, col: str) -> Optional[np.ndarray]:
    """Join-key column of a device batch as host int64 values (valid
    rows only); None for non-integral keys."""
    from spark_tpu import types as T

    try:
        f = batch.schema.field(col)
    except Exception:
        return None
    dt = f.dtype
    if not (getattr(dt, "is_integral", False)
            or isinstance(dt, (T.DateType, T.DecimalType))):
        return None
    cd = batch.column(col)
    data = np.asarray(cd.data).astype(np.int64)
    mask = np.asarray(batch.data.row_mask)
    if cd.validity is not None:
        mask = mask & np.asarray(cd.validity)
    return data[mask]


class _HostKeyFilter:
    """Host-side membership filter over one big-side key column: exact
    sorted-array semi filter up to ``semiFilterExactMax`` distinct keys,
    Bloom bitset above (same mergeable hash family as sketch.py's device
    Bloom; false positives only cost transfer). Also exposes the key
    range for parquet row-group pruning."""

    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def __init__(self, col: str, values: np.ndarray, exact_max: int):
        self.col = col
        uniq = np.unique(values)  # sorted
        self.lo = int(uniq[0]) if len(uniq) else 0
        self.hi = int(uniq[-1]) if len(uniq) else 0
        self.exact = len(uniq) <= exact_max
        if self.exact:
            self._keys = uniq
        else:
            # ~16 bits/key, two probes -> <1% false positives
            nbits = 1 << int(np.ceil(np.log2(max(len(uniq), 2) * 16)))
            self._nbits = np.uint64(nbits)
            words = np.zeros(nbits // 64, dtype=np.uint64)
            for salt in (np.uint64(1), np.uint64(2)):
                h = (uniq.astype(np.uint64) * self._MIX * salt) \
                    % self._nbits
                np.bitwise_or.at(words, (h // 64).astype(np.int64),
                                 np.uint64(1) << (h % np.uint64(64)))
            self._words = words

    def member(self, vals: np.ndarray) -> np.ndarray:
        vals = vals.astype(np.int64, copy=False)
        if self.exact:
            pos = np.searchsorted(self._keys, vals)
            pos = np.clip(pos, 0, max(len(self._keys) - 1, 0))
            return (self._keys[pos] == vals) if len(self._keys) \
                else np.zeros(len(vals), dtype=bool)
        ok = np.ones(len(vals), dtype=bool)
        for salt in (np.uint64(1), np.uint64(2)):
            h = (vals.astype(np.uint64) * self._MIX * salt) % self._nbits
            bit = (self._words[(h // 64).astype(np.int64)]
                   >> (h % np.uint64(64))) & np.uint64(1)
            ok &= bit.astype(bool)
        return ok

    def range_conjuncts(self, schema) -> List[E.Expression]:
        """min/max pushdown predicates for the parquet scan (row-group
        pruning; exact filtering there would re-hash per row in C++ —
        the membership test stays in numpy)."""
        from spark_tpu import types as T

        f = schema.field(self.col)
        lo: object = self.lo
        hi: object = self.hi
        if isinstance(f.dtype, T.DecimalType):
            return []  # literal would need descaling; range gain is nil
        if isinstance(f.dtype, T.DateType):
            lo = T.days_to_date(self.lo)
            hi = T.days_to_date(self.hi)
        return [E.Cmp(">=", E.Col(self.col), E.Literal(lo)),
                E.Cmp("<=", E.Col(self.col), E.Literal(hi))]


def _chunk_capacity(rows: int, cap_max: int) -> int:
    """Power-of-two capacity bucket in [2^16, cap_max]: at most ~12
    distinct compiled programs across a whole stream, while a heavily
    key-filtered chunk ships proportional to its SURVIVING rows (the
    tunnel to a remote TPU is bandwidth-bound; a fixed capacity padded
    every chunk to the maximum)."""
    cap = 1 << 16
    while cap < rows:
        cap <<= 1
    return min(cap, cap_max) if rows <= cap_max else cap_max


def _progress_logger(tag: str):
    """stderr progress lines when SPARK_TPU_PROGRESS is set — hour-long
    SF100 streams are otherwise a black box from outside. When the
    chunk pipeline's stats are passed, each line also reports the
    achieved decode/transfer-vs-compute overlap so the operator can see
    whether prefetch is actually hiding the tunnel."""
    import os
    import sys
    import time

    if not os.environ.get("SPARK_TPU_PROGRESS"):
        return lambda *_, **__: None
    t0 = time.time()

    def log(chunks: int, rows: int, stats=None) -> None:
        elapsed = time.time() - t0
        extra = ""
        if stats is not None:
            ov_s = stats.overlap_ms() / 1e3
            pct = 100.0 * ov_s / elapsed if elapsed > 0 else 0.0
            extra = f" overlap={ov_s:.1f}s ({pct:.0f}%)"
        print(f"[{tag}] chunk={chunks} rows={rows} "
              f"t={elapsed:.0f}s{extra}", file=sys.stderr, flush=True)

    return log


def _empty_rel(scan: L.UnresolvedScan) -> L.Relation:
    from spark_tpu.columnar.arrow import from_arrow
    from spark_tpu.io.datasource import _pa_schema_from_schema

    return L.Relation(
        from_arrow(_pa_schema_from_schema(scan.schema).empty_table()))


def _splice(root: L.LogicalPlan, mapping: Dict[int, L.LogicalPlan]):
    def repl(p: L.LogicalPlan) -> L.LogicalPlan:
        return mapping.get(id(p), p)

    return root.transform_up(repl)


@dataclasses.dataclass
class _ChunkedAgg:
    """Tiers 1+2: Aggregate over per-row ops / streamable joins around
    ONE over-budget scan."""

    above: List[L.LogicalPlan]
    agg: L.Aggregate
    big: L.UnresolvedScan
    path_joins: List[_PathJoin]

    def execute(self, conf, run_fn):
        from spark_tpu import metrics
        from spark_tpu.columnar.arrow import arrow_to_numpy
        from spark_tpu.columnar.batch import from_numpy, round_capacity
        from spark_tpu.physical.pipeline import ChunkPipeline

        agg, scan = self.agg, self.big
        spec = AggSpec(agg.groupings, agg.aggregates)
        key_aliases = tuple(E.Alias(g, n) for g, n
                            in zip(spec.groupings_exec, spec.key_names))
        chunk_rows = conf.get(CHUNK_ROWS)
        # ONE static capacity for every chunk: a varying capacity means
        # a fresh XLA compile per chunk (~minutes each on TPU)
        fixed_cap = round_capacity(chunk_rows)
        exact_max = conf.get(SEMI_FILTER_EXACT_MAX)
        depth = conf.get(CF.PIPELINE_DEPTH)
        prefetch_budget = conf.get(CF.PREFETCH_BYTES_MAX)
        stats = metrics.PipelineStats()

        # plan-only pre-pass: which path joins COULD yield a host key
        # filter. When none can, the chunk producer starts BEFORE the
        # sidecars materialize (sidecars ship while the first big
        # chunks decode); when one can, the stream waits for the
        # sidecar key sets so the membership filter and min/max
        # row-group pruning stay effective.
        filter_col: Dict[int, str] = {}
        for pj in self.path_joins:
            if exact_max > 0 and pj.can_filter and len(pj.big_keys) == 1:
                col = _resolve_to_scan_col(
                    pj.big_keys[0],
                    pj.join.left if pj.big_on_left else pj.join.right,
                    scan)
                if col is not None:
                    filter_col[id(pj)] = col

        scan_cols = scan.columns
        filters: List[_HostKeyFilter] = []
        counters = {"rows_in": 0, "rows_kept": 0}

        def make_prepare(read_cols):
            drop_extra = (scan_cols is not None
                          and len(read_cols or ()) != len(scan_cols))

            def prepare(tbl):
                counters["rows_in"] += tbl.num_rows
                if filters:
                    with stats.timed("filter"):
                        keep = np.ones(tbl.num_rows, dtype=bool)
                        for kf in filters:
                            vals = _decode_key_np(tbl.column(kf.col))
                            if vals is None:
                                continue
                            keep &= kf.member(vals)
                        if not keep.all():
                            tbl = tbl.filter(keep)
                        if drop_extra:
                            tbl = tbl.select(list(scan_cols))
                if tbl.num_rows == 0:
                    return None
                counters["rows_kept"] += tbl.num_rows
                with stats.timed("decode"):
                    sch, arrs, vlds = arrow_to_numpy(tbl)
                with stats.timed("transfer"):
                    batch = from_numpy(
                        sch, arrs, vlds,
                        capacity=_chunk_capacity(tbl.num_rows, fixed_cap),
                        narrow_transfer=True).block_until_ready()
                return L.Relation(batch)

            return prepare

        def rel_nbytes(rel):
            return rel.batch.device_nbytes()

        pipe = None
        try:
            if depth >= 1 and not filter_col:
                pipe = ChunkPipeline(
                    scan.source.iter_batches(scan_cols,
                                             tuple(scan.filters),
                                             chunk_rows),
                    make_prepare(scan_cols), depth=depth,
                    byte_budget=prefetch_budget, stats=stats,
                    nbytes_of=rel_nbytes, conf=conf)

            # 1. materialize each sidecar ONCE; they stay
            # device-resident
            sidecar_rel: Dict[int, L.LogicalPlan] = {}
            side_log = _progress_logger("sidecar")
            for si, pj in enumerate(self.path_joins):
                side_log(si, 0)
                with stats.timed("sidecar"):
                    batch = run_fn(pj.sidecar)
                sidecar_rel[id(pj.sidecar)] = L.Relation(batch)
                col = filter_col.get(id(pj))
                if col is None:
                    continue
                skey = E.strip_alias(pj.sidecar_keys[0])
                try:
                    with stats.timed("sidecar"):
                        kb = run_fn(L.Project(
                            (E.Alias(skey, "__semi_k"),),
                            L.Relation(batch)))
                    vals = _int_key_values(kb, "__semi_k")
                except Exception:
                    vals = None
                if vals is not None:
                    filters.append(_HostKeyFilter(col, vals, exact_max))
            skeleton = _splice(agg.child, sidecar_rel) \
                if sidecar_rel else agg.child

            if pipe is None:
                # 2. push key ranges into the scan, then stream +
                # filter chunks
                scan_filters = tuple(scan.filters)
                for kf in filters:
                    try:
                        scan_filters = scan_filters \
                            + tuple(kf.range_conjuncts(scan.schema))
                    except Exception:
                        pass
                if filters and scan_cols is not None:
                    # membership columns must be in the streamed
                    # projection
                    need = [kf.col for kf in filters
                            if kf.col not in scan_cols]
                    read_cols = tuple(scan_cols) \
                        + tuple(dict.fromkeys(need))
                else:
                    read_cols = scan_cols
                pipe = ChunkPipeline(
                    scan.source.iter_batches(read_cols, scan_filters,
                                             chunk_rows),
                    make_prepare(read_cols), depth=depth,
                    byte_budget=prefetch_budget, stats=stats,
                    nbytes_of=rel_nbytes, conf=conf)

            state = _MergeState(_merge_plan_for(spec), run_fn)
            progress = _progress_logger("chunked_agg")
            for rel in pipe:
                with stats.timed("compute"):
                    chunk_plan = _splice(skeleton, {id(scan): rel})
                    partial = L.Aggregate(
                        tuple(spec.groupings_exec),
                        key_aliases + tuple(spec.partials), chunk_plan)
                    state.feed(partial)
                progress(state.chunks, counters["rows_in"], stats)
        finally:
            if pipe is not None:
                pipe.close()
        metrics.record(
            "chunked_agg", chunks=state.chunks,
            sidecars=len(sidecar_rel), key_filters=len(filters),
            rows_in=counters["rows_in"],
            rows_kept=counters["rows_kept"],
            groups=0 if state.batch is None
            else state.batch.num_valid_rows(),
            pipeline_depth=depth, **stats.finish())

        if state.batch is None:
            # empty stream: run the aggregate over an EMPTY spliced
            # relation — the original plan would rematerialize the scan
            final0: L.LogicalPlan = L.Aggregate(
                agg.groupings, agg.aggregates,
                _splice(skeleton, {id(scan): _empty_rel(scan)}))
            for node in reversed(self.above):
                final0 = node.with_children((final0,))
            return run_fn(final0)
        final: L.LogicalPlan = L.Project(tuple(spec.outputs),
                                         L.Relation(state.batch))
        for node in reversed(self.above):
            final = node.with_children((final,))
        return run_fn(final)


def _decode_key_np(col) -> Optional[np.ndarray]:
    """Arrow (chunked) column -> int64 numpy for membership testing;
    None when the storage isn't integral (dictionary/strings)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    t = col.type
    if pa.types.is_dictionary(t):
        return None
    if pa.types.is_decimal(t):
        raw = np.frombuffer(col.buffers()[1], dtype=np.int64)
        lo = col.offset * 2
        return raw[lo:lo + 2 * len(col):2].copy()
    try:
        if pa.types.is_date(t) or pa.types.is_timestamp(t):
            col = col.cast(pa.int64())
        vals = pc.fill_null(col, 0).to_numpy(zero_copy_only=False)
        if not np.issubdtype(vals.dtype, np.integer):
            return None
        return vals.astype(np.int64, copy=False)
    except Exception:
        return None


@dataclasses.dataclass
class _GraceHashAgg:
    """Tier 3: Aggregate over Join(per-row(bigA), per-row(bigB)) with
    both scans over budget — grace-hash partitioning into host-RAM
    buckets, then per-bucket device joins feeding the merge state."""

    above: List[L.LogicalPlan]
    agg: L.Aggregate
    join: L.Join
    scan_a: L.UnresolvedScan  # under join.left
    scan_b: L.UnresolvedScan  # under join.right
    key_a: str  # partition column on scan_a
    key_b: str
    est_total: int

    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def execute(self, conf, run_fn):
        from spark_tpu import metrics
        from spark_tpu.columnar.arrow import arrow_to_numpy
        from spark_tpu.columnar.batch import from_numpy
        from spark_tpu.physical.pipeline import ChunkPipeline

        budget = conf.get(MAX_DEVICE_BATCH_BYTES)
        chunk_rows = conf.get(CHUNK_ROWS)
        depth = conf.get(CF.PIPELINE_DEPTH)
        prefetch_budget = conf.get(CF.PREFETCH_BYTES_MAX)
        stats = metrics.PipelineStats()
        nparts = int(min(conf.get(GRACE_PARTITIONS_MAX),
                         max(2, -(-4 * self.est_total // max(budget, 1)))))

        def partition(scan, key_col):
            buckets: List[list] = [[] for _ in range(nparts)]
            for tbl in scan.source.iter_batches(
                    scan.columns, scan.filters, chunk_rows):
                vals = _decode_key_np(tbl.column(key_col))
                if vals is None:
                    raise NotImplementedError(
                        "grace-hash join needs an integral partition key")
                h = ((vals.astype(np.uint64) * self._MIX)
                     >> np.uint64(32)) % np.uint64(nparts)
                h = h.astype(np.int64)
                for p in np.unique(h):
                    buckets[p].append(tbl.filter(h == p))
            return buckets

        with stats.timed("decode"):
            if depth >= 1:
                # both sides' partition passes are pure host work
                # (parquet decode + hash into bucket lists) over
                # disjoint state — run them concurrently
                import concurrent.futures as _cf

                with _cf.ThreadPoolExecutor(
                        2, thread_name_prefix="grace-partition") as pool:
                    fa = pool.submit(partition, self.scan_a, self.key_a)
                    fb = pool.submit(partition, self.scan_b, self.key_b)
                    buckets_a, buckets_b = fa.result(), fb.result()
            else:
                buckets_a = partition(self.scan_a, self.key_a)
                buckets_b = partition(self.scan_b, self.key_b)

        spec = AggSpec(self.agg.groupings, self.agg.aggregates)
        key_aliases = tuple(E.Alias(g, n) for g, n
                            in zip(spec.groupings_exec, spec.key_names))
        state = _MergeState(_merge_plan_for(spec), run_fn)
        import pyarrow as pa

        def concat(parts, scan):
            if not parts:
                # typed empty table so the spliced Relation keeps schema
                from spark_tpu.io.datasource import _pa_schema_from_schema

                return _pa_schema_from_schema(scan.schema).empty_table()
            return pa.concat_tables(parts)

        from spark_tpu.columnar.batch import round_capacity

        # ONE static capacity per side across all buckets (varying
        # capacities would compile a fresh XLA program per bucket)
        cap_a = round_capacity(max(
            [sum(t.num_rows for t in b or ()) for b in buckets_a] or [1]))
        cap_b = round_capacity(max(
            [sum(t.num_rows for t in b or ()) for b in buckets_b] or [1]))
        outer = self.join.how in ("left", "right", "full")
        parts = []
        for p in range(nparts):
            if not buckets_a[p] and not buckets_b[p]:
                continue
            if not outer and (not buckets_a[p] or not buckets_b[p]):
                if self.join.how != "left_anti" or not buckets_a[p]:
                    continue
            parts.append(p)

        def prepare(p):
            with stats.timed("decode"):
                ta = concat(buckets_a[p], self.scan_a)
                tb = concat(buckets_b[p], self.scan_b)
                buckets_a[p] = buckets_b[p] = None  # free host RAM
                sa, aa, va = arrow_to_numpy(ta)
                sb, ab, vb = arrow_to_numpy(tb)
            with stats.timed("transfer"):
                ba = from_numpy(sa, aa, va, capacity=cap_a,
                                narrow_transfer=True).block_until_ready()
                bb = from_numpy(sb, ab, vb, capacity=cap_b,
                                narrow_transfer=True).block_until_ready()
            return {id(self.scan_a): L.Relation(ba),
                    id(self.scan_b): L.Relation(bb)}

        pipe = ChunkPipeline(
            parts, prepare, depth=depth, byte_budget=prefetch_budget,
            stats=stats,
            nbytes_of=lambda m: sum(r.batch.device_nbytes()
                                    for r in m.values()),
            conf=conf)
        progress = _progress_logger("grace_hash_agg")
        try:
            for mapping in pipe:
                with stats.timed("compute"):
                    chunk_plan = _splice(self.agg.child, mapping)
                    partial = L.Aggregate(
                        tuple(spec.groupings_exec),
                        key_aliases + tuple(spec.partials), chunk_plan)
                    state.feed(partial)
                progress(state.chunks, 0, stats)
        finally:
            pipe.close()
        metrics.record("grace_hash_agg", partitions=nparts,
                       chunks=state.chunks, pipeline_depth=depth,
                       **stats.finish())

        if state.batch is None:
            final0: L.LogicalPlan = L.Aggregate(
                self.agg.groupings, self.agg.aggregates,
                _splice(self.agg.child,
                        {id(self.scan_a): _empty_rel(self.scan_a),
                         id(self.scan_b): _empty_rel(self.scan_b)}))
            for node in reversed(self.above):
                final0 = node.with_children((final0,))
            return run_fn(final0)
        final: L.LogicalPlan = L.Project(tuple(spec.outputs),
                                         L.Relation(state.batch))
        for node in reversed(self.above):
            final = node.with_children((final,))
        return run_fn(final)


# recursive-repartition bounds: an overflowing bucket splits 4 ways per
# level under a fresh hash salt; recursion stops once a bucket fits the
# device budget, shrinks below the row floor (device can chunk it), has
# a single hot key (splitting cannot help), or hits the depth cap.
_RECURSE_FANOUT = 4
_RECURSE_MAX_DEPTH = 8
_RECURSE_MIN_ROWS = 4096

#: HLL registers for the host-side distinct sketch maintained during
#: the hybrid join's partition pass (same estimator as the adaptive
#: aggregation sketch — one shared implementation in spark_tpu/sketch.py)
_HLL_REGISTERS = 256


def _session_memory_manager():
    """The active session's UnifiedMemoryManager, or None standalone
    (e.g. a bare MeshExecutor in tests) — the hybrid join then stages
    fully resident, exactly like the static grace join."""
    try:
        from spark_tpu.api.session import SparkSession

        sess = SparkSession._active
        return getattr(sess, "memory_manager", None)
    except Exception:
        return None


class _HybridSpillAbort(Exception):
    """A ``join.spill`` seam exhausted its retries or hit corruption:
    the hybrid pass discards its partial state and falls back ONE rung
    to the static grace-hash join, recomputed from source."""

    def __init__(self, op: str, kind: str):
        super().__init__(f"hybrid hash join {op} aborted ({kind})")
        self.op = op
        self.kind = kind


def _spill_seam(conf, op: str, attempts: int, fn):
    """Run one spill-side operation behind the ``join.spill`` fault
    point. transient/hang faults retry up to ``attempts`` times;
    corruption or retry exhaustion aborts the hybrid pass (the caller
    falls back to the static grace-hash join); OOM propagates so the
    degradation ladder stays the LAST resort. The injection fires
    BEFORE ``fn`` touches any file, so a retried injected fault never
    sees partial writes; real mid-write I/O errors are not transient
    and abort to the recompute-from-source fallback."""
    from spark_tpu import deadline, faults, metrics, recovery, trace

    attempts = max(0, int(attempts))
    last: Optional[BaseException] = None
    for attempt in range(attempts + 1):
        try:
            with trace.span("join.spill", op=op, attempt=attempt):
                faults.inject("join.spill", conf)
                return fn()
        except deadline.DeadlineExceeded:
            # not a spill failure: the query's window closed, so the
            # abort-to-grace-hash fallback would just burn more time
            raise
        except Exception as e:
            if recovery.is_oom(e):
                raise
            if recovery.is_transient(e) and attempt < attempts:
                deadline.check(f"join.spill.{op}")
                if not recovery.retry_allowed("join.spill"):
                    raise recovery.RetryBudgetExhausted(
                        "join.spill", recovery.current_budget()) from e
                last = e
                metrics.note_join("spill_retries")
                metrics.record("stage_retry", label=f"join.spill.{op}",
                               attempt=attempt, error=repr(e))
                continue
            raise _HybridSpillAbort(
                op, getattr(e, "kind", type(e).__name__)) from e
    raise _HybridSpillAbort(
        op, getattr(last, "kind", "exhausted")) from last


class _HybridPart:
    """One side of one hybrid-join partition: resident arrow tables
    while it fits the grant, a write-through host spill file after
    demotion."""

    __slots__ = ("tables", "rows", "nbytes", "path", "sink", "writer",
                 "spilled")

    def __init__(self):
        self.tables: Optional[list] = []
        self.rows = 0
        self.nbytes = 0
        self.path: Optional[str] = None
        self.sink = None
        self.writer = None
        self.spilled = False


@dataclasses.dataclass
class _HybridHashJoinAgg:
    """Tier 3, dynamic: grant-driven hybrid hash join.

    Where the static ``_GraceHashAgg`` stages BOTH sides fully in host
    RAM and hopes, this tier executes the same join as a planned single
    pass at ANY memory level:

    1. **Grant.** Before touching data it requests an execution grant
       from the session's UnifiedMemoryManager, sized by the MEASURED
       build bytes of a prior run of the same plan shape
       (admission.seeded_build_bytes — the AQE feedback loop) or the
       planner estimate. The grant is what the staging pass may keep
       resident; a 0-byte grant means everything spills (the join still
       completes in one planned pass — it never blocks on storage).
    2. **Partition pass.** Both scans stream once, hash-bucketed with
       the grace hash. Partitions accumulate resident until the grant
       is exhausted; then the join first tries to GROW the grant from
       the manager's free span (growWhenIdle — never evicting storage)
       and otherwise demotes the largest resident partition to a
       write-through arrow-IPC spill file. A host-side HLL distinct
       sketch of the join keys is maintained during the pass.
    3. **Join pass.** Partitions execute in index order (resident
       directly, spilled read back), feeding the same device merge
       state as grace — results are byte-identical. A bucket pair whose
       working set would blow the device budget is recursively
       REPARTITIONED with a per-level hash salt instead of
       shipped-and-hoped, so the OOM ladder becomes the last resort
       rather than the sizing mechanism.

    Every spill-file write, read-back, and recursive repartition is a
    ``join.spill`` fault seam with bounded retries; unrecoverable seam
    failures fall back one rung to the static grace join recomputed
    from source. Observed staging bytes are fed back to admission, so
    the NEXT run's grant is measured, not estimated."""

    above: List[L.LogicalPlan]
    agg: L.Aggregate
    join: L.Join
    scan_a: L.UnresolvedScan
    scan_b: L.UnresolvedScan
    key_a: str
    key_b: str
    est_total: int

    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def execute(self, conf, run_fn):
        from spark_tpu import metrics

        try:
            return self._execute_hybrid(conf, run_fn)
        except _HybridSpillAbort as e:
            metrics.note_join("fallbacks")
            metrics.record("fault_recovered", point="join.spill",
                           fault=e.kind, op=e.op,
                           action="grace_fallback")
            return _GraceHashAgg(
                self.above, self.agg, self.join, self.scan_a,
                self.scan_b, self.key_a, self.key_b,
                self.est_total).execute(conf, run_fn)

    def _execute_hybrid(self, conf, run_fn):
        import os
        import shutil
        import tempfile

        import pyarrow as pa

        from spark_tpu import metrics, trace
        from spark_tpu.columnar.arrow import arrow_to_numpy
        from spark_tpu.columnar.batch import from_numpy, round_capacity
        from spark_tpu.io.datasource import _pa_schema_from_schema
        from spark_tpu.physical.pipeline import ChunkPipeline
        from spark_tpu.sketch import HyperLogLog
        from spark_tpu.scheduler import admission

        budget = conf.get(MAX_DEVICE_BATCH_BYTES)
        chunk_rows = conf.get(CHUNK_ROWS)
        depth = conf.get(CF.PIPELINE_DEPTH)
        prefetch_budget = conf.get(CF.PREFETCH_BYTES_MAX)
        retries = int(conf.get(JOIN_HYBRID_SPILL_RETRIES))
        grow_idle = bool(conf.get(JOIN_HYBRID_GROW_WHEN_IDLE))
        stats = metrics.PipelineStats()
        nparts = int(min(conf.get(JOIN_HYBRID_PARTITIONS_MAX),
                         max(2, -(-4 * self.est_total
                                  // max(budget, 1)))))

        manager = _session_memory_manager()
        charge = 0
        resident_cap: Optional[int] = None  # None = ungoverned
        if manager is not None:
            request = admission.seeded_build_bytes(self.agg,
                                                   self.est_total)
            charge = manager.acquire_execution(request)
            resident_cap = charge
            metrics.note_join("grants")
            metrics.note_join("grant_bytes", charge)
            if charge == 0:
                metrics.note_join("zero_grants")
        granted0 = charge

        parts_a = [_HybridPart() for _ in range(nparts)]
        parts_b = [_HybridPart() for _ in range(nparts)]
        hll = HyperLogLog(_HLL_REGISTERS)
        counters = {"resident": 0, "staged": 0, "spill_bytes": 0,
                    "max_depth": 0}
        tmpdir: Optional[str] = None

        def spill_write(side, p, part, tables):
            nbytes = sum(t.nbytes for t in tables)

            def _do():
                nonlocal tmpdir
                if part.writer is None:
                    if tmpdir is None:
                        tmpdir = tempfile.mkdtemp(
                            prefix="spark-tpu-hybrid-join-")
                    part.path = os.path.join(tmpdir,
                                             f"{side}{p}.arrows")
                    part.sink = pa.OSFile(part.path, "wb")
                    part.writer = pa.ipc.new_stream(part.sink,
                                                    tables[0].schema)
                for t in tables:
                    part.writer.write_table(t)

            _spill_seam(conf, "write", retries, _do)
            metrics.note_join("spill_writes")
            metrics.note_join("spill_bytes", nbytes)
            counters["spill_bytes"] += nbytes

        def demote_one() -> int:
            """Spill the largest resident partition wholesale; returns
            the resident bytes freed (0 when nothing is demotable)."""
            best = None
            for side, plist in (("a", parts_a), ("b", parts_b)):
                for p, part in enumerate(plist):
                    if part.tables and (best is None
                                        or part.nbytes > best[2].nbytes):
                        best = (side, p, part)
            if best is None:
                return 0
            side, p, part = best
            tables, freed = part.tables, part.nbytes
            part.tables, part.nbytes = [], 0
            if not part.spilled:
                part.spilled = True
                metrics.note_join("spilled_partitions")
            spill_write(side, p, part, tables)
            return freed

        def partition_side(side, scan, key_col, plist):
            nonlocal charge, resident_cap
            for tbl in scan.source.iter_batches(
                    scan.columns, scan.filters, chunk_rows):
                vals = _decode_key_np(tbl.column(key_col))
                if vals is None:
                    raise NotImplementedError(
                        "hybrid hash join needs an integral "
                        "partition key")
                hll.update(vals)
                h = ((vals.astype(np.uint64) * self._MIX)
                     >> np.uint64(32)) % np.uint64(nparts)
                h = h.astype(np.int64)
                for p in np.unique(h):
                    part = plist[p]
                    sub = tbl.filter(h == p)
                    part.rows += sub.num_rows
                    counters["staged"] += sub.nbytes
                    if part.spilled:  # write-through: stays spilled
                        spill_write(side, p, part, [sub])
                        continue
                    part.tables.append(sub)
                    part.nbytes += sub.nbytes
                    counters["resident"] += sub.nbytes
                # planned spilling: keep staged bytes inside the grant
                # — grow from the manager's free span when allowed,
                # demote the largest partition otherwise
                while resident_cap is not None \
                        and counters["resident"] > resident_cap:
                    need = counters["resident"] - resident_cap
                    if grow_idle and manager is not None:
                        got = manager.try_grow(need)
                        if got:
                            charge += got
                            resident_cap += got
                            metrics.note_join("grows")
                            continue
                    freed = demote_one()
                    if freed == 0:
                        break  # nothing demotable: run over-grant
                    counters["resident"] -= freed

        def close_writers():
            for plist in (parts_a, parts_b):
                for part in plist:
                    if part.writer is not None:
                        part.writer.close()
                        part.sink.close()
                        part.writer = part.sink = None

        def read_back(part) -> "pa.Table":
            def _do():
                with pa.OSFile(part.path, "rb") as f:
                    return pa.ipc.open_stream(f).read_all()

            tbl = _spill_seam(conf, "read", retries, _do)
            metrics.note_join("spill_reads")
            return tbl

        def materialize(part, scan) -> "pa.Table":
            if part.spilled:
                return read_back(part)
            if not part.tables:
                return _pa_schema_from_schema(scan.schema).empty_table()
            return pa.concat_tables(part.tables)

        spec = AggSpec(self.agg.groupings, self.agg.aggregates)
        key_aliases = tuple(E.Alias(g, n) for g, n
                            in zip(spec.groupings_exec, spec.key_names))
        state = _MergeState(_merge_plan_for(spec), run_fn)
        outer = self.join.how in ("left", "right", "full")

        def keep_pair(has_a: bool, has_b: bool) -> bool:
            if not has_a and not has_b:
                return False
            if not outer and (not has_a or not has_b):
                return self.join.how == "left_anti" and has_a
            return True

        try:
            with trace.span("join.partition", partitions=nparts,
                            granted=granted0):
                # sequential sides (grace runs them concurrently):
                # spill/grow decisions against the shared grant stay
                # deterministic, so spill counts are reproducible
                partition_side("a", self.scan_a, self.key_a, parts_a)
                partition_side("b", self.scan_b, self.key_b, parts_b)
                close_writers()

            # ONE power-of-two capacity ladder per side: top-level caps
            # from the largest bucket, sub-buckets reuse the
            # _chunk_capacity buckets below it (bounded program count)
            cap_a = round_capacity(
                max([p.rows for p in parts_a] + [1]))
            cap_b = round_capacity(
                max([p.rows for p in parts_b] + [1]))
            parts = [p for p in range(nparts)
                     if keep_pair(parts_a[p].rows > 0,
                                  parts_b[p].rows > 0)]

            def to_device(ta, tb):
                with stats.timed("decode"):
                    sa, aa, va = arrow_to_numpy(ta)
                    sb, ab, vb = arrow_to_numpy(tb)
                with stats.timed("transfer"):
                    ba = from_numpy(
                        sa, aa, va,
                        capacity=_chunk_capacity(
                            max(ta.num_rows, 1), cap_a),
                        narrow_transfer=True).block_until_ready()
                    bb = from_numpy(
                        sb, ab, vb,
                        capacity=_chunk_capacity(
                            max(tb.num_rows, 1), cap_b),
                        narrow_transfer=True).block_until_ready()
                return {id(self.scan_a): L.Relation(ba),
                        id(self.scan_b): L.Relation(bb)}

            def split_bucket(ta, tb, level, out):
                pair = ta.nbytes + tb.nbytes
                if (4 * pair <= budget
                        or level >= _RECURSE_MAX_DEPTH
                        or max(ta.num_rows,
                               tb.num_rows) <= _RECURSE_MIN_ROWS):
                    out.append(to_device(ta, tb))
                    return
                ka = _decode_key_np(ta.column(self.key_a)) \
                    if ta.num_rows else None
                if ka is not None and len(np.unique(ka)) <= 1:
                    # single hot key: splitting cannot help; ship it
                    out.append(to_device(ta, tb))
                    return

                def _do():
                    salt = np.uint64(2 * level + 3)

                    def rehash(tbl, col):
                        if tbl.num_rows == 0:
                            return [tbl] * _RECURSE_FANOUT
                        vals = _decode_key_np(tbl.column(col))
                        h = ((vals.astype(np.uint64) * self._MIX
                              * salt) >> np.uint64(32)) \
                            % np.uint64(_RECURSE_FANOUT)
                        h = h.astype(np.int64)
                        return [tbl.filter(h == i)
                                for i in range(_RECURSE_FANOUT)]

                    return (rehash(ta, self.key_a),
                            rehash(tb, self.key_b))

                subs_a, subs_b = _spill_seam(conf, "repartition",
                                             retries, _do)
                metrics.note_join("recursive_repartitions")
                counters["max_depth"] = max(counters["max_depth"],
                                            level + 1)
                for i in range(_RECURSE_FANOUT):
                    if keep_pair(subs_a[i].num_rows > 0,
                                 subs_b[i].num_rows > 0):
                        split_bucket(subs_a[i], subs_b[i],
                                     level + 1, out)

            def prepare(p):
                ta = materialize(parts_a[p], self.scan_a)
                tb = materialize(parts_b[p], self.scan_b)
                parts_a[p].tables = parts_b[p].tables = None  # free RAM
                out: list = []
                split_bucket(ta, tb, 0, out)
                return out or None

            pipe = ChunkPipeline(
                parts, prepare, depth=depth,
                byte_budget=prefetch_budget, stats=stats,
                nbytes_of=lambda ms: sum(
                    r.batch.device_nbytes()
                    for m in ms for r in m.values()),
                conf=conf)
            progress = _progress_logger("hybrid_hash_agg")
            try:
                for mappings in pipe:
                    for mapping in mappings:
                        with stats.timed("compute"):
                            chunk_plan = _splice(self.agg.child,
                                                 mapping)
                            partial = L.Aggregate(
                                tuple(spec.groupings_exec),
                                key_aliases + tuple(spec.partials),
                                chunk_plan)
                            state.feed(partial)
                    progress(state.chunks, 0, stats)
            finally:
                pipe.close()
        finally:
            close_writers()
            if tmpdir is not None:
                shutil.rmtree(tmpdir, ignore_errors=True)
            if manager is not None:
                manager.release_execution(charge)

        spilled = sum(1 for plist in (parts_a, parts_b)
                      for pt in plist if pt.spilled)
        metrics.record(
            "hybrid_hash_agg", partitions=nparts,
            spilled_parts=spilled,
            resident_parts=2 * nparts - spilled,
            granted_bytes=granted0, grown_bytes=charge - granted0,
            staged_bytes=counters["staged"],
            spill_bytes=counters["spill_bytes"],
            depth=counters["max_depth"],
            ndv=int(hll.estimate()),
            chunks=state.chunks, pipeline_depth=depth,
            **stats.finish())
        # AQE feedback: the NEXT run of this plan shape requests a
        # grant sized by what staging actually took
        admission.note_measured_bytes(self.agg, counters["staged"])

        if state.batch is None:
            final0: L.LogicalPlan = L.Aggregate(
                self.agg.groupings, self.agg.aggregates,
                _splice(self.agg.child,
                        {id(self.scan_a): _empty_rel(self.scan_a),
                         id(self.scan_b): _empty_rel(self.scan_b)}))
            for node in reversed(self.above):
                final0 = node.with_children((final0,))
            return run_fn(final0)
        final: L.LogicalPlan = L.Project(tuple(spec.outputs),
                                         L.Relation(state.batch))
        for node in reversed(self.above):
            final = node.with_children((final,))
        return run_fn(final)


@dataclasses.dataclass
class _ChunkedTopK:
    """Streamed top-k: Limit(Sort(per-row(big scan))) keeps a running
    device top-(n+offset), merged per chunk (ExternalSorter's
    TakeOrderedAndProjectExec shape)."""

    above: List[L.LogicalPlan]  # Projects above the Limit
    limit: L.Limit
    sort: L.Sort
    chain_root: L.LogicalPlan  # sort.child (per-row ops over the scan)
    big: L.UnresolvedScan

    def execute(self, conf, run_fn):
        from spark_tpu import metrics
        from spark_tpu.columnar.arrow import arrow_to_numpy
        from spark_tpu.columnar.batch import from_numpy, round_capacity
        from spark_tpu.physical.pipeline import ChunkPipeline

        chunk_rows = conf.get(CHUNK_ROWS)
        depth = conf.get(CF.PIPELINE_DEPTH)
        prefetch_budget = conf.get(CF.PREFETCH_BYTES_MAX)
        stats = metrics.PipelineStats()
        k = self.limit.n + self.limit.offset

        def merge_plan(state_rel, chunk_plan):
            child = chunk_plan if state_rel is None else L.Union(
                state_rel,
                L.Project(tuple(E.Col(n)
                                for n in state_rel.schema.names),
                          chunk_plan))
            return L.Limit(k, L.Sort(self.sort.orders, child))

        fixed_cap = round_capacity(chunk_rows)
        state = _MergeState(merge_plan, run_fn)

        def prepare(tbl):
            if tbl.num_rows == 0:
                return None
            with stats.timed("decode"):
                sch, arrs, vlds = arrow_to_numpy(tbl)
            with stats.timed("transfer"):
                batch = from_numpy(
                    sch, arrs, vlds,
                    capacity=_chunk_capacity(tbl.num_rows, fixed_cap),
                    narrow_transfer=True).block_until_ready()
            return L.Relation(batch)

        pipe = ChunkPipeline(
            self.big.source.iter_batches(self.big.columns,
                                         self.big.filters, chunk_rows),
            prepare, depth=depth, byte_budget=prefetch_budget,
            stats=stats,
            nbytes_of=lambda rel: rel.batch.device_nbytes(),
            conf=conf)
        progress = _progress_logger("chunked_topk")
        try:
            for rel in pipe:
                with stats.timed("compute"):
                    chunk_plan = _splice(self.chain_root,
                                         {id(self.big): rel})
                    state.feed(chunk_plan)
                progress(state.chunks, 0, stats)
        finally:
            pipe.close()
        metrics.record("chunked_topk", chunks=state.chunks, k=k,
                       pipeline_depth=depth, **stats.finish())

        if state.batch is None:
            base: L.LogicalPlan = L.Limit(
                self.limit.n,
                L.Sort(self.sort.orders,
                       _splice(self.chain_root,
                               {id(self.big): _empty_rel(self.big)})),
                offset=self.limit.offset)
        else:
            base = L.Limit(self.limit.n,
                           L.Sort(self.sort.orders,
                                  L.Relation(state.batch)),
                           offset=self.limit.offset)
        for node in reversed(self.above):
            base = node.with_children((base,))
        return run_fn(base)


def find_chunkable(plan: L.LogicalPlan, conf):
    """Detect an out-of-HBM-executable shape around over-budget scans;
    returns an executable tier object (with .execute(conf, run_fn)) or
    None to run the plan resident."""
    budget = conf.get(MAX_DEVICE_BATCH_BYTES)
    above, node = _peel_above(plan)

    if isinstance(node, L.Aggregate):
        return _find_agg(above, node, budget, conf)

    # top-k tier: Project* (Limit (Sort (per-row (big scan))))
    above2: List[L.LogicalPlan] = []
    n2 = plan
    while isinstance(n2, L.Project):
        above2.append(n2)
        n2 = n2.children()[0]
    if not isinstance(n2, L.Limit):
        return None
    lim = n2
    if not isinstance(lim.child, L.Sort):
        return None
    sort = lim.child
    node = sort.child
    chain = node
    while isinstance(node, (L.Filter, L.Project, L.SubqueryAlias)):
        node = node.children()[0]
    if not isinstance(node, L.UnresolvedScan):
        return None
    est = _est_scan(node)
    if est is None or est <= budget:
        return None
    if lim.n + lim.offset > conf.get(CHUNK_ROWS):
        return None  # running state would itself exceed a chunk
    return _ChunkedTopK(above2, lim, sort, chain, node)


def _find_agg(above, agg: L.Aggregate, budget: int, conf=None):
    # cheap structural pre-check via the shared legality rule set
    # (analysis/legality.py) before paying for full AggSpec planning;
    # AggSpec itself enforces the same verdicts
    from spark_tpu.analysis import legality

    if not legality.accumulators_verdict(agg.aggregates):
        return None  # non-mergeable aggregate: execute directly
    try:
        AggSpec(agg.groupings, agg.aggregates)
    except NotImplementedError:
        return None
    scans = L.collect_nodes(agg.child, L.UnresolvedScan)
    ests = []
    for s in scans:
        e = _est_scan(s)
        if e is None:
            return None
        ests.append(e)
    big = [(s, e) for s, e in zip(scans, ests) if e > budget]
    if not big:
        return None

    if len(big) == 1:
        scan = big[0][0]
        path = _stream_path(agg.child, scan)
        if path is not None:
            # every sidecar must itself fit the device budget
            ok = True
            for pj in path:
                side_est = sum(
                    _est_scan(s) or (budget + 1)
                    for s in L.collect_nodes(pj.sidecar,
                                             L.UnresolvedScan))
                if side_est > budget:
                    ok = False
                    break
            if ok:
                return _ChunkedAgg(above, agg, scan, path)

    if len(big) == 2:
        gh = _find_grace(above, agg, big[0][0], big[1][0],
                         big[0][1] + big[1][1], conf)
        if gh is not None:
            return gh
    return None


def _find_grace(above, agg: L.Aggregate, sa: L.UnresolvedScan,
                sb: L.UnresolvedScan, est_total: int, conf=None):
    """Shape check for tier 3: one join under the aggregate separates
    the two big scans, with only per-row ops between."""
    # find the join whose sides split {sa, sb}
    joins = [j for j in L.collect_nodes(agg.child, L.Join)
             if _contains(j.left, sa) != _contains(j.left, sb)]
    if len(joins) != 1:
        return None
    join = joins[0]
    if _contains(join.left, sb):
        sa, sb = sb, sa
    # per-row only between agg and the join, and join and each scan
    node = agg.child
    while node is not join:
        if not isinstance(node, (L.Filter, L.Project, L.SubqueryAlias)):
            return None
        node = node.children()[0]

    def per_row_to(root, target):
        n = root
        while n is not target:
            if not isinstance(n, (L.Filter, L.Project, L.SubqueryAlias)):
                return False
            n = n.children()[0]
        return True

    if not per_row_to(join.left, sa) or not per_row_to(join.right, sb):
        return None
    if len(join.left_keys) != 1 or join.how == "cross":
        return None
    ka = _resolve_to_scan_col(join.left_keys[0], join.left, sa)
    kb = _resolve_to_scan_col(join.right_keys[0], join.right, sb)
    if ka is None or kb is None:
        return None
    from spark_tpu import types as T

    for scan, key in ((sa, ka), (sb, kb)):
        dt = scan.schema.field(key).dtype
        if not (getattr(dt, "is_integral", False)
                or isinstance(dt, (T.DateType, T.DecimalType))):
            return None
    hybrid = bool(conf.get(JOIN_HYBRID_ENABLED)) if conf is not None \
        else bool(JOIN_HYBRID_ENABLED.default)
    cls = _HybridHashJoinAgg if hybrid else _GraceHashAgg
    return cls(above, agg, join, sa, sb, ka, kb, est_total)


def execute_chunked(found, conf, run_fn):
    """Execute a chunkable plan (``found`` from find_chunkable);
    ``run_fn(logical_plan) -> Batch`` is the engine (single-device or
    mesh). Returns the final Batch."""
    return found.execute(conf, run_fn)
